#!/bin/bash
# Poll the axon TPU tunnel; whenever it is up, run the INCREMENTAL sweep
# (tools/measure_tpu.py — skips configs already captured, exits 1 on a
# mid-sweep tunnel drop).  Loops until every config is captured on TPU.
# Status lines -> tools/tpu_watch.status ; sweep output appends to
# TPU_SWEEP_r05.log ; per-config results -> TPU_SWEEP_STATE.json
REPO="$(cd "$(dirname "$0")/.." && pwd)"
STATUS="$REPO/tools/tpu_watch.status"
SWEEP="$REPO/TPU_SWEEP_r05.log"
LOCK="$REPO/tools/tpu_watch.lock"

exec 9>"$LOCK"
flock -n 9 || { echo "another watcher is running" >&2; exit 0; }

# Round-3 postmortem: a stale sweep from a previous window overwrote the
# state file and dropped a banked row.  That overwrite is now impossible
# (per-row flock read-merge-write in measure_tpu.py + a process-lifetime
# sweep lock that makes a second concurrent sweep abort), so no pkill —
# killing by pattern would also take down the driver's own end-of-round
# bench children or a legitimate manual sweep mid-bank.

while true; do
  ts=$(date -u +%H:%M:%S)
  if python "$REPO/tools/measure_tpu.py" --probe >/dev/null 2>&1; then
    echo "$ts TUNNEL UP - incremental sweep" >> "$STATUS"
    # 18000s > worst-case sum of inner timeouts (~15900s), so a sweep is
    # never SIGTERMed mid-config (which would orphan the inner bench
    # process on the serialized tunnel)
    cd "$REPO" && timeout 18000 python tools/measure_tpu.py >> "$SWEEP" 2>&1
    rc=$?
    echo "$(date -u +%H:%M:%S) sweep pass exit=$rc" >> "$STATUS"
    [ "$rc" -eq 0 ] && { echo "ALL CAPTURED" >> "$STATUS"; exit 0; }
  else
    echo "$ts tunnel down" >> "$STATUS"
  fi
  # a down-probe already burns its 150 s timeout, so the short sleep
  # gives a ~3.5 min cycle — tunnel windows shorter than the old ~10 min
  # cycle were being missed entirely
  sleep 60
done
