#!/bin/bash
# Poll the axon TPU tunnel; the moment backend init succeeds, run the
# full measurement sweep (tools/measure_tpu.py) once and exit.
# Status lines -> tools/tpu_watch.status ; sweep output -> TPU_SWEEP_r03.log
REPO="$(cd "$(dirname "$0")/.." && pwd)"
STATUS="$REPO/tools/tpu_watch.status"
SWEEP="$REPO/TPU_SWEEP_r03.log"

while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 150 env JAX_PLATFORMS=axon python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != 'cpu'
x = jnp.ones((128, 128))
(x @ x).block_until_ready()
" >/dev/null 2>&1; then
    echo "$ts TUNNEL UP - starting sweep" >> "$STATUS"
    # worst case: 7 configs x 1800s each + the word2vec A/B
    cd "$REPO" && timeout 16200 python tools/measure_tpu.py > "$SWEEP" 2>&1
    rc=$?
    echo "$(date -u +%H:%M:%S) sweep done exit=$rc -> $SWEEP" >> "$STATUS"
    [ "$rc" -eq 0 ] && exit 0
    # truncated/failed sweep: keep watching and try again
  else
    echo "$ts tunnel down" >> "$STATUS"
  fi
  sleep 420
done
