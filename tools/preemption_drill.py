"""Preemption-drill smoke gate (tools/ci.sh).

Machine-checks the PR 8 preemption contract end to end, with a REAL
signal against a REAL process:

1. spawn a subprocess training a small net through ``ResilientFit``
   (async snapshots, PreemptionGuard installed — the default);
2. once the child reports training steps, deliver SIGTERM;
3. the child must write a final committed snapshot at the next step
   boundary and exit 0 (clean preemption, not a crash);
4. this process then resumes from the child's checkpoint directory
   with ``ResilienceConfig(resume=True)`` and must run to completion
   from the preempted step.

Phase 2 (multi-host, skip-aware): the SAME drill across a REAL
2-process ``jax.distributed`` cluster — SIGTERM delivered to ONE
process must drain BOTH at the same step boundary (the cluster-wide
flag OR in ``ResilientFit``) and commit ONE cluster-consistent final
snapshot; both processes exit 0 with ``preempted=True``.  Skips with a
note (not a failure) where 2-process bring-up is unavailable.

Exits non-zero on any violation.  Seconds on CPU.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {repo!r})
    import numpy as np
    import jax.numpy as jnp
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)

    conf = (NeuralNetConfiguration.builder()
            .n_in(8).lr(0.05).num_iterations(1).activation("tanh")
            .list(2).hidden_layer_sizes(16)
            .override(1, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True).build())
    rng = np.random.RandomState(0)
    batches = [DataSet(jnp.asarray(rng.randn(32, 8).astype(np.float32)),
                       jnp.asarray(np.eye(3, dtype=np.float32)[
                           rng.randint(0, 3, 32)]))
               for _ in range(8)]
    net = MultiLayerNetwork(conf).init(seed=1)

    class Beacon:
        def iteration_done(self, model, it, score):
            print("DRILL_STEP", it, flush=True)
    net.set_listeners([Beacon()])

    driver = ResilientFit(net, ResilienceConfig(
        checkpoint_dir={ckdir!r}, checkpoint_every=4))
    driver.fit(batches, num_epochs=200, seed=3)
    print("DRILL_EXIT preempted=%s step=%s" % (
        driver.preempted, driver.manager.latest_step()), flush=True)
""")


def main() -> int:
    import queue
    import threading

    with tempfile.TemporaryDirectory() as d:
        ckdir = os.path.join(d, "ckpts")
        # stderr goes to a FILE: a PIPE nobody drains while we wait on
        # stdout can fill and deadlock a chatty/warning-heavy child
        err_path = os.path.join(d, "worker.stderr")
        with open(err_path, "w") as err_f:
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 WORKER.format(repo=REPO, ckdir=ckdir)],
                stdout=subprocess.PIPE, stderr=err_f, text=True)

        # wait until the child is demonstrably mid-training — stdout is
        # read on a helper thread so the deadline is REAL (a blocking
        # readline would only check the clock after a line arrives,
        # i.e. never, if the child hangs before its first print)
        lines: "queue.Queue" = queue.Queue()
        threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],
            daemon=True).start()
        deadline = time.time() + 120
        seen_step = False
        while time.time() < deadline:
            try:
                if lines.get(timeout=1).startswith("DRILL_STEP"):
                    seen_step = True
                    break
            except queue.Empty:
                if proc.poll() is not None:
                    break
        if not seen_step:
            proc.kill()
            proc.wait(timeout=30)
            print("[preemption-drill] FAIL: worker produced no steps:\n"
                  + open(err_path).read()[-2000:])
            return 1
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
        out_rest: list = []
        while True:
            try:
                out_rest.append(lines.get(timeout=1))
            except queue.Empty:
                break
        out = "".join(out_rest)
        if proc.returncode != 0:
            print(f"[preemption-drill] FAIL: worker exit code "
                  f"{proc.returncode} after SIGTERM (wanted clean 0):\n"
                  + open(err_path).read()[-2000:])
            return 1
        if "preempted=True" not in out:
            print("[preemption-drill] FAIL: worker finished without "
                  "reporting a preemption stop:\n" + out[-2000:])
            return 1

        # the final snapshot must be COMMITTED (manifest verifies) and
        # resumable by a fresh process (this one)
        from deeplearning4j_tpu.runtime.checkpoint import CheckpointManager
        from deeplearning4j_tpu.runtime.resilience import (
            ResilienceConfig, ResilientFit)
        import numpy as np
        import jax.numpy as jnp
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.conf import (LayerKind,
                                                NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        mgr = CheckpointManager(ckdir)
        latest = mgr.latest_step()
        if latest is None:
            print("[preemption-drill] FAIL: no checkpoint committed")
            return 1
        mgr.verify(latest)

        conf = (NeuralNetConfiguration.builder()
                .n_in(8).lr(0.05).num_iterations(1).activation("tanh")
                .list(2).hidden_layer_sizes(16)
                .override(1, kind=LayerKind.OUTPUT, n_out=3,
                          activation="softmax", loss_function="mcxent")
                .pretrain(False).backward(True).build())
        rng = np.random.RandomState(0)
        batches = [DataSet(jnp.asarray(rng.randn(32, 8)
                                       .astype(np.float32)),
                           jnp.asarray(np.eye(3, dtype=np.float32)[
                               rng.randint(0, 3, 32)]))
                   for _ in range(8)]
        net = MultiLayerNetwork(conf).init(seed=1)
        driver = ResilientFit(net, ResilienceConfig(
            checkpoint_dir=ckdir, resume=True, checkpoint_every=4,
            max_steps=8))           # bounded resume slice: fast smoke
        driver.fit(batches, num_epochs=200, seed=3)
        if driver.steps_run < 1:
            print("[preemption-drill] FAIL: resume ran no steps")
            return 1
        print(f"[preemption-drill] ok: SIGTERM at a live step -> clean "
              f"exit 0, committed snapshot at step {latest}, fresh "
              f"process resumed {driver.steps_run} step(s)")
    return cluster_phase()


_CLUSTER_WORKER = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    import jax.numpy as jnp
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import multihost
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)
    cluster = multihost.initialize(
        multihost.ClusterConfig({coord!r}, 2, {pid}),
        attempts=2, timeout_s=120)
    conf = (NeuralNetConfiguration.builder()
            .n_in(8).lr(0.05).num_iterations(1).activation("tanh")
            .list(2).hidden_layer_sizes(16)
            .override(1, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True).build())
    rng = np.random.RandomState(0)
    batches = [DataSet(jnp.asarray(rng.randn(32, 8).astype(np.float32)),
                       jnp.asarray(np.eye(3, dtype=np.float32)[
                           rng.randint(0, 3, 32)]))
               for _ in range(8)]
    net = MultiLayerNetwork(conf).init(seed=1)

    class Beacon:
        def iteration_done(self, model, it, score):
            print("DRILL_STEP", it, flush=True)
    net.set_listeners([Beacon()])
    drv = ResilientFit(net, ResilienceConfig(
        checkpoint_dir={ckdir!r}, checkpoint_every=4,
        cluster_timeout_s=90, hb_interval_s=0.2, hb_timeout_s=10.0),
        cluster=cluster, fault_hook=lambda step: time.sleep(0.1))
    drv.fit(batches, num_epochs=100, seed=3)
    print("DRILL_EXIT preempted=%s step=%s" % (
        drv.preempted, drv.manager.latest_step()), flush=True)
""")


def cluster_phase() -> int:
    """SIGTERM to ONE member of a real 2-process cluster drains both
    at the same boundary (skip-aware)."""
    with tempfile.TemporaryDirectory() as d:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coord = f"127.0.0.1:{s.getsockname()[1]}"
        ckdir = os.path.join(d, "ckpts")
        err_paths = [os.path.join(d, f"worker{p}.stderr") for p in (0, 1)]
        procs = []
        for pid in (0, 1):
            with open(err_paths[pid], "w") as err_f:
                procs.append(subprocess.Popen(
                    [sys.executable, "-c",
                     _CLUSTER_WORKER.format(repo=REPO, coord=coord,
                                            pid=pid, ckdir=ckdir)],
                    stdout=subprocess.PIPE, stderr=err_f, text=True))
        # SIGTERM goes ONLY to worker 1; worker 0 must stop via the
        # cluster flag OR
        deadline = time.time() + 180
        seen = False
        while time.time() < deadline and not seen:
            line = procs[1].stdout.readline()
            if not line and procs[1].poll() is not None:
                break
            seen = line.startswith("DRILL_STEP")
        if not seen:
            for p in procs:
                p.kill()
                p.communicate(timeout=30)
            err = open(err_paths[1]).read().strip()
            tail = err.splitlines()[-1][:160] if err else "no steps"
            print(f"[preemption-drill] SKIP cluster phase: 2-process "
                  f"bring-up unavailable here ({tail})")
            return 0
        procs[1].send_signal(signal.SIGTERM)
        exits = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                exits.append((p.returncode, out))
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            print("[preemption-drill] FAIL: cluster drill hung after "
                  "SIGTERM (flag propagation broken?)")
            return 1
        lines = []
        for rc, out in exits:
            if rc != 0:
                print(f"[preemption-drill] FAIL: cluster worker exit "
                      f"{rc} (wanted clean 0)")
                return 1
            done = [ln for ln in out.splitlines()
                    if ln.startswith("DRILL_EXIT")]
            if not done or "preempted=True" not in done[0]:
                print(f"[preemption-drill] FAIL: cluster worker ended "
                      f"without a preemption stop: {done}")
                return 1
            lines.append(done[0])
        if len(set(lines)) != 1:
            print(f"[preemption-drill] FAIL: members stopped at "
                  f"different boundaries: {lines}")
            return 1
        from deeplearning4j_tpu.runtime.checkpoint import \
            CheckpointManager
        mgr = CheckpointManager(ckdir)
        latest = mgr.latest_step()
        mgr.verify(latest)
        print(f"[preemption-drill] cluster ok: SIGTERM to ONE member "
              f"drained BOTH at the same boundary, one cluster-"
              f"committed snapshot at step {latest}")
        return 0


if __name__ == "__main__":
    sys.exit(main())
