#!/usr/bin/env python
"""Lint: hot-path code in ``nn/`` and ``optimize/`` must compile through
the runtime engine (``runtime/compile_cache.cached_jit``), never raw
``jax.jit`` — a stray jit bypasses the cross-network compile cache and
the compile-count/cache-hit/compile-ms counters, silently re-charging
every worker replica a full XLA compile.

This is now a thin shim over ``tools/jaxlint`` (the AST analysis
framework this check grew into): the ``stray-jit`` rule there is the
same check, plus inline ``# jaxlint: disable=stray-jit`` suppressions
instead of a hardcoded exemption list.  CLI and exit codes are
unchanged — ``python tools/check_no_stray_jit.py`` still exits 1 on
findings — and the tier-1 run via ``tests/test_compile_engine.py``
still calls ``find_stray_jits``.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List


def _ensure_importable() -> None:
    """Make ``tools.jaxlint`` importable when this file is run as a
    script (sys.path[0] is tools/, not the repo root) or loaded from a
    file spec."""
    root = str(pathlib.Path(__file__).resolve().parent.parent)
    if root not in sys.path:
        sys.path.insert(0, root)


def find_stray_jits(repo_root: pathlib.Path) -> List[str]:
    """Return ``path:line: finding`` strings for every bypass in the
    engine-scoped packages (delegates to the jaxlint ``stray-jit``
    rule; paths are relative to ``repo_root`` as before)."""
    _ensure_importable()
    from tools.jaxlint import run_paths
    from tools.jaxlint.rules.stray_jit import SCOPES

    repo_root = pathlib.Path(repo_root)
    scope_dirs = [repo_root / s for s in SCOPES
                  if (repo_root / s).is_dir()]
    out: List[str] = []
    for f in run_paths(scope_dirs, select=["stray-jit"]):
        try:
            rel = pathlib.Path(f.path).relative_to(repo_root).as_posix()
        except ValueError:
            rel = f.path
        out.append(f"{rel}:{f.line}: {f.message}")
    return out


def main() -> int:
    repo_root = pathlib.Path(__file__).resolve().parent.parent
    findings = find_stray_jits(repo_root)
    if findings:
        print("stray jit calls bypassing the compile engine "
              f"({len(findings)}):")
        for f in findings:
            print("  " + f)
        return 1
    print("ok: nn/, optimize/, runtime/, serving/, and eval/ compile "
          "through the engine")
    return 0


if __name__ == "__main__":
    sys.exit(main())
