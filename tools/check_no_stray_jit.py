#!/usr/bin/env python
"""Lint: hot-path code in ``nn/`` and ``optimize/`` must compile through
the runtime engine (``runtime/compile_cache.cached_jit``), never raw
``jax.jit`` — a stray jit bypasses the cross-network compile cache and
the compile-count/cache-hit/compile-ms counters, silently re-charging
every worker replica a full XLA compile.

AST-based, so comments/docstrings mentioning jax.jit don't trip it.
Flags:
- ``jax.jit(...)`` / ``@jax.jit`` / ``partial(jax.jit, ...)`` attribute
  references (any expression position);
- ``from jax import jit`` / ``from jax import pjit`` imports (aliased or
  not) that would let a later bare call hide from the attribute check.

Runs standalone (exit 1 on findings) and as a tier-1 test via
``tests/test_compile_engine.py``.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from typing import List

#: package dirs whose every .py is a hot path routed through the engine
#: (runtime/ added with the resilience layer: guard code that compiled
#: outside the engine would silently re-charge every worker a compile
#: AND hide the guard's compile count from the no-extra-compiles
#: acceptance check; serving/ + eval/ added with the inference engine:
#: a stray jit there would hide serving-path compiles from the
#: steady-state compile_delta == 0 acceptance assertion)
SCOPES = ("deeplearning4j_tpu/nn", "deeplearning4j_tpu/optimize",
          "deeplearning4j_tpu/runtime", "deeplearning4j_tpu/serving",
          "deeplearning4j_tpu/eval")

#: the one legitimate jax.jit call site: the engine implementation itself
_EXEMPT = {"deeplearning4j_tpu/runtime/compile_cache.py"}

#: jax callables that compile programs and must go through the engine
_COMPILERS = {"jit", "pjit"}


def find_stray_jits(repo_root: pathlib.Path) -> List[str]:
    """Return ``path:line: finding`` strings for every bypass in SCOPES."""
    findings: List[str] = []
    for scope in SCOPES:
        for path in sorted((repo_root / scope).rglob("*.py")):
            rel = path.relative_to(repo_root)
            if str(rel).replace("\\", "/") in _EXEMPT:
                continue
            tree = ast.parse(path.read_text(), filename=str(path))
            for node in ast.walk(tree):
                if (isinstance(node, ast.Attribute)
                        and node.attr in _COMPILERS
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "jax"):
                    findings.append(
                        f"{rel}:{node.lineno}: jax.{node.attr} bypasses "
                        "runtime/compile_cache.cached_jit")
                elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                    for alias in node.names:
                        if alias.name in _COMPILERS:
                            findings.append(
                                f"{rel}:{node.lineno}: 'from jax import "
                                f"{alias.name}' hides compiles from the "
                                "engine")
    return findings


def main() -> int:
    repo_root = pathlib.Path(__file__).resolve().parent.parent
    findings = find_stray_jits(repo_root)
    if findings:
        print("stray jit calls bypassing the compile engine "
              f"({len(findings)}):")
        for f in findings:
            print("  " + f)
        return 1
    print("ok: nn/, optimize/, runtime/, serving/, and eval/ compile "
          "through the engine")
    return 0


if __name__ == "__main__":
    sys.exit(main())
