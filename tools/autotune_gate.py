"""CI smoke gate for the persistent kernel autotuner (runtime/autotune.py).

Machine-checks the MFU-campaign persistence contract on CPU, seconds:

1. a tiny sweep (XLA vs one interpreted Pallas block candidate, fwd+bwd)
   must complete and persist a winner;
2. the on-disk cache file must be well-formed JSON whose record carries
   the full evidence (key, impl, blocks, timings, device kind);
3. a COLD consult (in-process memo dropped — what a second process does)
   must return the winner from disk with ZERO re-sweeps;
4. after warmup, re-dispatching an attention step built from the cached
   winner must show ``compile_delta == 0`` — consults are pure host-side
   reads, so the steady state compiles nothing.

Run by ``tools/ci.sh`` after the telemetry gate; exits non-zero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    with tempfile.TemporaryDirectory() as cache_home:
        os.environ["DL4J_TPU_AUTOTUNE_CACHE"] = cache_home

        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.ops.pallas_attention import make_attn_fn
        from deeplearning4j_tpu.runtime import autotune
        from deeplearning4j_tpu.runtime.metrics import (compile_metrics,
                                                        mfu_metrics)

        # 1) tiny sweep completes
        mfu_metrics.reset()
        rec = autotune.sweep_attention(64, 64, 8, True, batch=1, n_heads=1,
                                       blocks=((16, 16),), repeats=1)
        if mfu_metrics.count("sweeps") != 1:
            print("[autotune-gate] FAIL: sweep did not book into the mfu "
                  "counter family")
            return 1

        # 2) cache file well-formed
        path = autotune.cache_path()
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"[autotune-gate] FAIL: cache file unreadable: {e!r}")
            return 1
        record = doc.get(rec["key"])
        missing = [k for k in ("impl", "block_q", "block_k", "step_ms",
                               "device_kind", "candidates")
                   if not isinstance(record, dict) or k not in record]
        if missing:
            print(f"[autotune-gate] FAIL: persisted record malformed "
                  f"(missing {missing}): {record!r}")
            return 1

        # 3) cold consult: winner from disk, zero re-sweeps
        autotune.reset_memo()
        sweeps_before = mfu_metrics.count("sweeps")
        got = autotune.ensure_attention(64, 64, 8, True)
        if got is None or got["impl"] != rec["impl"]:
            print(f"[autotune-gate] FAIL: cold consult returned {got!r}, "
                  f"swept winner was {rec['impl']!r}")
            return 1
        if mfu_metrics.count("sweeps") != sweeps_before:
            print("[autotune-gate] FAIL: a warmed consult re-swept")
            return 1
        if mfu_metrics.count("cache_hits") < 1:
            print("[autotune-gate] FAIL: cold consult did not book a "
                  "cache hit")
            return 1

        # 4) warmed dispatch through the policy: compile_delta == 0
        attn = make_attn_fn("pallas")      # interpret mode on CPU
        q = jax.random.normal(jax.random.key(0), (1, 64, 1, 8))

        def step(q):
            return jnp.sum(attn(q, q, q, None, True))

        from deeplearning4j_tpu.runtime import compile_cache
        fn = compile_cache.cached_jit(step, label="autotune_gate.step")
        float(fn(q))                               # warm
        before = compile_metrics.snapshot()["compile_count"]
        float(fn(q))
        delta = compile_metrics.snapshot()["compile_count"] - before
        if delta != 0:
            print(f"[autotune-gate] FAIL: warmed dispatch compiled "
                  f"{delta} new program(s)")
            return 1

    print(f"[autotune-gate] ok: winner={rec['impl']} "
          f"blocks=({rec['block_q']},{rec['block_k']}) cache hit with "
          f"0 re-sweeps, warmed compile_delta=0")
    return 0


if __name__ == "__main__":
    sys.exit(main())
