"""jaxlint — AST-based tracing-safety analyzer for this repo's JAX
invariants (see tools/jaxlint/core.py for the framework and
tools/jaxlint/rules/ for the rule set).

Public API::

    from tools.jaxlint import run_paths, check_source, REGISTRY
    findings = run_paths(["deeplearning4j_tpu", "bench.py", "tools"])

CLI: ``python -m tools.jaxlint [paths...]`` (see cli.py).
"""

from tools.jaxlint import rules  # noqa: F401 — registers the rule set
from tools.jaxlint.core import (  # noqa: F401
    Finding, REGISTRY, Rule, check_source, register, run_paths,
)

__all__ = ["Finding", "REGISTRY", "Rule", "check_source", "register",
           "run_paths"]
