"""Baseline file: grandfather existing findings without muting the rule.

The baseline is a checked-in JSON list of finding FINGERPRINTS — (rule,
path, stripped source-line text, occurrence index), deliberately not raw
line numbers, so unrelated edits above a grandfathered finding don't
churn the file.  A finding whose fingerprint is in the baseline is
reported separately and does not fail the run; anything new does.

Workflow:
- ``python -m tools.jaxlint <paths> --write-baseline`` snapshots the
  current findings into the baseline file;
- fixing a grandfathered finding leaves a stale entry behind — rerun
  ``--write-baseline`` to shed it (entries are never auto-pruned, so a
  finding can't silently flicker back in);
- NEW deliberate exceptions belong inline
  (``# jaxlint: disable=<rule> — reason``), not in the baseline: the
  baseline records debt, the annotation records a decision.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from tools.jaxlint.core import Finding

VERSION = 1

#: entries store REPO-RELATIVE paths (absolute outside the repo) so the
#: same finding fingerprints identically whether jaxlint was invoked
#: with relative paths from the repo root, absolute paths, or another cwd
_REPO_ROOT = Path(__file__).resolve().parents[2]


def norm_path(path_str: str) -> str:
    p = Path(path_str).resolve()
    try:
        return p.relative_to(_REPO_ROOT).as_posix()
    except ValueError:
        return p.as_posix()


def fingerprint_all(findings: Sequence[Finding]) -> List[Dict[str, object]]:
    """Stable fingerprints, with an occurrence index to disambiguate
    identical lines flagged by the same rule in one file."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Dict[str, object]] = []
    sources: Dict[str, List[str]] = {}
    for f in findings:
        norm = norm_path(f.path)
        if f.path not in sources:
            try:
                sources[f.path] = Path(f.path).read_text(
                    encoding="utf-8").splitlines()
            except OSError:
                sources[f.path] = []
        lines = sources[f.path]
        text = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        key = (f.rule, norm, text)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        out.append({"rule": f.rule, "path": norm, "line_text": text,
                    "occurrence": idx})
    return out


def _keys(entries: Sequence[Dict[str, object]]) -> set:
    return {(e.get("rule"), e.get("path"), e.get("line_text"),
             e.get("occurrence", 0)) for e in entries}


def load(path: Path) -> List[Dict[str, object]]:
    if not path.exists():
        return []
    data = json.loads(path.read_text(encoding="utf-8"))
    if isinstance(data, dict):
        if data.get("version") != VERSION:
            raise ValueError(
                f"baseline {path} has version {data.get('version')!r}, "
                f"this jaxlint reads version {VERSION}")
        entries = data.get("entries", [])
    else:
        entries = data
    if not isinstance(entries, list) \
            or not all(isinstance(e, dict) for e in entries):
        raise ValueError(f"baseline {path} is malformed (expected a list "
                         "of entry objects)")
    return list(entries)


def save(path: Path, findings: Sequence[Finding],
         scanned_paths: Optional[set] = None) -> int:
    """Snapshot ``findings`` into the baseline.  With ``scanned_paths``
    (normalized, from the run's actual file set) entries for files
    OUTSIDE the scan are retained — a partial-tree ``--write-baseline``
    must not erase another file's grandfathered debt."""
    entries = fingerprint_all(findings)
    if scanned_paths is not None and path.exists():
        retained = [e for e in load(path)
                    if e.get("path") not in scanned_paths]
        entries = retained + entries
    path.write_text(json.dumps({"version": VERSION, "entries": entries},
                               indent=2, sort_keys=True) + "\n",
                    encoding="utf-8")
    return len(entries)


def apply(findings: Sequence[Finding], entries: Sequence[Dict[str, object]]
          ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (new, grandfathered) against the baseline entries."""
    baselined_keys = _keys(entries)
    fps = fingerprint_all(findings)
    new: List[Finding] = []
    old: List[Finding] = []
    for f, fp in zip(findings, fps):
        key = (fp["rule"], fp["path"], fp["line_text"], fp["occurrence"])
        (old if key in baselined_keys else new).append(f)
    return new, old
