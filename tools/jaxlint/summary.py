"""Per-module export summaries — pass 1 of the jaxlint v4 linker.

jaxlint's rules were single-module by design; the invariants that have
actually bitten us lately are cross-module: the PR 17 page leak (a
failed-dispatch path dropped a ``PageAllocator`` pool's page-table
references), and the ``shard_specs``-vs-mesh contract the
``spec-axis-outside-mesh`` rule can only check when spec and mesh live
in the same file.  The classic fix is summary-based interprocedural
analysis (Infer's bi-abduction summaries, arXiv:1505.04055;
FlowDroid's taint summaries, PLDI'14): pass 1 extracts, per module, a
small JSON **export summary** of the facts other modules need; pass 2
(``link.py``) resolves call sites against the callee's summary.

What a summary records, per module-level function:

- ``donates`` — positional parameter indices whose buffers the function
  consumes (its body passes them into a literal ``donate_argnums``
  position of a jit-like call, or the function itself is decorated with
  one);
- ``donation_forwards`` — ``[param_idx, "dep.module:callee", pos]``
  edges where a param is forwarded positionally into an IMPORTED
  callable: the linker closes ``donates`` over these (fixpoint, so
  import cycles converge instead of recursing);
- ``spec_axes`` — the mesh axis names its ``PartitionSpec`` literals
  emit (``None`` when any entry is statically opaque — an unknowable
  spec is the caller's contract, never a finding);
- ``key_impure`` — the PR 15 ``key_impurities`` walker's verdicts over
  the body (a cache-key helper is pure iff this is empty and, at link
  time, every intra-repo callee it calls is pure too);
- ``key_calls`` — intra-repo callees, for the purity fixpoint.

And per class: a refcount **resource protocol** — method names that
acquire (``alloc``/``acquire``/``admit``), share (``share``), and
release (``free``/``release``/``recycle``) refcounted resources, for
classes that define both sides (``PageAllocator`` is the canonical
instance).

Summaries are persisted beside the result cache (``<cache>.summaries``)
keyed on (analyzer fingerprint, schema version, file source), so a warm
run re-extracts nothing.  The summary FINGERPRINT hashes the summary
CONTENT, not the source — editing a dependency's docstring doesn't
re-link its importers, changing its donation contract does.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.jaxlint import astutil

#: bump when the summary shape changes — a version mismatch discards
#: the whole summary cache (full re-extraction), never a partial read
SCHEMA_VERSION = 1

#: refcount-protocol method-name conventions.  A class exposes the
#: protocol iff it defines at least one acquire AND one release name;
#: ``share`` additionally bumps refcounts where present.
ACQUIRE_METHOD_NAMES = {"alloc", "acquire", "admit"}
SHARE_METHOD_NAMES = {"share"}
RELEASE_METHOD_NAMES = {"free", "release", "recycle"}

_REPO_ROOT = Path(__file__).resolve().parents[2]


# ---------------------------------------------------------------------------
# module naming and intra-repo import resolution
# ---------------------------------------------------------------------------

class Resolver:
    """Maps files <-> dotted module names for one run.

    ``roots`` are package roots — the repo root plus the parent of
    every scanned directory, so linting a scratch tree (``run_paths([
    tmp / 'pkg'])``) resolves ``pkg.dep`` imports exactly like linting
    ``deeplearning4j_tpu`` from the checkout does.  ``known`` seeds
    extra module names with no backing file — the in-memory fixture
    path tests link through (``link.link_sources``).
    """

    def __init__(self, roots: Sequence[Path],
                 known: Iterable[str] = ()) -> None:
        self.roots = [Path(r).resolve() for r in roots]
        self.known: Set[str] = set(known)

    def module_name(self, path: Path) -> Optional[str]:
        """``<root>/pkg/mod.py`` -> ``pkg.mod`` (``__init__.py`` -> the
        package itself) under the first containing root; None when no
        root contains the file — such a file cannot be imported by
        name, so it neither exports a summary address nor links."""
        p = Path(path)
        p = p if p.is_absolute() else p.resolve()
        for root in self.roots:
            try:
                rel = p.resolve().relative_to(root)
            except ValueError:
                continue
            parts = list(rel.parts)
            if not parts or not parts[-1].endswith(".py"):
                continue
            parts[-1] = parts[-1][:-3]
            if parts[-1] == "__init__":
                parts = parts[:-1]
            if parts:
                return ".".join(parts)
        return None

    def module_file(self, module: str) -> Optional[Path]:
        """Inverse mapping (plain module first, then package
        ``__init__``), under the first root that has it."""
        rel = Path(*module.split("."))
        for root in self.roots:
            for cand in (root / rel.with_suffix(".py"),
                         root / rel / "__init__.py"):
                if cand.is_file():
                    return cand
        return None

    def is_package(self, path: Path) -> bool:
        return Path(path).name == "__init__.py"

    def has_module(self, module: str) -> bool:
        return module in self.known \
            or self.module_file(module) is not None


def default_roots(paths: Sequence[Path]) -> List[Path]:
    roots: List[Path] = [_REPO_ROOT]
    for p in paths:
        p = Path(p)
        if p.is_dir():
            parent = p.resolve().parent
            if parent not in roots:
                roots.append(parent)
    return roots


def _resolve_relative(base_module: str, is_package: bool, level: int,
                      target: Optional[str]) -> Optional[str]:
    """``from ..mod import x`` inside ``base_module`` -> absolute dotted
    module, mirroring Python's resolution (level 1 = own package)."""
    parts = base_module.split(".")
    if not is_package:
        parts = parts[:-1]
    drop = level - 1
    if drop > len(parts):
        return None
    if drop:
        parts = parts[:-drop]
    if target:
        parts += target.split(".")
    return ".".join(parts) if parts else None


def import_bindings(tree: ast.Module, module: str, is_package: bool,
                    resolver: Resolver
                    ) -> Dict[str, Tuple[str, Optional[str]]]:
    """Local name -> (intra-repo dotted module, attr-or-None) for every
    import that resolves under the resolver's roots.

    ``from pkg.dep import f``      -> ``f: ("pkg.dep", "f")``
    ``from pkg import dep``        -> ``dep: ("pkg.dep", None)`` when
                                      ``pkg.dep`` is itself a module,
                                      else ``dep: ("pkg", "dep")``
    ``import pkg.dep as d``        -> ``d: ("pkg.dep", None)``
    ``from .dep import f``         -> resolved against ``module``
    """
    out: Dict[str, Tuple[str, Optional[str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if not resolver.has_module(a.name):
                    continue
                if a.asname is not None:
                    out[a.asname] = (a.name, None)
                else:
                    # ``import pkg.sub`` binds ``pkg``; the attribute-
                    # chain walk in resolve_imported_callee recovers
                    # ``pkg.sub.f`` calls from the head binding
                    head = a.name.split(".")[0]
                    out[head] = (head, None)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                src = _resolve_relative(module, is_package, node.level,
                                        node.module)
            else:
                src = node.module
            if src is None or not resolver.has_module(src):
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                if resolver.has_module(f"{src}.{a.name}"):
                    out[local] = (f"{src}.{a.name}", None)
                else:
                    out[local] = (src, a.name)
    return out


def intra_repo_imports(tree: ast.Module, module: str, is_package: bool,
                       resolver: Resolver) -> List[str]:
    """The intra-repo modules this module imports (sorted, deduped) —
    the edges of the linker's import graph."""
    deps = {t[0] for t in
            import_bindings(tree, module, is_package, resolver).values()}
    deps.discard(module)
    return sorted(deps)


def resolve_imported_callee(expr: ast.AST,
                            bindings: Dict[str, Tuple[str, Optional[str]]]
                            ) -> Optional[Tuple[str, str]]:
    """Resolve a call's func expression to ``(module, name)`` when it
    names an intra-repo import: a bare imported name (``f(...)`` after
    ``from pkg.dep import f``) or a module attribute (``dep.f(...)``
    after ``from pkg import dep`` / ``import pkg.dep``)."""
    dotted = astutil.dotted_name(expr)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    bound = bindings.get(head)
    if bound is None:
        return None
    mod, attr = bound
    if attr is not None:
        # the name was imported as an attribute: only the bare spelling
        # resolves (``f.sub`` would be an attribute OF the function)
        return (mod, attr) if not rest else None
    if not rest:
        return None                 # a bare module reference, not a call
    # ``pkg.sub.f(...)``: everything but the last attribute extends the
    # module path (``import pkg.sub`` binds just ``pkg`` above)
    parts = rest.split(".")
    return (".".join([mod] + parts[:-1]) if len(parts) > 1 else mod,
            parts[-1])


# ---------------------------------------------------------------------------
# per-function fact extraction
# ---------------------------------------------------------------------------

def _local_donation_positions(fn: astutil.FunctionNode) -> Set[int]:
    """Positional-param indices ``fn``'s own body (or decorator)
    provably donates: decorated ``@partial(jit, donate_argnums=...)``;
    ``g = cached_jit(body, donate_argnums=(k,))`` then ``g(p, ...)``;
    or the direct form ``cached_jit(body, donate_argnums=(k,))(p,...)``."""
    params = astutil.positional_params(fn)
    index = {p: i for i, p in enumerate(params)}
    donated: Set[int] = set()

    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            jit_like = astutil.is_jit_reference(dec.func) or (
                (astutil.dotted_name(dec.func) or "").rsplit(".", 1)[-1]
                == "partial" and dec.args
                and astutil.is_jit_reference(dec.args[0]))
            if jit_like:
                donated |= {i for i in astutil.donated_argnums(dec)
                            if i < len(params)}

    # names bound (anywhere in the body) to a jit call with donation
    jit_names: Dict[str, Set[int]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and astutil.is_jit_reference(node.value.func):
            nums = astutil.donated_argnums(node.value)
            if nums:
                jit_names[node.targets[0].id] = nums
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id in jit_names:
            nums = jit_names[node.func.id]
        elif isinstance(node.func, ast.Call) \
                and astutil.is_jit_reference(node.func.func):
            nums = astutil.donated_argnums(node.func)
        else:
            continue
        for pos, arg in enumerate(node.args):
            if pos in nums and isinstance(arg, ast.Name) \
                    and arg.id in index:
                donated.add(index[arg.id])
    return donated


def _donation_forwards(fn: astutil.FunctionNode,
                       bindings: Dict[str, Tuple[str, Optional[str]]]
                       ) -> List[List[object]]:
    """``[param_idx, "module:callee", callee_pos]`` for every positional
    forwarding of a param into an intra-repo imported callable — the
    linker's fixpoint edges."""
    index = {p: i for i, p in enumerate(astutil.positional_params(fn))}
    out: List[List[object]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = resolve_imported_callee(node.func, bindings)
        if callee is None:
            continue
        for pos, arg in enumerate(node.args):
            if isinstance(arg, ast.Name) and arg.id in index:
                edge: List[object] = [index[arg.id],
                                      f"{callee[0]}:{callee[1]}", pos]
                if edge not in out:
                    out.append(edge)
    return out


def _spec_axes(fn: astutil.FunctionNode, tree: ast.Module,
               chain: Dict[int, List[ast.AST]]) -> Optional[List[str]]:
    """Axis names the function's ``PartitionSpec`` literals emit.

    ``[]`` — the function builds no specs; ``None`` — it builds at
    least one spec whose entries are statically opaque (the axis set is
    the caller's contract); else the sorted union of resolved names.
    """
    aliases = astutil.partition_spec_aliases(tree)
    axes: Set[str] = set()
    saw_spec = False
    opaque = False
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.dotted_name(node.func)
        if name is None:
            continue
        leaf = name.rsplit(".", 1)[-1]
        if leaf != "PartitionSpec" and name not in aliases:
            continue
        saw_spec = True
        for entry in astutil.partition_spec_entries(node):
            values = astutil.resolve_axis_entry(
                entry, tree, chain.get(id(entry), []))
            if values is None:
                opaque = True
            else:
                axes |= values
    if not saw_spec:
        return []
    if opaque:
        return None
    return sorted(axes)


def _key_facts(fn: astutil.FunctionNode,
               bindings: Dict[str, Tuple[str, Optional[str]]]
               ) -> Tuple[List[str], List[str]]:
    """(impurity reasons, intra-repo callees) for the purity fixpoint:
    a cache-key helper is pure iff its own body carries no
    ``key_impurities`` AND every intra-repo callee is pure."""
    reasons: List[str] = []
    for stmt in fn.body:
        for _node, why in astutil.key_impurities(stmt):
            if why not in reasons:
                reasons.append(why)
    calls: List[str] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = resolve_imported_callee(node.func, bindings)
            if callee is not None:
                ref = f"{callee[0]}:{callee[1]}"
                if ref not in calls:
                    calls.append(ref)
    return reasons, calls


def _class_protocols(tree: ast.Module) -> Dict[str, Dict[str, List[str]]]:
    """Classes exposing the refcount resource protocol, by method-name
    convention: at least one acquire-named AND one release-named method
    (``share`` recorded where present).  The summary is the contract
    pass 2's ``page-refcount-balance`` checks call sites against."""
    out: Dict[str, Dict[str, List[str]]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = {s.name for s in cls.body
                   if isinstance(s, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        acquire = sorted(methods & ACQUIRE_METHOD_NAMES)
        release = sorted(methods & RELEASE_METHOD_NAMES)
        if acquire and release:
            out[cls.name] = {
                "acquire": acquire,
                "share": sorted(methods & SHARE_METHOD_NAMES),
                "release": release,
            }
    return out


# ---------------------------------------------------------------------------
# the summary itself
# ---------------------------------------------------------------------------

def extract(tree: ast.Module, module: str, is_package: bool,
            resolver: Resolver) -> Dict[str, object]:
    """One module's export summary (a pure-JSON dict, schema-versioned).

    Every module-level function is summarized (not only public ones —
    the linker needs private helpers for its fixpoints); consumers that
    care about the public surface filter on the leading underscore.
    """
    bindings = import_bindings(tree, module, is_package, resolver)
    chain = astutil.enclosing_chain(tree)
    functions: Dict[str, Dict[str, object]] = {}
    for name, fn in astutil.module_functions(tree).items():
        impure, key_calls = _key_facts(fn, bindings)
        functions[name] = {
            "params": astutil.positional_params(fn),
            "donates": sorted(_local_donation_positions(fn)),
            "donation_forwards": _donation_forwards(fn, bindings),
            "spec_axes": _spec_axes(fn, tree, chain),
            "key_impure": impure,
            "key_calls": key_calls,
        }
    deps = sorted({t[0] for t in bindings.values()} - {module})
    return {
        "schema": SCHEMA_VERSION,
        "module": module,
        "imports": deps,
        "functions": functions,
        "classes": _class_protocols(tree),
    }


def fingerprint(summary: Dict[str, object]) -> str:
    """Content hash of a summary — what importers' cache entries record.
    Hashing the summary (not the source) means an edit that leaves the
    export contract intact doesn't re-link a single importer."""
    blob = json.dumps(summary, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def cache_key(source: str) -> str:
    """Summary-cache key for one file: analyzer fingerprint + schema
    version + the file's own source.  (Dependency fingerprints are NOT
    part of this key — extraction is purely local; it's the RESULT
    cache whose entries record consumed summary fingerprints.)"""
    from tools.jaxlint.core import _analyzer_fingerprint
    return hashlib.sha256(
        (_analyzer_fingerprint() + "\x00" + str(SCHEMA_VERSION)
         + "\x00" + source).encode("utf-8")).hexdigest()
