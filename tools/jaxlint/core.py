"""jaxlint core: rule registry, suppression parsing, file runner.

The repo's JAX invariants (engine-routed jits, donation discipline,
shard_map only via ``compat.py``, pure host-sync-free step functions)
are whole-program properties XLA cannot check for us — a violation
compiles fine and fails silently as a recompile storm, use-after-donate
garbage, or a hidden device→host sync.  jaxlint machine-checks them the
way graph-level validation does in TensorFlow (arXiv:1605.08695) and
ahead-of-time checking does in the Julia-to-TPU work (arXiv:1810.09868):
statically, over the real ``ast``, before anything runs.

Everything here is stdlib-only (``ast`` + ``tokenize`` — **no regex**,
per the framework contract: rules match syntax trees, not strings) so
the analyzer imports in milliseconds and never drags jax into CI.

Suppression syntax (parsed from real COMMENT tokens, so string literals
never suppress anything):

- ``# jaxlint: disable=rule-a,rule-b — reason`` on a flagged line
  suppresses those rules for that line;
- the same comment on a ``def`` line suppresses the rules for the whole
  function body (the reason clause is required by convention — the
  point is a reviewed, explained exception, not a mute button);
- ``# jaxlint: disable-file=rule-a`` anywhere suppresses the rule for
  the entire file (e.g. ``compat.py`` IS the designated shard_map shim).
"""

from __future__ import annotations

import ast
import io
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""
    rule: str
    path: str          # POSIX-style, as resolved by the runner
    line: int
    col: int
    message: str
    severity: str      # "error" | "warning" — display only; any
                       # non-baselined finding fails the run
    end_line: int = 0  # last physical line of the flagged node, so a
                       # disable comment trailing a multi-line statement
                       # still suppresses it (0 = same as ``line``)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}")


class Rule:
    """Base class: subclass, set ``name``/``severity``/``description``
    (and ``family`` for non-tracing rules), implement ``check``.
    Register with ``@register``.

    Cross-module rules (the v4 ``cross-module`` family) set
    ``requires_link = True`` and implement ``check_linked`` instead:
    they only run when the two-pass pipeline hands them a
    ``link.LinkContext`` (module identity + the linked export summaries
    of the run's dependency closure).  Without a context — plain
    ``check_source`` calls, ``--no-link`` runs — they are silently
    skipped, never half-run."""

    name: str = ""
    severity: str = "error"
    description: str = ""
    family: str = "tracing"    # "tracing" | "collective" | "concurrency"
    requires_link: bool = False

    def applies_to(self, posix_path: str) -> bool:
        """Path filter (POSIX string).  Default: every file."""
        return True

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        raise NotImplementedError

    def check_linked(self, tree: ast.Module, posix_path: str,
                     ctx) -> Iterable[Finding]:
        """Linked check (``requires_link`` rules only).  ``ctx`` is a
        ``tools.jaxlint.link.LinkContext``."""
        raise NotImplementedError

    # helper so rules build findings without repeating themselves
    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(self.name, path, line,
                       getattr(node, "col_offset", 0), message,
                       self.severity,
                       end_line=getattr(node, "end_lineno", None) or line)


#: name -> rule INSTANCE (rules are stateless; one instance serves all runs)
REGISTRY: Dict[str, Rule] = {}


def register(cls):
    if not cls.name:
        raise ValueError(f"rule class {cls.__name__} has no name")
    if cls.name in REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    REGISTRY[cls.name] = cls()
    return cls


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def _parse_directive(comment: str) -> Optional[Tuple[str, Set[str]]]:
    """Parse ``# jaxlint: disable=a,b — reason`` without regex.

    Returns (kind, rule-names) where kind is "line" or "file", or None
    if the comment carries no jaxlint directive.
    """
    # the directive must be the comment's CONTENT, not a mention inside
    # prose ("# TODO: the jaxlint: disable syntax exists" mutes nothing)
    marker = "jaxlint:"
    text = comment.lstrip("#").strip()
    if not text.startswith(marker):
        return None
    rest = text[len(marker):].strip()
    for prefix, kind in (("disable-file=", "file"), ("disable=", "line")):
        if rest.startswith(prefix):
            # comma-separated rule names, tolerating spaces after commas
            # (``disable=rule-a, rule-b — reason``): each chunk's leading
            # [a-z0-9_-] run is the rule name; the first chunk with
            # trailing junk starts the human reason clause
            names: Set[str] = set()
            for chunk in rest[len(prefix):].split(","):
                chunk = chunk.strip()
                head = ""
                for ch in chunk:
                    if ch.isalnum() or ch in "-_":
                        head += ch
                    else:
                        break
                if head:
                    names.add(head)
                if head != chunk:
                    break
            return (kind, names) if names else None
    return None


class Suppressions:
    """Per-file suppression state, built once from the token stream."""

    def __init__(self, source: str, tree: ast.Module):
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        standalone: Set[int] = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                if not tok.line[:tok.start[1]].strip():
                    standalone.add(tok.start[0])
                parsed = _parse_directive(tok.string)
                if parsed is None:
                    continue
                kind, names = parsed
                if kind == "file":
                    self.file_wide |= names
                else:
                    self.by_line.setdefault(tok.start[0], set()).update(names)
        except tokenize.TokenError:
            pass
        # a disable TRAILING a `def`/decorator line (up to the first body
        # statement) covers the whole function body — the idiom for
        # "this function is a deliberate exception".  Standalone comment
        # lines in that range do NOT widen to the function: a developer
        # writing a full-line comment above the first statement means
        # that spot, not a blanket mute.
        self.spans: List[Tuple[int, int, Set[str]]] = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            first_body = node.body[0].lineno if node.body else node.lineno
            covered: Set[str] = set()
            start = min(d.lineno for d in node.decorator_list) \
                if node.decorator_list else node.lineno
            for line in range(start, first_body):
                if line not in standalone:
                    covered |= self.by_line.get(line, set())
            if covered:
                self.spans.append(
                    (node.lineno, node.end_lineno or node.lineno, covered))

    def hides(self, finding: Finding) -> bool:
        if finding.rule in self.file_wide:
            return True
        # any physical line of the flagged node may carry the comment —
        # a multi-line call is suppressed from its closing line too
        last = max(finding.end_line, finding.line)
        if any(finding.rule in self.by_line.get(line, set())
               for line in range(finding.line, last + 1)):
            return True
        return any(start <= finding.line <= end and finding.rule in rules
                   for start, end, rules in self.spans)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def iter_python_files(paths: Sequence[Path]) -> List[Path]:
    """Expand files/dirs to a sorted, deduplicated .py list."""
    out: List[Path] = []
    seen = set()
    for p in paths:
        if p.is_dir():
            candidates = sorted(p.rglob("*.py"))
        else:
            candidates = [p]
        for c in candidates:
            if "__pycache__" in c.parts:
                continue
            r = c.resolve()
            if r not in seen:
                seen.add(r)
                out.append(c)
    return out


def check_source(source: str, posix_path: str,
                 rules: Optional[Sequence[Rule]] = None,
                 filename: Optional[str] = None,
                 link_ctx=None,
                 tree: Optional[ast.Module] = None) -> List[Finding]:
    """Run ``rules`` (default: all registered) over one source blob.

    Returns only findings that survive inline suppressions.  Exposed
    directly so tests can lint fixture snippets without touching disk.
    ``link_ctx`` (a ``link.LinkContext``) enables the cross-module
    rules; without it they are skipped — a single-module call cannot
    half-run a linking rule.  ``tree`` reuses a pre-parsed AST (pass 1
    already parsed summary-cache misses; a cold two-pass run must not
    pay the parse twice).
    """
    if tree is None:
        tree = ast.parse(source, filename=filename or posix_path)
    sup = Suppressions(source, tree)
    active = list(REGISTRY.values()) if rules is None else list(rules)
    findings: List[Finding] = []
    for rule in active:
        if not rule.applies_to(posix_path):
            continue
        if rule.requires_link:
            if link_ctx is None:
                continue
            found = rule.check_linked(tree, posix_path, link_ctx)
        else:
            found = rule.check(tree, posix_path)
        findings.extend(f for f in found if not sup.hides(f))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


_ANALYZER_FP: Optional[str] = None


def _analyzer_fingerprint(root: Optional[Path] = None) -> str:
    """Hash of the analyzer's OWN sources — part of every cache key so
    that editing ANY of them (rule modules, but also the shared
    framework: ``astutil.py``, ``core.py``, ``cli.py``, ...) invalidates
    cached results for unchanged target files too.  A fix to the
    class-scoped lock tracking must re-lint every file, not only the
    ones whose text changed.

    ``root`` overrides the hashed package directory (tests point it at
    a scratch copy to prove framework edits change the fingerprint);
    the default — this package — is computed once per process.
    """
    import hashlib

    def compute(pkg: Path) -> str:
        h = hashlib.sha256()
        for f in sorted(pkg.rglob("*.py")):
            if "__pycache__" not in f.parts:
                # path RELATIVE to the package, so the fingerprint only
                # depends on the analyzer's content, not where the
                # checkout lives
                h.update(f.relative_to(pkg).as_posix().encode())
                h.update(b"\x00")
                h.update(f.read_bytes())
        return h.hexdigest()

    if root is not None:
        return compute(Path(root))
    global _ANALYZER_FP
    if _ANALYZER_FP is None:
        _ANALYZER_FP = compute(Path(__file__).resolve().parent)
    return _ANALYZER_FP


def summary_cache_path(cache_path: Path) -> Path:
    """The summary store rides beside the result cache:
    ``.jaxlint_cache.json`` -> ``.jaxlint_cache.json.summaries``."""
    return cache_path.with_name(cache_path.name + ".summaries")


class _Pass1:
    """Everything pass 1 (summary extraction) hands to pass 2."""

    def __init__(self) -> None:
        self.resolver = None             # summary.Resolver
        self.module_by_path: Dict[str, Tuple[str, bool]] = {}
        self.linked: Dict[str, dict] = {}    # linked summaries
        self.fp_by_module: Dict[str, str] = {}   # RAW summary content fp
        self.closure: Dict[str, List[str]] = {}
        self.sources: Dict[str, str] = {}
        self.trees: Dict[str, ast.Module] = {}   # parsed on cache miss
        self.extracted = 0
        self.cached = 0

    def deps_for(self, posix: str) -> Dict[str, str]:
        """The summary fingerprints this file's linking consumed — what
        its result-cache entry must record.  Closing over the TRANSITIVE
        import set matters: the donation/purity fixpoints flow facts
        through intermediate modules, so a dep-of-a-dep edit can change
        what linking concludes here."""
        mod_pkg = self.module_by_path.get(posix)
        if mod_pkg is None:
            return {}
        return {m: self.fp_by_module[m]
                for m in self.closure.get(mod_pkg[0], [])
                if m in self.fp_by_module}

    def context_for(self, posix: str):
        mod_pkg = self.module_by_path.get(posix)
        if mod_pkg is None:
            return None
        from tools.jaxlint.link import LinkContext
        return LinkContext(module=mod_pkg[0], is_package=mod_pkg[1],
                           resolver=self.resolver,
                           summaries=self.linked)


def _build_summaries(files: List[Path], paths: Sequence,
                     cache_path: Optional[Path]) -> _Pass1:
    """Pass 1: extract (or load) the export summary of every scanned
    file AND of every intra-repo module in their transitive import
    closure — single-file runs still link against the full summaries of
    what they import.  Persisted beside the result cache, keyed on
    (analyzer fingerprint, schema version, file source): a warm run
    re-extracts nothing."""
    import json
    from tools.jaxlint import link as link_mod
    from tools.jaxlint import summary as summary_mod

    out = _Pass1()
    out.resolver = summary_mod.Resolver(
        summary_mod.default_roots([Path(p) for p in paths]))

    store: dict = {}
    spath = summary_cache_path(cache_path) if cache_path else None
    if spath is not None and spath.exists():
        try:
            data = json.loads(spath.read_text(encoding="utf-8"))
            # a schema mismatch discards the WHOLE store: summaries
            # must be re-extracted in full, never half-read
            if isinstance(data, dict) \
                    and data.get("schema") == summary_mod.SCHEMA_VERSION:
                store = data.get("entries", {})
                if not isinstance(store, dict):
                    store = {}
        except (OSError, ValueError):
            store = {}

    raw: Dict[str, dict] = {}
    dirty = False
    queue: List[Path] = list(files)
    seen_paths: Set[str] = set()
    while queue:
        path = queue.pop(0)
        posix = path.as_posix()
        if posix in seen_paths:
            continue
        seen_paths.add(posix)
        module = out.resolver.module_name(path)
        if module is None or module in raw:
            continue
        try:
            source = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError):
            continue
        out.sources[posix] = source
        is_pkg = out.resolver.is_package(path)
        out.module_by_path[posix] = (module, is_pkg)
        key = summary_mod.cache_key(source)
        entry = store.get(posix)
        if entry is not None and entry.get("key") == key:
            summ = entry["summary"]
            out.cached += 1
        else:
            try:
                tree = ast.parse(source, filename=posix)
            except SyntaxError:
                continue        # pass 2 reports the parse error
            out.trees[posix] = tree
            summ = summary_mod.extract(tree, module, is_pkg,
                                       out.resolver)
            store[posix] = {"key": key, "module": module,
                            "summary": summ}
            out.extracted += 1
            dirty = True
        raw[module] = summ
        out.fp_by_module[module] = summary_mod.fingerprint(summ)
        for dep in summ.get("imports", []):
            dep_file = out.resolver.module_file(dep)
            if dep_file is not None \
                    and dep_file.as_posix() not in seen_paths:
                queue.append(dep_file)

    out.linked = link_mod.resolve(raw)
    out.closure = link_mod.dependency_closure(
        link_mod.import_graph(raw))

    if spath is not None and dirty:
        # prune entries whose file vanished (renames/moves would
        # otherwise accrete forever), then persist
        store = {p: e for p, e in store.items() if Path(p).exists()}
        try:
            spath.write_text(json.dumps(
                {"schema": summary_mod.SCHEMA_VERSION,
                 "entries": store}, sort_keys=True), encoding="utf-8")
        except OSError:
            pass
    return out


def _lint_file(path: Path, rules: Optional[Sequence[Rule]],
               rule_names: Sequence[str], cache: Optional[dict],
               pass1: Optional[_Pass1]
               ) -> Tuple[str, List[Finding], Optional[str], bool,
                          Dict[str, str]]:
    """One file's worth of work: returns (posix path, findings, cache
    key or None, hit, consumed summary fingerprints) — pure w.r.t.
    shared state, so files can run on any worker in any order."""
    posix = path.as_posix()
    source = pass1.sources.get(posix) if pass1 is not None else None
    if source is None:
        try:
            source = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            return posix, [Finding("parse-error", posix, 1, 0,
                                   f"unreadable: {e}", "error")], \
                None, False, {}
    link_ctx = pass1.context_for(posix) if pass1 is not None else None
    deps = pass1.deps_for(posix) if pass1 is not None else {}
    key = None
    if cache is not None:
        import hashlib
        key = hashlib.sha256(
            (_analyzer_fingerprint() + "\x00"
             + "\x00".join(rule_names) + "\x00" + source)
            .encode("utf-8")).hexdigest()
        hit = cache.get(posix)
        # a hit must ALSO have been produced under the same linking
        # conditions: same linked/unlinked mode, and the very summary
        # fingerprints this file's dependency closure carries NOW —
        # otherwise editing module A would serve B's stale cross-module
        # findings from B's unchanged text (the v3 staleness hole)
        if hit is not None and hit.get("key") == key \
                and bool(hit.get("linked")) == (link_ctx is not None) \
                and hit.get("deps", {}) == deps:
            return posix, [Finding(**f) for f in hit["findings"]], \
                key, True, deps
    tree = pass1.trees.get(posix) if pass1 is not None else None
    try:
        file_findings = check_source(source, posix, rules,
                                     link_ctx=link_ctx, tree=tree)
    except SyntaxError as e:
        file_findings = [Finding("parse-error", posix, e.lineno or 1,
                                 e.offset or 0,
                                 f"syntax error: {e.msg}", "error")]
    return posix, file_findings, key, False, deps


def run_paths(paths: Sequence, select: Optional[Sequence[str]] = None,
              cache_path: Optional[Path] = None,
              jobs: int = 1, link: bool = True,
              stats: Optional[dict] = None) -> List[Finding]:
    """Lint every .py under ``paths``; returns unsuppressed findings.

    ``select`` restricts to a subset of rule names.  Baseline filtering
    is layered on top by the CLI (``baseline.apply``) so API callers see
    the raw truth.  With ``cache_path`` a per-file result cache is
    consulted and updated — keyed on (analyzer sources, rule selection,
    file source) PLUS, since v4, the summary fingerprints of the file's
    intra-repo dependency closure: editing module A re-links (re-lints)
    every importer of A whose cross-module findings could change, while
    a docstring-only edit that leaves A's export summary intact does
    not.

    ``link`` enables the v4 two-pass pipeline: pass 1 extracts/loads
    per-module export summaries (cached beside the result cache),
    pass 2 runs every rule with a ``LinkContext`` so the cross-module
    family can check call sites against callee summaries.  With
    ``link=False`` only the single-module rules run (the v3 behavior).

    ``jobs`` > 1 analyzes files concurrently — files are independent
    (rules are stateless instances, the caches and the linked summary
    table are read-only during the run) and results are stitched back
    in file order, so the output is byte-identical whatever the worker
    count.  ``stats``, when given, is filled with ``summary_ms``/
    ``link_ms`` timings and summary-cache hit counts.
    """
    import time

    if select is not None:
        unknown = set(select) - set(REGISTRY)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        rules = [REGISTRY[n] for n in select]
        rule_names = sorted(select)
    else:
        rules = None
        rule_names = sorted(REGISTRY)

    cache: Optional[dict] = None
    if cache_path is not None:
        cache = {}
        if cache_path.exists():
            import json
            try:
                cache = json.loads(cache_path.read_text(encoding="utf-8"))
                if not isinstance(cache, dict):
                    cache = {}
            except (OSError, ValueError):
                cache = {}

    files = iter_python_files([Path(p) for p in paths])

    pass1: Optional[_Pass1] = None
    t0 = time.perf_counter()
    if link:
        pass1 = _build_summaries(files, paths, cache_path)
    t1 = time.perf_counter()

    if jobs > 1 and len(files) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            results = list(pool.map(
                lambda p: _lint_file(p, rules, rule_names, cache, pass1),
                files))
    else:
        results = [_lint_file(p, rules, rule_names, cache, pass1)
                   for p in files]
    t2 = time.perf_counter()

    if stats is not None:
        stats["summary_ms"] = round((t1 - t0) * 1000.0, 3)
        stats["link_ms"] = round((t2 - t1) * 1000.0, 3)
        stats["summaries_extracted"] = pass1.extracted if pass1 else 0
        stats["summaries_cached"] = pass1.cached if pass1 else 0

    findings: List[Finding] = []
    dirty = False
    for posix, file_findings, key, hit, deps in results:
        findings.extend(file_findings)
        if cache is not None and key is not None and not hit:
            cache[posix] = {"key": key,
                            "linked": pass1 is not None
                            and pass1.context_for(posix) is not None,
                            "deps": deps,
                            "findings": [vars(f) for f in file_findings]}
            dirty = True

    if cache_path is not None and dirty:
        import json
        try:
            cache_path.write_text(json.dumps(cache), encoding="utf-8")
        except OSError:
            pass
    return findings
