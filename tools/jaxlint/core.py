"""jaxlint core: rule registry, suppression parsing, file runner.

The repo's JAX invariants (engine-routed jits, donation discipline,
shard_map only via ``compat.py``, pure host-sync-free step functions)
are whole-program properties XLA cannot check for us — a violation
compiles fine and fails silently as a recompile storm, use-after-donate
garbage, or a hidden device→host sync.  jaxlint machine-checks them the
way graph-level validation does in TensorFlow (arXiv:1605.08695) and
ahead-of-time checking does in the Julia-to-TPU work (arXiv:1810.09868):
statically, over the real ``ast``, before anything runs.

Everything here is stdlib-only (``ast`` + ``tokenize`` — **no regex**,
per the framework contract: rules match syntax trees, not strings) so
the analyzer imports in milliseconds and never drags jax into CI.

Suppression syntax (parsed from real COMMENT tokens, so string literals
never suppress anything):

- ``# jaxlint: disable=rule-a,rule-b — reason`` on a flagged line
  suppresses those rules for that line;
- the same comment on a ``def`` line suppresses the rules for the whole
  function body (the reason clause is required by convention — the
  point is a reviewed, explained exception, not a mute button);
- ``# jaxlint: disable-file=rule-a`` anywhere suppresses the rule for
  the entire file (e.g. ``compat.py`` IS the designated shard_map shim).
"""

from __future__ import annotations

import ast
import io
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""
    rule: str
    path: str          # POSIX-style, as resolved by the runner
    line: int
    col: int
    message: str
    severity: str      # "error" | "warning" — display only; any
                       # non-baselined finding fails the run
    end_line: int = 0  # last physical line of the flagged node, so a
                       # disable comment trailing a multi-line statement
                       # still suppresses it (0 = same as ``line``)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.severity}] {self.rule}: {self.message}")


class Rule:
    """Base class: subclass, set ``name``/``severity``/``description``
    (and ``family`` for non-tracing rules), implement ``check``.
    Register with ``@register``."""

    name: str = ""
    severity: str = "error"
    description: str = ""
    family: str = "tracing"    # "tracing" | "collective" | "concurrency"

    def applies_to(self, posix_path: str) -> bool:
        """Path filter (POSIX string).  Default: every file."""
        return True

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        raise NotImplementedError

    # helper so rules build findings without repeating themselves
    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(self.name, path, line,
                       getattr(node, "col_offset", 0), message,
                       self.severity,
                       end_line=getattr(node, "end_lineno", None) or line)


#: name -> rule INSTANCE (rules are stateless; one instance serves all runs)
REGISTRY: Dict[str, Rule] = {}


def register(cls):
    if not cls.name:
        raise ValueError(f"rule class {cls.__name__} has no name")
    if cls.name in REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    REGISTRY[cls.name] = cls()
    return cls


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def _parse_directive(comment: str) -> Optional[Tuple[str, Set[str]]]:
    """Parse ``# jaxlint: disable=a,b — reason`` without regex.

    Returns (kind, rule-names) where kind is "line" or "file", or None
    if the comment carries no jaxlint directive.
    """
    # the directive must be the comment's CONTENT, not a mention inside
    # prose ("# TODO: the jaxlint: disable syntax exists" mutes nothing)
    marker = "jaxlint:"
    text = comment.lstrip("#").strip()
    if not text.startswith(marker):
        return None
    rest = text[len(marker):].strip()
    for prefix, kind in (("disable-file=", "file"), ("disable=", "line")):
        if rest.startswith(prefix):
            # comma-separated rule names, tolerating spaces after commas
            # (``disable=rule-a, rule-b — reason``): each chunk's leading
            # [a-z0-9_-] run is the rule name; the first chunk with
            # trailing junk starts the human reason clause
            names: Set[str] = set()
            for chunk in rest[len(prefix):].split(","):
                chunk = chunk.strip()
                head = ""
                for ch in chunk:
                    if ch.isalnum() or ch in "-_":
                        head += ch
                    else:
                        break
                if head:
                    names.add(head)
                if head != chunk:
                    break
            return (kind, names) if names else None
    return None


class Suppressions:
    """Per-file suppression state, built once from the token stream."""

    def __init__(self, source: str, tree: ast.Module):
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        standalone: Set[int] = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                if not tok.line[:tok.start[1]].strip():
                    standalone.add(tok.start[0])
                parsed = _parse_directive(tok.string)
                if parsed is None:
                    continue
                kind, names = parsed
                if kind == "file":
                    self.file_wide |= names
                else:
                    self.by_line.setdefault(tok.start[0], set()).update(names)
        except tokenize.TokenError:
            pass
        # a disable TRAILING a `def`/decorator line (up to the first body
        # statement) covers the whole function body — the idiom for
        # "this function is a deliberate exception".  Standalone comment
        # lines in that range do NOT widen to the function: a developer
        # writing a full-line comment above the first statement means
        # that spot, not a blanket mute.
        self.spans: List[Tuple[int, int, Set[str]]] = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            first_body = node.body[0].lineno if node.body else node.lineno
            covered: Set[str] = set()
            start = min(d.lineno for d in node.decorator_list) \
                if node.decorator_list else node.lineno
            for line in range(start, first_body):
                if line not in standalone:
                    covered |= self.by_line.get(line, set())
            if covered:
                self.spans.append(
                    (node.lineno, node.end_lineno or node.lineno, covered))

    def hides(self, finding: Finding) -> bool:
        if finding.rule in self.file_wide:
            return True
        # any physical line of the flagged node may carry the comment —
        # a multi-line call is suppressed from its closing line too
        last = max(finding.end_line, finding.line)
        if any(finding.rule in self.by_line.get(line, set())
               for line in range(finding.line, last + 1)):
            return True
        return any(start <= finding.line <= end and finding.rule in rules
                   for start, end, rules in self.spans)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def iter_python_files(paths: Sequence[Path]) -> List[Path]:
    """Expand files/dirs to a sorted, deduplicated .py list."""
    out: List[Path] = []
    seen = set()
    for p in paths:
        if p.is_dir():
            candidates = sorted(p.rglob("*.py"))
        else:
            candidates = [p]
        for c in candidates:
            if "__pycache__" in c.parts:
                continue
            r = c.resolve()
            if r not in seen:
                seen.add(r)
                out.append(c)
    return out


def check_source(source: str, posix_path: str,
                 rules: Optional[Sequence[Rule]] = None,
                 filename: Optional[str] = None) -> List[Finding]:
    """Run ``rules`` (default: all registered) over one source blob.

    Returns only findings that survive inline suppressions.  Exposed
    directly so tests can lint fixture snippets without touching disk.
    """
    tree = ast.parse(source, filename=filename or posix_path)
    sup = Suppressions(source, tree)
    active = list(REGISTRY.values()) if rules is None else list(rules)
    findings: List[Finding] = []
    for rule in active:
        if not rule.applies_to(posix_path):
            continue
        findings.extend(f for f in rule.check(tree, posix_path)
                        if not sup.hides(f))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


_ANALYZER_FP: Optional[str] = None


def _analyzer_fingerprint(root: Optional[Path] = None) -> str:
    """Hash of the analyzer's OWN sources — part of every cache key so
    that editing ANY of them (rule modules, but also the shared
    framework: ``astutil.py``, ``core.py``, ``cli.py``, ...) invalidates
    cached results for unchanged target files too.  A fix to the
    class-scoped lock tracking must re-lint every file, not only the
    ones whose text changed.

    ``root`` overrides the hashed package directory (tests point it at
    a scratch copy to prove framework edits change the fingerprint);
    the default — this package — is computed once per process.
    """
    import hashlib

    def compute(pkg: Path) -> str:
        h = hashlib.sha256()
        for f in sorted(pkg.rglob("*.py")):
            if "__pycache__" not in f.parts:
                # path RELATIVE to the package, so the fingerprint only
                # depends on the analyzer's content, not where the
                # checkout lives
                h.update(f.relative_to(pkg).as_posix().encode())
                h.update(b"\x00")
                h.update(f.read_bytes())
        return h.hexdigest()

    if root is not None:
        return compute(Path(root))
    global _ANALYZER_FP
    if _ANALYZER_FP is None:
        _ANALYZER_FP = compute(Path(__file__).resolve().parent)
    return _ANALYZER_FP


def _lint_file(path: Path, rules: Optional[Sequence[Rule]],
               rule_names: Sequence[str], cache: Optional[dict]
               ) -> Tuple[str, List[Finding], Optional[str], bool]:
    """One file's worth of work: returns (posix path, findings, cache
    key or None, hit) — pure w.r.t. shared state, so files can run on
    any worker in any order."""
    posix = path.as_posix()
    try:
        source = path.read_text(encoding="utf-8")
    except (OSError, UnicodeDecodeError) as e:
        return posix, [Finding("parse-error", posix, 1, 0,
                               f"unreadable: {e}", "error")], None, False
    key = None
    if cache is not None:
        import hashlib
        key = hashlib.sha256(
            (_analyzer_fingerprint() + "\x00"
             + "\x00".join(rule_names) + "\x00" + source)
            .encode("utf-8")).hexdigest()
        hit = cache.get(posix)
        if hit is not None and hit.get("key") == key:
            return posix, [Finding(**f) for f in hit["findings"]], \
                key, True
    try:
        file_findings = check_source(source, posix, rules)
    except SyntaxError as e:
        file_findings = [Finding("parse-error", posix, e.lineno or 1,
                                 e.offset or 0,
                                 f"syntax error: {e.msg}", "error")]
    return posix, file_findings, key, False


def run_paths(paths: Sequence, select: Optional[Sequence[str]] = None,
              cache_path: Optional[Path] = None,
              jobs: int = 1) -> List[Finding]:
    """Lint every .py under ``paths``; returns unsuppressed findings.

    ``select`` restricts to a subset of rule names.  Baseline filtering
    is layered on top by the CLI (``baseline.apply``) so API callers see
    the raw truth.  With ``cache_path`` a per-file result cache is
    consulted and updated — keyed on (analyzer sources, rule selection,
    file source), so editing either the file or ANY jaxlint source
    (rules, astutil, core) re-lints.

    ``jobs`` > 1 analyzes files concurrently — files are independent
    (rules are stateless instances, the cache is read-only during the
    run) and results are stitched back in file order, so the output is
    byte-identical whatever the worker count.
    """
    if select is not None:
        unknown = set(select) - set(REGISTRY)
        if unknown:
            raise KeyError(f"unknown rule(s): {sorted(unknown)}")
        rules = [REGISTRY[n] for n in select]
        rule_names = sorted(select)
    else:
        rules = None
        rule_names = sorted(REGISTRY)

    cache: Optional[dict] = None
    if cache_path is not None:
        cache = {}
        if cache_path.exists():
            import json
            try:
                cache = json.loads(cache_path.read_text(encoding="utf-8"))
                if not isinstance(cache, dict):
                    cache = {}
            except (OSError, ValueError):
                cache = {}

    files = iter_python_files([Path(p) for p in paths])
    if jobs > 1 and len(files) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            results = list(pool.map(
                lambda p: _lint_file(p, rules, rule_names, cache), files))
    else:
        results = [_lint_file(p, rules, rule_names, cache) for p in files]

    findings: List[Finding] = []
    dirty = False
    for posix, file_findings, key, hit in results:
        findings.extend(file_findings)
        if cache is not None and key is not None and not hit:
            cache[posix] = {"key": key,
                            "findings": [vars(f) for f in file_findings]}
            dirty = True

    if cache_path is not None and dirty:
        import json
        try:
            cache_path.write_text(json.dumps(cache), encoding="utf-8")
        except OSError:
            pass
    return findings
