"""``python -m tools.jaxlint [paths...]`` — the command-line front-end.

Exit codes: 0 = clean (or every finding baselined/suppressed),
1 = at least one non-baselined finding, 2 = usage error.

``--format json`` emits one machine-readable object (file/line/col/
rule/severity/family/message records plus the summary, including
``summary_ms``/``link_ms`` pass timings and the summary-cache hit
counts) on stdout with the SAME exit codes, so CI renders findings as
annotations instead of scraping text; ``--jobs N`` fans per-file
analysis out over N workers with byte-identical output ordering.

v4 adds the two-pass linked analysis: ``--no-link`` falls back to the
v3 single-pass behavior (cross-module rules skipped), and
``--dump-summaries [MODULE]`` prints the linked export summaries pass
1 extracted — the debugging window into what the cross-module rules
actually saw.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional, Sequence

from tools.jaxlint import baseline as baseline_mod
from tools.jaxlint import core as core_mod
from tools.jaxlint import rules  # noqa: F401 — registers the rule set
from tools.jaxlint.core import REGISTRY, iter_python_files, run_paths

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
DEFAULT_CACHE = Path(".jaxlint_cache.json")
DEFAULT_PATHS = ("deeplearning4j_tpu", "bench.py", "tools")


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="AST-based tracing-safety analyzer for this repo's "
                    "JAX invariants")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/dirs to lint (default: %(default)s)")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule subset")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    metavar="FILE",
                    help="baseline JSON (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into the baseline "
                         "and exit 0")
    # a flag + a separate FILE option on purpose: an optional-argument
    # form (--cache [FILE]) would silently swallow the first positional
    # path as the cache filename and lint nothing
    ap.add_argument("--cache", action="store_true",
                    help=f"use the per-file result cache {DEFAULT_CACHE} "
                         "(gitignored)")
    ap.add_argument("--cache-file", type=Path, default=None,
                    metavar="FILE",
                    help="result cache at FILE (implies --cache)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the registered rules and exit")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    dest="fmt",
                    help="finding output format (default: %(default)s); "
                         "json emits file/line/rule/severity records for "
                         "CI annotation rendering, same exit codes")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="analyze N files concurrently (files are "
                         "independent; output order is deterministic "
                         "regardless of N)")
    ap.add_argument("--no-link", action="store_true",
                    help="skip pass 1 (summary extraction) and pass-2 "
                         "linking; cross-module rules don't run — the "
                         "v3 single-pass behavior")
    ap.add_argument("--dump-summaries", nargs="?", const="", default=None,
                    metavar="MODULE",
                    help="print the extracted (linked) export summary "
                         "of MODULE as JSON and exit — or every "
                         "summary in the run's closure when MODULE is "
                         "omitted (spell it --dump-summaries=MODULE "
                         "when positional paths follow)")
    args = ap.parse_args(argv)

    if args.jobs < 1:
        print(f"error: --jobs must be >= 1, got {args.jobs}",
              file=sys.stderr)
        return 2

    if args.list_rules:
        # grouped by family so the two PR 10 rule families read as the
        # units they ship as
        by_family: dict = {}
        for name in sorted(REGISTRY):
            by_family.setdefault(REGISTRY[name].family, []).append(name)
        for family in sorted(by_family):
            print(f"{family}:")
            for name in by_family[family]:
                rule = REGISTRY[name]
                print(f"  {name:30s} [{rule.severity}] "
                      f"{rule.description}")
        return 0

    select = [s.strip() for s in args.select.split(",") if s.strip()] \
        if args.select else None
    cache_path = args.cache_file if args.cache_file is not None \
        else (DEFAULT_CACHE if args.cache else None)

    if args.dump_summaries is not None:
        files = iter_python_files([Path(p) for p in args.paths])
        pass1 = core_mod._build_summaries(files, args.paths, cache_path)
        if args.dump_summaries:
            summ = pass1.linked.get(args.dump_summaries)
            if summ is None:
                print(f"error: no export summary for module "
                      f"{args.dump_summaries!r} in the scanned closure "
                      f"({len(pass1.linked)} modules); module names are "
                      "dotted, rooted at the repo",
                      file=sys.stderr)
                return 2
            print(json.dumps(summ, indent=2, sort_keys=True))
        else:
            print(json.dumps(pass1.linked, indent=2, sort_keys=True))
        return 0

    stats: dict = {}
    try:
        findings = run_paths(args.paths, select, cache_path=cache_path,
                             jobs=args.jobs, link=not args.no_link,
                             stats=stats)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        if select is not None:
            print("error: --write-baseline with --select would snapshot "
                  "a partial rule set (erasing other rules' entries); "
                  "run it without --select", file=sys.stderr)
            return 2
        scanned = {baseline_mod.norm_path(p.as_posix())
                   for p in iter_python_files(
                       [Path(p) for p in args.paths])}
        try:
            n = baseline_mod.save(args.baseline, findings,
                                  scanned_paths=scanned)
        except (OSError, ValueError) as e:
            print(f"error: baseline {args.baseline}: {e}", file=sys.stderr)
            return 2
        print(f"wrote {n} baseline entr{'y' if n == 1 else 'ies'} to "
              f"{args.baseline}")
        return 0

    try:
        entries = [] if args.no_baseline \
            else baseline_mod.load(args.baseline)
    except (OSError, ValueError) as e:
        # a corrupt/mismatched baseline must be a clean usage
        # diagnostic, not a traceback
        print(f"error: baseline {args.baseline}: {e}", file=sys.stderr)
        return 2
    new, grandfathered = baseline_mod.apply(findings, entries)
    errors = sum(1 for f in new if f.severity == "error")
    warnings = len(new) - errors

    if args.fmt == "json":
        # one object, not a line stream: CI reads it with a single
        # json.load and renders per-record annotations
        print(json.dumps({
            "ok": not new,
            "errors": errors,
            "warnings": warnings,
            "baselined": len(grandfathered),
            "rules": len(REGISTRY) if select is None else len(select),
            "summary_ms": stats.get("summary_ms", 0.0),
            "link_ms": stats.get("link_ms", 0.0),
            "summaries_extracted": stats.get("summaries_extracted", 0),
            "summaries_cached": stats.get("summaries_cached", 0),
            "findings": [{
                "file": f.path, "line": f.line, "col": f.col,
                "rule": f.rule, "severity": f.severity,
                "family": getattr(REGISTRY.get(f.rule), "family",
                                  "framework"),
                "message": f.message,
            } for f in new],
        }, indent=2))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if grandfathered:
        print(f"({len(grandfathered)} baselined finding"
              f"{'' if len(grandfathered) == 1 else 's'} not shown; "
              "see --baseline)")
    if new:
        print(f"jaxlint: {errors} error(s), {warnings} warning(s)")
        return 1
    print(f"jaxlint: ok ({len(REGISTRY) if select is None else len(select)}"
          f" rules, {len(findings) - len(new)} baselined)")
    return 0
