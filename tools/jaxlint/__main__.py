import sys

from tools.jaxlint.cli import main

if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout was a pipe whose reader exited (jaxlint ... | head);
        # the findings already written made it through — not an error.
        sys.exit(0)
