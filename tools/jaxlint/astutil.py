"""Shared AST analysis: what counts as "jitted"/"hot" code.

Three rules (host-sync-in-hot-path, impure-jit, use-after-donate) need
the same answers — which callables end up traced by XLA, which of their
parameters are static, and which names a function binds locally — so
the answers live here once.

Two further layers serve the PR 10 rule families:

- collective analysis (``collective_axis_expr``, ``bound_axis_names``,
  ``resolve_axis_literal``) — which ``psum``/``pmean``/... calls name
  which mesh axes, and which axis names the module actually binds;
- class-scoped concurrency analysis (``class_infos`` → ``ClassInfo``) —
  per-class lock/queue/thread attribute typing, thread-target
  resolution through ``Thread(target=self._worker)`` and bare method
  references, the self-call closure that turns a thread target into the
  full worker-method set, and lexical held-lock regions
  (``lock_regions``).  This is the framework step that makes
  thread-safety rules cheap: a rule reads the ``ClassInfo`` instead of
  re-deriving who runs on which thread under which lock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

#: callables that hand their first argument to the XLA tracer
_JIT_NAMES = {"jit", "pjit", "cached_jit"}
_TRACING_WRAPPERS = {"shard_map", "checkpoint", "remat"}

#: attribute reads that touch only trace-time METADATA — static under
#: jit (shape specialization) and legal on a donated array (JAX frees
#: the buffer, the aval survives)
METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}


def metadata_only_names(nodes) -> Set[int]:
    """ids of Name nodes read solely as ``name.<metadata attr>``."""
    return {id(n.value) for n in nodes
            if isinstance(n, ast.Attribute)
            and n.attr in METADATA_ATTRS
            and isinstance(n.value, ast.Name)}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.experimental.shard_map`` -> that string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_reference(node: ast.AST) -> bool:
    """Does this expression name a jit-like compiler (``jax.jit``,
    bare ``jit``/``pjit``, any ``*.cached_jit``)?"""
    name = dotted_name(node)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    return leaf in _JIT_NAMES


def _is_partial(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "partial"


def _literal_ints(node: Optional[ast.AST]) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
    return out


def _literal_strs(node: Optional[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def jit_static_info(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static_argnums, static_argnames) literals from a jit-ish call."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _literal_ints(kw.value)
        elif kw.arg == "static_argnames":
            names |= _literal_strs(kw.value)
    return nums, names


def donated_argnums(call: ast.Call) -> Set[int]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _literal_ints(kw.value)
    return set()


def positional_params(fn: FunctionNode) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def dynamic_param_names(fn: FunctionNode, static_argnums: Set[int],
                        static_argnames: Set[str]) -> Set[str]:
    """Parameters that are TRACERS inside ``fn`` when jitted: positional
    params minus declared statics.  Keyword-only params are excluded —
    jitted code in this repo only ever passes them via
    ``static_argnames`` (a kw-only tracer would already be a bug the
    tracer itself reports)."""
    pos = positional_params(fn)
    out = {p for i, p in enumerate(pos) if i not in static_argnums}
    out -= static_argnames
    out -= {"self", "cls"}
    return out


@dataclass
class HotInfo:
    """Why a function is considered traced, and what we know about it."""
    reason: str
    static_argnums: Set[int] = field(default_factory=set)
    static_argnames: Set[str] = field(default_factory=set)


def _first_arg_names_of_tracing_calls(tree: ast.Module
                                      ) -> Dict[str, HotInfo]:
    """Names passed (by identifier) as the traced function of a jit-like
    or tracing-wrapper call anywhere in the module: ``cached_jit(step,
    ...)``, ``jax.jit(step_fn, ...)``, ``shard_map(round_fn, ...)``."""
    out: Dict[str, HotInfo] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        callee = dotted_name(node.func)
        if callee is None:
            continue
        leaf = callee.rsplit(".", 1)[-1]
        if leaf in _JIT_NAMES:
            reason = f"passed to {callee}"
        elif leaf in _TRACING_WRAPPERS:
            reason = f"wrapped by {callee}"
        else:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name):
            nums, names = jit_static_info(node)
            out[target.id] = HotInfo(reason, nums, names)
    return out


def _decorator_hotness(fn: FunctionNode) -> Optional[HotInfo]:
    for dec in fn.decorator_list:
        if is_jit_reference(dec):
            return HotInfo(f"decorated @{dotted_name(dec)}")
        if isinstance(dec, ast.Call):
            if is_jit_reference(dec.func):
                nums, names = jit_static_info(dec)
                return HotInfo(f"decorated @{dotted_name(dec.func)}(...)",
                               nums, names)
            if _is_partial(dec.func) and dec.args \
                    and is_jit_reference(dec.args[0]):
                nums, names = jit_static_info(dec)
                return HotInfo("decorated @partial(jit, ...)", nums, names)
    return None


def hot_functions(tree: ast.Module) -> Dict[FunctionNode, HotInfo]:
    """Every function the analyzer treats as XLA-traced ("hot"):

    - decorated with ``jax.jit`` / ``pjit`` / ``cached_jit`` (directly or
      via ``partial``);
    - passed by name as the traced argument of such a call (or of
      ``shard_map``/``checkpoint``/``remat``) anywhere in the module;
    - named ``*_step`` — the repo's step-function convention — unless the
      name starts with ``make_`` (factories RETURN steps, they aren't
      steps);
    - lexically nested inside a hot function (the tracer runs nested
      bodies too).
    """
    by_call = _first_arg_names_of_tracing_calls(tree)
    hot: Dict[FunctionNode, HotInfo] = {}

    def visit(node: ast.AST, inside_hot: bool) -> None:
        here_hot = inside_hot
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info: Optional[HotInfo] = None
            dec = _decorator_hotness(node)
            if dec is not None:
                info = dec
            elif node.name in by_call:
                info = by_call[node.name]
            elif node.name.endswith("_step") \
                    and not node.name.startswith("make_"):
                info = HotInfo("named *_step")
            elif inside_hot:
                info = HotInfo("nested in a traced function")
            if info is not None:
                hot[node] = info
                here_hot = True
            else:
                here_hot = False
        for child in ast.iter_child_nodes(node):
            visit(child, here_hot)

    visit(tree, False)
    return hot


def hot_roots(hot: Dict[FunctionNode, HotInfo]
              ) -> List[Tuple[FunctionNode, HotInfo]]:
    """Hot functions not nested inside another hot function — walking
    each root's whole subtree visits every hot body exactly once."""
    spans = [(fn.lineno, fn.end_lineno or fn.lineno) for fn in hot]
    roots = []
    for fn, info in hot.items():
        enclosed = any(s < fn.lineno and (fn.end_lineno or fn.lineno) <= e
                       for s, e in spans
                       if (s, e) != (fn.lineno, fn.end_lineno or fn.lineno))
        if not enclosed:
            roots.append((fn, info))
    return sorted(roots, key=lambda p: p[0].lineno)


def local_bindings(fn: FunctionNode) -> Set[str]:
    """Names ``fn`` binds locally: params plus every Store-context name
    in its own body (nested function bodies excluded — those are their
    own scopes)."""
    a = fn.args
    names: Set[str] = {p.arg for p in
                       a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    names.add(child.name)
                continue
            if isinstance(child, ast.Name) \
                    and isinstance(child.ctx, (ast.Store, ast.Del)):
                names.add(child.id)
            elif isinstance(child, ast.alias):
                names.add((child.asname or child.name).split(".")[0])
            elif isinstance(child, ast.ExceptHandler) and child.name:
                names.add(child.name)
            visit(child)

    visit(fn)
    return names


def enclosing_function_params(tree: ast.Module
                              ) -> Dict[ast.AST, FunctionNode]:
    """Map every node to its nearest enclosing function def (if any)."""
    owner: Dict[ast.AST, FunctionNode] = {}

    def visit(node: ast.AST, current: Optional[FunctionNode]) -> None:
        if current is not None:
            owner[node] = current
        nxt = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) else current
        for child in ast.iter_child_nodes(node):
            visit(child, nxt)

    visit(tree, None)
    return owner


# ---------------------------------------------------------------------------
# collective analysis (unbound-axis, collective-in-divergent-branch)
# ---------------------------------------------------------------------------

#: SPMD collectives whose axis argument names a mesh/pmap axis.  The
#: leaf spelling is what matters: ``lax.psum``, ``jax.lax.psum`` and the
#: repo's own ``parallel/collectives.py`` wrappers all end in these.
COLLECTIVE_FNS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                  "psum_scatter", "all_to_all", "ppermute", "pshuffle",
                  "axis_index"}

#: the package-wide axis vocabulary (parallel/mesh.py ALL_AXES).  An
#: axis literal outside this set must be bound by an explicit
#: pmap/vmap/shard_map ``axis_name`` somewhere in the module or the
#: collective is a silent no-op / NameError waiting for eager mode.
MESH_AXIS_VOCAB = {"data", "model", "pipe", "seq", "expert"}

#: callables whose ``axis_name``/``axis_names`` kwarg BINDS an axis
_AXIS_BINDERS = {"pmap", "vmap", "xmap", "shard_map", "Mesh",
                 "make_mesh"}


def is_collective_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name is not None \
        and name.rsplit(".", 1)[-1] in COLLECTIVE_FNS


def collective_axis_expr(call: ast.Call) -> Optional[ast.AST]:
    """The axis-NAME expression of a collective call: the ``axis_name``
    keyword if present, else the conventional positional slot
    (``psum(x, axis)`` — slot 1; ``axis_index(axis)`` — slot 0).  The
    integer ``axis=`` kwarg of ``all_gather`` is a gather DIMENSION,
    not an axis name, and is never returned."""
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    name = dotted_name(call.func) or ""
    leaf = name.rsplit(".", 1)[-1]
    pos = 0 if leaf == "axis_index" else 1
    if len(call.args) > pos:
        return call.args[pos]
    return None


def bound_axis_names(tree: ast.Module) -> Set[str]:
    """Axis names the module BINDS beyond the mesh vocabulary: literal
    ``axis_name=``/``axis_names=`` kwargs of pmap/vmap/xmap/shard_map/
    Mesh calls, plus literal Mesh axis tuples (``Mesh(devs, ("x",))``)."""
    out: Set[str] = set(MESH_AXIS_VOCAB)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None or name.rsplit(".", 1)[-1] not in _AXIS_BINDERS:
            continue
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                out |= _literal_strs(kw.value)
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("Mesh", "make_mesh") and len(node.args) > 1:
            out |= _literal_strs(node.args[1])
    return out


def _imported_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])
    return out


def _default_for_param(fn_or_lambda, name: str) -> Optional[ast.AST]:
    """The default-value expression for parameter ``name``, if any."""
    a = fn_or_lambda.args
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        if p.arg == name:
            return d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg == name and d is not None:
            return d
    return None


def resolve_axis_literal(expr: ast.AST, tree: ast.Module,
                         enclosing: List[ast.AST]) -> Optional[Set[str]]:
    """Best-effort resolution of an axis expression to its literal
    string value(s).  ``enclosing`` is the chain of function/lambda
    nodes around the call site, innermost last.  Returns None when the
    value cannot be known statically (a parameter without a literal
    default, an imported constant, an attribute read) — unresolvable
    axes are the CALLER's contract, not this module's."""
    strs = _literal_strs(expr)
    if strs:
        return strs
    if not isinstance(expr, ast.Name):
        return None
    name = expr.id
    if name in _imported_names(tree):
        return None                 # bound elsewhere; trust the exporter
    # innermost enclosing function that declares it as a parameter wins
    for fn in reversed(enclosing):
        a = getattr(fn, "args", None)
        if a is None:
            continue
        params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        if name in params:
            d = _default_for_param(fn, name)
            if d is not None:
                got = _literal_strs(d)
                return got or None
            return None
    # a single unambiguous literal binding VISIBLE from the call site:
    # module top-level plus the enclosing function scopes — a same-named
    # variable local to an unrelated function must not leak in
    values: Set[str] = set()
    opaque = False

    def _own_scope_nodes(body):
        stack = list(body)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue        # nested scope: its bindings aren't ours
            for c in ast.iter_child_nodes(n):
                stack.append(c)

    scopes = [list(tree.body)]
    scopes += [list(b) for b in (getattr(fn, "body", None)
                                 for fn in enclosing)
               if isinstance(b, list)]
    for body in scopes:
        for node in _own_scope_nodes(body):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == name
                            for t in node.targets):
                got = _literal_strs(node.value)
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                got = _literal_strs(node.value)
            elif isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                # ``for axis in ("data", "model")`` binds each element
                got = _literal_strs(node.iter)
            else:
                continue
            if got:
                values |= got
            else:
                opaque = True
    if values and not opaque:
        return values
    return None


def enclosing_chain(tree: ast.Module) -> Dict[int, List[ast.AST]]:
    """id(node) -> the function/lambda nodes lexically enclosing it,
    outermost first.  The collective rules resolve parameter defaults
    against this chain."""
    out: Dict[int, List[ast.AST]] = {}

    def visit(node: ast.AST, stack: List[ast.AST]) -> None:
        out[id(node)] = list(stack)
        nxt = stack + [node] if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
            else stack
        for child in ast.iter_child_nodes(node):
            visit(child, nxt)

    visit(tree, [])
    return out


# ---------------------------------------------------------------------------
# class-scoped concurrency analysis (unlocked-shared-mutation,
# blocking-under-lock, impure-signal-handler)
# ---------------------------------------------------------------------------

#: threading constructors, by leaf name, bucketed by how a rule must
#: treat an attribute built from them
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_RLOCK_CTORS = {"RLock"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_THREAD_CTORS = {"Thread", "Timer"}
_EVENT_CTORS = {"Event"}
_SEM_CTORS = {"Semaphore", "BoundedSemaphore"}


def _ctor_leaf(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        name = dotted_name(value.func)
        if name is not None:
            return name.rsplit(".", 1)[-1]
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (else None)."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@dataclass
class ClassInfo:
    """Everything the concurrency rules need to know about one class."""
    node: ast.ClassDef
    methods: Dict[str, FunctionNode] = field(default_factory=dict)
    lock_attrs: Set[str] = field(default_factory=set)
    rlock_attrs: Set[str] = field(default_factory=set)
    cond_attrs: Set[str] = field(default_factory=set)
    queue_attrs: Set[str] = field(default_factory=set)
    thread_attrs: Set[str] = field(default_factory=set)
    event_attrs: Set[str] = field(default_factory=set)
    sem_attrs: Set[str] = field(default_factory=set)
    #: method names passed as a Thread/Timer ``target=`` (directly or
    #: as a bare ``self.m`` reference handed to a spawner)
    thread_targets: Set[str] = field(default_factory=set)
    #: thread_targets closed under the self-call graph: every method a
    #: worker thread can reach via ``self.m()``
    worker_methods: Set[str] = field(default_factory=set)

    def owns_thread(self) -> bool:
        return bool(self.thread_targets)


def _self_call_edges(fn: FunctionNode) -> Set[str]:
    """Names of methods this method calls as ``self.m(...)``."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            attr = self_attr(node.func)
            if attr is not None:
                out.add(attr)
    return out


def class_infos(tree: ast.Module) -> List[ClassInfo]:
    """One ``ClassInfo`` per class in the module (nested classes
    included), with attribute typing seeded from every ``self.X = ctor``
    assignment anywhere in the class body and thread targets resolved
    through ``Thread(target=self.m)`` keyword and positional forms."""
    infos: List[ClassInfo] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        info = ClassInfo(cls)
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[stmt.name] = stmt
        buckets = ((_LOCK_CTORS, info.lock_attrs),
                   (_RLOCK_CTORS, info.rlock_attrs),
                   ({"Condition"}, info.cond_attrs),
                   (_QUEUE_CTORS, info.queue_attrs),
                   (_THREAD_CTORS, info.thread_attrs),
                   (_EVENT_CTORS, info.event_attrs),
                   (_SEM_CTORS, info.sem_attrs))
        for node in ast.walk(cls):
            # attribute typing: self.X = threading.Lock() / queue.Queue()
            if isinstance(node, ast.Assign):
                leaf = _ctor_leaf(node.value)
                if leaf is not None:
                    for tgt in node.targets:
                        attr = self_attr(tgt)
                        if attr is None:
                            continue
                        for ctors, bucket in buckets:
                            if leaf in ctors:
                                bucket.add(attr)
            # thread-target resolution: Thread(target=self.m, ...) /
            # Timer(interval, self.m) in ANY expression position —
            # assignments, comprehensions
            # (``[Thread(target=self._worker_loop) for ...]``),
            # bare ``Thread(...).start()`` chains.  The positional slot
            # is ctor-specific: Thread's args[0] is ``group`` and
            # Timer's is ``interval`` — the callable rides at index 1
            # for both (Timer spells its keyword ``function``).
            ctor = _ctor_leaf(node) if isinstance(node, ast.Call) else None
            if ctor in _THREAD_CTORS:
                target_kw = "function" if ctor == "Timer" else "target"
                for kw in node.keywords:
                    if kw.arg == target_kw:
                        attr = self_attr(kw.value)
                        if attr is not None:
                            info.thread_targets.add(attr)
                if len(node.args) > 1:
                    attr = self_attr(node.args[1])
                    if attr is not None:
                        info.thread_targets.add(attr)
        # close thread targets over the self-call graph
        edges = {name: _self_call_edges(fn)
                 for name, fn in info.methods.items()}
        seen = set()
        frontier = [t for t in info.thread_targets if t in info.methods]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            for callee in edges.get(m, ()):
                if callee in info.methods and callee not in seen:
                    frontier.append(callee)
        info.worker_methods = seen
        infos.append(info)
    return infos


def _with_lock_names(stmt: ast.With, lockish: Set[str],
                     local_locks: Set[str]) -> Set[str]:
    """Lock identifiers a ``with`` statement acquires: ``self.X`` where
    X is a known lock/condition attr (returned as ``"self.X"``), or a
    bare local name known to hold a lock (returned as-is)."""
    held: Set[str] = set()
    for item in stmt.items:
        expr = item.context_expr
        attr = self_attr(expr)
        if attr is not None and attr in lockish:
            held.add(f"self.{attr}")
        elif isinstance(expr, ast.Name) and expr.id in local_locks:
            held.add(expr.id)
    return held


def lock_regions(fn: FunctionNode, lockish: Set[str],
                 module_locks: Optional[Set[str]] = None
                 ) -> Dict[int, Set[str]]:
    """id(node) -> the set of lock identifiers lexically HELD there.

    ``lockish`` is the class's lock+condition attribute names;
    ``module_locks`` adds module-level lock variables (``with _LOCK:``).
    Nested function bodies are excluded — a closure defined under a
    lock does not run under it."""
    local_locks = set(module_locks or ())
    # locals assigned from a lock ctor inside this function body
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) \
                and _ctor_leaf(node.value) in (_LOCK_CTORS | {"Condition"}):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    local_locks.add(tgt.id)
    out: Dict[int, Set[str]] = {}

    def visit(node: ast.AST, held: Set[str], top: bool) -> None:
        out[id(node)] = set(held)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and not top:
            return                      # closures don't inherit the lock
        nxt = held
        if isinstance(node, ast.With):
            nxt = held | _with_lock_names(node, lockish, local_locks)
        for child in ast.iter_child_nodes(node):
            visit(child, nxt, False)

    visit(fn, set(), True)
    return out


def module_lock_names(tree: ast.Module) -> Set[str]:
    """Module-level ``NAME = threading.Lock()``-style bindings."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) \
                and _ctor_leaf(stmt.value) in (_LOCK_CTORS | {"Condition"}):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


# ---------------------------------------------------------------------------
# callable resolution (impure-signal-handler, donation factories)
# ---------------------------------------------------------------------------

def module_functions(tree: ast.Module) -> Dict[str, FunctionNode]:
    """Top-level (module-scope) function defs by name."""
    return {stmt.name: stmt for stmt in tree.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))}


def resolve_callable(expr: ast.AST, tree: ast.Module,
                     cls: Optional[ast.ClassDef]) -> Optional[FunctionNode]:
    """Resolve a callable REFERENCE to its definition, where statically
    possible: a bare name -> module-level def, ``self.m`` -> method of
    the enclosing class.  Anything else (imported callables, attributes
    of other objects) returns None."""
    if isinstance(expr, ast.Name):
        return module_functions(tree).get(expr.id)
    attr = self_attr(expr)
    if attr is not None and cls is not None:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name == attr:
                return stmt
    return None


def enclosing_class(tree: ast.Module) -> Dict[int, ast.ClassDef]:
    """id(node) -> nearest enclosing ClassDef."""
    out: Dict[int, ast.ClassDef] = {}

    def visit(node: ast.AST, current: Optional[ast.ClassDef]) -> None:
        if current is not None:
            out[id(node)] = current
        nxt = node if isinstance(node, ast.ClassDef) else current
        for child in ast.iter_child_nodes(node):
            visit(child, nxt)

    visit(tree, None)
    return out


# ---------------------------------------------------------------------------
# per-HOST divergence analysis (cluster-sync-in-divergent-branch,
# uncommitted-coordinator-write) — the host-level mirror of the
# per-replica taint above: the multi-host control plane
# (parallel/multihost.Cluster) is SPMD over PROCESSES the same way the
# mesh is SPMD over replicas, and the same class of bug applies — a
# rendezvous reachable only under state that differs per host (being
# the coordinator, a local exception, a local heartbeat finding) is a
# cross-host deadlock.
# ---------------------------------------------------------------------------

#: Cluster control-plane operations every member must reach together.
#: barrier/any_flag/gather/agree_lost_ids are KV rendezvous; shrink is
#: a generation change — a member that shrinks while a peer does not
#: namespaces itself away from every later rendezvous, which is the
#: same deadlock one hop later.
CLUSTER_SYNC_OPS = {"barrier", "any_flag", "gather", "agree_lost_ids",
                    "shrink"}

#: attribute reads that differ per host BY DEFINITION
HOST_DIVERGENT_ATTRS = {"is_coordinator"}
#: identity reads that differ per host when branched on
HOST_ID_ATTRS = {"process_id", "process_index", "member_rank"}
#: calls whose RESULT is a local (heartbeat/topology) finding — each
#: host's filesystem view of its peers, not an agreed value
HOST_FINDING_FNS = {"stale_members", "lost_device_ids"}

#: receivers a bare ``.gather(...)`` must hang off to count as a
#: Cluster op — ``gather`` alone is too generic (lax.gather is an
#: array op); the other four op names are unambiguous.
_CLUSTERISH_RECEIVERS = {"cl", "cluster", "survivors"}


def _receiver_name(func: ast.Attribute) -> Optional[str]:
    """Leaf identifier of a call receiver: ``cl.barrier`` -> ``cl``,
    ``self.cluster.barrier`` -> ``cluster``."""
    value = func.value
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    return None


def is_cluster_sync_call(call: ast.Call) -> bool:
    """Is this an ``X.barrier()``/``X.any_flag()``/... control-plane
    rendezvous?  ``gather`` additionally requires a cluster-ish
    receiver name so ``lax.gather`` never matches."""
    if not isinstance(call.func, ast.Attribute):
        return False
    op = call.func.attr
    if op not in CLUSTER_SYNC_OPS:
        return False
    if op == "gather":
        recv = _receiver_name(call.func)
        return recv is not None and (
            recv in _CLUSTERISH_RECEIVERS or recv.endswith("cluster"))
    return True


def host_divergent_read(expr: ast.AST, taint: Set[str]) -> Optional[str]:
    """First per-host-divergent thing the expression reads, as a human
    label — an ``.is_coordinator`` read, a process-identity read, a
    heartbeat finding, ``jax.process_index()``, or a name tainted by
    one of those — else None."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            if node.attr in HOST_DIVERGENT_ATTRS:
                return node.attr
            if node.attr in HOST_ID_ATTRS:
                return node.attr
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None:
                leaf = name.rsplit(".", 1)[-1]
                if leaf in HOST_FINDING_FNS or leaf == "process_index":
                    return f"{leaf}()"
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in taint:
            return node.id
    return None


def is_coordinator_test(expr: ast.AST) -> Optional[bool]:
    """Classify a branch test as a COORDINATOR gate: True for a test
    that can only be true ON the coordinator (``cl.is_coordinator``,
    possibly ``and``-composed), False for a test that can only be
    FALSE on the coordinator (``not cl.is_coordinator``, ``not (cl
    .is_coordinator and x)``), None for anything else.  Only the True
    classification propagates through ``and``: ``not cl.is_coordinator
    and fast`` is NOT a full non-coordinator gate — a non-coordinator
    with ``fast`` false fails the test too, so the false branch is not
    coordinator-only."""
    if isinstance(expr, ast.Attribute) and expr.attr == "is_coordinator":
        return True
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
        if is_coordinator_test(expr.operand) is True:
            return False
    if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.And):
        # test-true implies EVERY conjunct true, so one coordinator
        # conjunct makes the whole test coordinator-only; the False
        # classification must not propagate (see docstring)
        for v in expr.values:
            if is_coordinator_test(v) is True:
                return True
    return None


#: nodes that open a new scope — subtree walks stop at them
SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
               ast.Lambda)


def walk_no_scopes(node: ast.AST):
    """Walk a subtree without descending into nested function/class
    bodies — a nested def under a branch is not executed by it."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, SCOPE_NODES):
                stack.append(child)


def walk_own_body(fn):
    """Walk a function's OWN body, nested scopes excluded.  Unlike
    :func:`walk_no_scopes` starting from each statement, a nested def
    that is itself a direct body statement is yielded but never
    descended into."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, SCOPE_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


def can_exit_suite(stmts: List[ast.stmt]) -> bool:
    """Whether executing these statements can leave the ENCLOSING suite
    early: a ``return``/``raise`` anywhere in their own scope, or a
    ``break``/``continue`` not already absorbed by a loop nested
    WITHIN them (a ``break`` inside an inner ``for`` exits that loop,
    not the suite)."""
    def walk(node: ast.AST, in_loop: bool) -> bool:
        if isinstance(node, (ast.Return, ast.Raise)):
            return True
        if isinstance(node, (ast.Break, ast.Continue)):
            return not in_loop
        if isinstance(node, SCOPE_NODES):
            return False
        loop = in_loop or isinstance(node, (ast.For, ast.AsyncFor,
                                            ast.While))
        return any(walk(c, loop) for c in ast.iter_child_nodes(node))

    return any(walk(s, False) for s in stmts)


# ---------------------------------------------------------------------------
# PartitionSpec literal extraction (unknown-axis-in-partition-spec,
# spec-without-divisibility-guard)
# ---------------------------------------------------------------------------

#: the canonical axis-constant names ``parallel/mesh.py`` exports —
#: models spell their specs with these (``P(None, MODEL_AXIS)``), so
#: resolving them is resolving the repo's own vocabulary, not guessing
#: at a foreign import
AXIS_CONSTANT_NAMES = {"DATA_AXIS": "data", "MODEL_AXIS": "model",
                       "PIPE_AXIS": "pipe", "SEQ_AXIS": "seq",
                       "EXPERT_AXIS": "expert"}


def partition_spec_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to ``jax.sharding.PartitionSpec`` by import
    (``from jax.sharding import PartitionSpec as P`` — the repo-wide
    spelling).  ``PartitionSpec`` itself is always accepted."""
    out = {"PartitionSpec"}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "PartitionSpec":
                    out.add(a.asname or a.name)
    return out


def partition_spec_calls(tree: ast.Module) -> List[ast.Call]:
    """Every ``P(...)``/``PartitionSpec(...)`` call in the module."""
    aliases = partition_spec_aliases(tree)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        leaf = name.rsplit(".", 1)[-1]
        if leaf == "PartitionSpec" or name in aliases:
            out.append(node)
    return out


def partition_spec_entries(call: ast.Call) -> List[ast.AST]:
    """The axis-entry expressions of a PartitionSpec literal, with
    tuple entries flattened (``P(("data", "model"), None)`` yields both
    names).  Starred entries are skipped — unresolvable by design."""
    out: List[ast.AST] = []
    for arg in call.args:
        if isinstance(arg, ast.Starred):
            continue
        if isinstance(arg, (ast.Tuple, ast.List)):
            out.extend(e for e in arg.elts
                       if not isinstance(e, ast.Starred))
        else:
            out.append(arg)
    return out


def _axis_const_values(expr: ast.AST) -> Optional[Set[str]]:
    """Literal axis value(s) of an expression built from string
    constants, ``None``, the mesh axis-constant names, and ``IfExp``
    combinations of those (``MODEL_AXIS if deg > 1 else None``) — None
    when any part is opaque."""
    if isinstance(expr, ast.Constant):
        if expr.value is None:
            return set()
        if isinstance(expr.value, str):
            return {expr.value}
        return None
    if isinstance(expr, ast.Name) and expr.id in AXIS_CONSTANT_NAMES:
        return {AXIS_CONSTANT_NAMES[expr.id]}
    if isinstance(expr, ast.IfExp):
        a = _axis_const_values(expr.body)
        b = _axis_const_values(expr.orelse)
        if a is None or b is None:
            return None
        return a | b
    return None


def resolve_axis_entry(expr: ast.AST, tree: ast.Module,
                       enclosing: List[ast.AST]) -> Optional[Set[str]]:
    """Resolve one PartitionSpec entry to its axis-name value(s):
    ``None`` entries resolve to the empty set, string literals and the
    mesh axis constants to their names, a local alias (``m =
    MODEL_AXIS``, including through an ``IfExp``) through the enclosing
    scopes, anything else through :func:`resolve_axis_literal`.
    Returns None when statically unknowable."""
    direct = _axis_const_values(expr)
    if direct is not None:
        return direct
    if isinstance(expr, ast.Name):
        # a PARAMETER of an enclosing function shadows any same-named
        # module binding: the value is the caller's, so only the
        # param-default resolution of resolve_axis_literal applies
        for fn in enclosing:
            a = getattr(fn, "args", None)
            if a is not None and expr.id in {
                    p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}:
                return resolve_axis_literal(expr, tree, enclosing)
        # unambiguous alias binding visible from the call site (module
        # top level + enclosing function bodies, own-scope only)
        values: Set[str] = set()
        opaque = False
        scopes = [list(tree.body)]
        scopes += [list(b) for b in (getattr(fn, "body", None)
                                     for fn in enclosing)
                   if isinstance(b, list)]
        for body in scopes:
            stack = list(body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda, ast.ClassDef)):
                    continue
                stack.extend(ast.iter_child_nodes(node))
                if isinstance(node, ast.Assign) \
                        and any(isinstance(t, ast.Name) and t.id == expr.id
                                for t in node.targets):
                    got = _axis_const_values(node.value)
                    if got is None:
                        opaque = True
                    else:
                        values |= got
        if opaque:
            return None
        if values:
            return values
    return resolve_axis_literal(expr, tree, enclosing)


# ---------------------------------------------------------------------------
# key-expression purity (unstable-cache-key)
# ---------------------------------------------------------------------------

#: module roots whose calls vary per call/process — a compile-cache key
#: built from them NEVER matches an existing entry, so every dispatch
#: "misses" into a fresh executable and the zero-steady-state-compile
#: invariant dies silently
_KEY_IMPURE_ROOTS = {"time", "uuid", "random", "datetime"}
_KEY_IMPURE_BUILTINS = {"id", "hash", "object"}


def key_impurities(expr: ast.AST) -> List[Tuple[ast.AST, str]]:
    """(node, why) for every per-process/per-call subexpression of a
    compile-cache key or engine label:

    - ``id(x)``/``hash(x)``/``object()`` — per-process (``hash`` of a
      str is salted per interpreter, of an object is its id);
    - ``time.*()``/``uuid.*()``/``random.*()``/``datetime.*()`` calls;
    - f-string ``!r`` interpolation — ``repr`` of a non-literal object
      embeds its id;
    - f-string float interpolation (a float constant, or a float
      format spec like ``:.3f``) — floats carry measurement noise, and
      two "equal" keys differ in the last ulp.
    """
    out: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is None:
                continue
            if name in _KEY_IMPURE_BUILTINS:
                out.append((node, f"{name}() is per-process — a restarted "
                                  "(or second) process never hits the entry"))
            elif name.split(".", 1)[0] in _KEY_IMPURE_ROOTS \
                    and "." in name:
                out.append((node, f"{name}() varies per call/process"))
        elif isinstance(node, ast.FormattedValue):
            if node.conversion == ord("r"):
                out.append((node, "f-string !r interpolation renders an "
                                  "object repr (embeds its id)"))
            elif isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, float):
                out.append((node, "f-string interpolates a float literal"))
            elif isinstance(node.format_spec, ast.JoinedStr) \
                    and any(isinstance(v, ast.Constant)
                            and isinstance(v.value, str)
                            and v.value.rstrip("}").endswith(
                                ("f", "e", "g", "%"))
                            for v in node.format_spec.values):
                out.append((node, "f-string float-formats its value "
                                  "(measurement noise becomes key churn)"))
    return out


# ---------------------------------------------------------------------------
# worker-thread attribution across classes (host-sync-on-serving-worker)
# ---------------------------------------------------------------------------

def _annotation_class_name(ann: Optional[ast.AST]) -> Optional[str]:
    """``engine: DecodeEngine`` / ``engine: "DecodeEngine"`` -> the
    class name; subscripted/dotted annotations return None."""
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value
    return None


def _typed_self_attrs(info: ClassInfo,
                      module_classes: Set[str]) -> Dict[str, str]:
    """self.X -> class name, for attrs assigned from a ctor param whose
    annotation names a module class (``self.engine = engine`` with
    ``engine: DecodeEngine``) or directly from that class's ctor
    (``self.engine = DecodeEngine(...)``)."""
    out: Dict[str, str] = {}
    for fn in info.methods.values():
        ann_by_param: Dict[str, str] = {}
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            cls_name = _annotation_class_name(p.annotation)
            if cls_name is not None and cls_name in module_classes:
                ann_by_param[p.arg] = cls_name
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                attr = self_attr(tgt)
                if attr is None:
                    continue
                if isinstance(node.value, ast.Name) \
                        and node.value.id in ann_by_param:
                    out[attr] = ann_by_param[node.value.id]
                else:
                    leaf = _ctor_leaf(node.value)
                    if leaf in module_classes:
                        out[attr] = leaf
    return out


def _local_thread_targets(tree: ast.Module) -> List[FunctionNode]:
    """Nested/module function defs passed as a Thread/Timer target by
    BARE NAME (``Thread(target=loop)`` where ``loop`` is a local def —
    the lazy-worker idiom ``self.m`` resolution misses)."""
    owner = enclosing_function_params(tree)
    mod_fns = module_functions(tree)
    out: List[FunctionNode] = []
    seen: Set[int] = set()
    for node in ast.walk(tree):
        ctor = _ctor_leaf(node) if isinstance(node, ast.Call) else None
        if ctor not in _THREAD_CTORS:
            continue
        target_kw = "function" if ctor == "Timer" else "target"
        targets = [kw.value for kw in node.keywords
                   if kw.arg == target_kw]
        if len(node.args) > 1:
            targets.append(node.args[1])
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            # the def visible from the spawn site: same enclosing
            # function's own body, else a module-level def
            fn = owner.get(node)
            resolved = None
            if fn is not None:
                for stmt in ast.walk(fn):
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and stmt.name == t.id:
                        resolved = stmt
                        break
            if resolved is None:
                resolved = mod_fns.get(t.id)
            if resolved is not None and id(resolved) not in seen:
                seen.add(id(resolved))
                out.append(resolved)
    return out


def worker_attributed_functions(tree: ast.Module
                                ) -> List[Tuple[FunctionNode, str]]:
    """Every function the thread-target resolver attributes to a worker
    thread, with a human attribution label:

    - worker methods of thread-owning classes (``class_infos``
      closure over ``self.m()`` calls — the PR 10 resolver);
    - methods of OTHER module classes those workers drive through a
      typed attribute (``self.engine.advance()`` where ``self.engine``
      was assigned from a param annotated ``DecodeEngine`` — closed
      transitively over the target class's own self-call graph);
    - local/module function defs spawned by bare name
      (``Thread(target=loop)``).
    """
    infos = class_infos(tree)
    by_name = {info.node.name: info for info in infos}
    module_classes = set(by_name)
    out: List[Tuple[FunctionNode, str]] = []
    seen: Set[int] = set()
    # BFS over (class, method) pairs so cross-class hops close
    frontier: List[Tuple[ClassInfo, str, str]] = []
    for info in infos:
        for m in info.worker_methods:
            frontier.append((info, m,
                             f"worker thread of {info.node.name}"))
    typed = {info.node.name: _typed_self_attrs(info, module_classes)
             for info in infos}
    while frontier:
        info, mname, why = frontier.pop()
        fn = info.methods.get(mname)
        if fn is None or id(fn) in seen:
            continue
        seen.add(id(fn))
        out.append((fn, why))
        # cross-class edges: self.<attr>.m(...) with a typed attr
        attrs = typed.get(info.node.name, {})
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            recv = node.func.value
            attr = self_attr(recv)
            if attr is None or attr not in attrs:
                continue
            target_info = by_name.get(attrs[attr])
            if target_info is None:
                continue
            callee = node.func.attr
            if callee in target_info.methods:
                frontier.append(
                    (target_info, callee,
                     f"driven by {why} via self.{attr}.{callee}()"))
                # close over the target's own self-call graph
                sub = _self_call_edges(target_info.methods[callee])
                stack = list(sub)
                visited = set()
                while stack:
                    s = stack.pop()
                    if s in visited or s not in target_info.methods:
                        continue
                    visited.add(s)
                    frontier.append(
                        (target_info, s,
                         f"driven by {why} via self.{attr}.{callee}()"))
                    stack.extend(
                        _self_call_edges(target_info.methods[s]))
    for fn in _local_thread_targets(tree):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append((fn, f"thread target {fn.name!r} (by bare name)"))
    return sorted(out, key=lambda p: p[0].lineno)
