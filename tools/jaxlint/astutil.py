"""Shared AST analysis: what counts as "jitted"/"hot" code.

Three rules (host-sync-in-hot-path, impure-jit, use-after-donate) need
the same answers — which callables end up traced by XLA, which of their
parameters are static, and which names a function binds locally — so
the answers live here once.

Two further layers serve the PR 10 rule families:

- collective analysis (``collective_axis_expr``, ``bound_axis_names``,
  ``resolve_axis_literal``) — which ``psum``/``pmean``/... calls name
  which mesh axes, and which axis names the module actually binds;
- class-scoped concurrency analysis (``class_infos`` → ``ClassInfo``) —
  per-class lock/queue/thread attribute typing, thread-target
  resolution through ``Thread(target=self._worker)`` and bare method
  references, the self-call closure that turns a thread target into the
  full worker-method set, and lexical held-lock regions
  (``lock_regions``).  This is the framework step that makes
  thread-safety rules cheap: a rule reads the ``ClassInfo`` instead of
  re-deriving who runs on which thread under which lock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

#: callables that hand their first argument to the XLA tracer
_JIT_NAMES = {"jit", "pjit", "cached_jit"}
_TRACING_WRAPPERS = {"shard_map", "checkpoint", "remat"}

#: attribute reads that touch only trace-time METADATA — static under
#: jit (shape specialization) and legal on a donated array (JAX frees
#: the buffer, the aval survives)
METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}


def metadata_only_names(nodes) -> Set[int]:
    """ids of Name nodes read solely as ``name.<metadata attr>``."""
    return {id(n.value) for n in nodes
            if isinstance(n, ast.Attribute)
            and n.attr in METADATA_ATTRS
            and isinstance(n.value, ast.Name)}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.experimental.shard_map`` -> that string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_reference(node: ast.AST) -> bool:
    """Does this expression name a jit-like compiler (``jax.jit``,
    bare ``jit``/``pjit``, any ``*.cached_jit``)?"""
    name = dotted_name(node)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    return leaf in _JIT_NAMES


def _is_partial(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "partial"


def _literal_ints(node: Optional[ast.AST]) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
    return out


def _literal_strs(node: Optional[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def jit_static_info(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static_argnums, static_argnames) literals from a jit-ish call."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _literal_ints(kw.value)
        elif kw.arg == "static_argnames":
            names |= _literal_strs(kw.value)
    return nums, names


def donated_argnums(call: ast.Call) -> Set[int]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _literal_ints(kw.value)
    return set()


def positional_params(fn: FunctionNode) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def dynamic_param_names(fn: FunctionNode, static_argnums: Set[int],
                        static_argnames: Set[str]) -> Set[str]:
    """Parameters that are TRACERS inside ``fn`` when jitted: positional
    params minus declared statics.  Keyword-only params are excluded —
    jitted code in this repo only ever passes them via
    ``static_argnames`` (a kw-only tracer would already be a bug the
    tracer itself reports)."""
    pos = positional_params(fn)
    out = {p for i, p in enumerate(pos) if i not in static_argnums}
    out -= static_argnames
    out -= {"self", "cls"}
    return out


@dataclass
class HotInfo:
    """Why a function is considered traced, and what we know about it."""
    reason: str
    static_argnums: Set[int] = field(default_factory=set)
    static_argnames: Set[str] = field(default_factory=set)


def _first_arg_names_of_tracing_calls(tree: ast.Module
                                      ) -> Dict[str, HotInfo]:
    """Names passed (by identifier) as the traced function of a jit-like
    or tracing-wrapper call anywhere in the module: ``cached_jit(step,
    ...)``, ``jax.jit(step_fn, ...)``, ``shard_map(round_fn, ...)``."""
    out: Dict[str, HotInfo] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        callee = dotted_name(node.func)
        if callee is None:
            continue
        leaf = callee.rsplit(".", 1)[-1]
        if leaf in _JIT_NAMES:
            reason = f"passed to {callee}"
        elif leaf in _TRACING_WRAPPERS:
            reason = f"wrapped by {callee}"
        else:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name):
            nums, names = jit_static_info(node)
            out[target.id] = HotInfo(reason, nums, names)
    return out


def _decorator_hotness(fn: FunctionNode) -> Optional[HotInfo]:
    for dec in fn.decorator_list:
        if is_jit_reference(dec):
            return HotInfo(f"decorated @{dotted_name(dec)}")
        if isinstance(dec, ast.Call):
            if is_jit_reference(dec.func):
                nums, names = jit_static_info(dec)
                return HotInfo(f"decorated @{dotted_name(dec.func)}(...)",
                               nums, names)
            if _is_partial(dec.func) and dec.args \
                    and is_jit_reference(dec.args[0]):
                nums, names = jit_static_info(dec)
                return HotInfo("decorated @partial(jit, ...)", nums, names)
    return None


def hot_functions(tree: ast.Module) -> Dict[FunctionNode, HotInfo]:
    """Every function the analyzer treats as XLA-traced ("hot"):

    - decorated with ``jax.jit`` / ``pjit`` / ``cached_jit`` (directly or
      via ``partial``);
    - passed by name as the traced argument of such a call (or of
      ``shard_map``/``checkpoint``/``remat``) anywhere in the module;
    - named ``*_step`` — the repo's step-function convention — unless the
      name starts with ``make_`` (factories RETURN steps, they aren't
      steps);
    - lexically nested inside a hot function (the tracer runs nested
      bodies too).
    """
    by_call = _first_arg_names_of_tracing_calls(tree)
    hot: Dict[FunctionNode, HotInfo] = {}

    def visit(node: ast.AST, inside_hot: bool) -> None:
        here_hot = inside_hot
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info: Optional[HotInfo] = None
            dec = _decorator_hotness(node)
            if dec is not None:
                info = dec
            elif node.name in by_call:
                info = by_call[node.name]
            elif node.name.endswith("_step") \
                    and not node.name.startswith("make_"):
                info = HotInfo("named *_step")
            elif inside_hot:
                info = HotInfo("nested in a traced function")
            if info is not None:
                hot[node] = info
                here_hot = True
            else:
                here_hot = False
        for child in ast.iter_child_nodes(node):
            visit(child, here_hot)

    visit(tree, False)
    return hot


def hot_roots(hot: Dict[FunctionNode, HotInfo]
              ) -> List[Tuple[FunctionNode, HotInfo]]:
    """Hot functions not nested inside another hot function — walking
    each root's whole subtree visits every hot body exactly once."""
    spans = [(fn.lineno, fn.end_lineno or fn.lineno) for fn in hot]
    roots = []
    for fn, info in hot.items():
        enclosed = any(s < fn.lineno and (fn.end_lineno or fn.lineno) <= e
                       for s, e in spans
                       if (s, e) != (fn.lineno, fn.end_lineno or fn.lineno))
        if not enclosed:
            roots.append((fn, info))
    return sorted(roots, key=lambda p: p[0].lineno)


def local_bindings(fn: FunctionNode) -> Set[str]:
    """Names ``fn`` binds locally: params plus every Store-context name
    in its own body (nested function bodies excluded — those are their
    own scopes)."""
    a = fn.args
    names: Set[str] = {p.arg for p in
                       a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    names.add(child.name)
                continue
            if isinstance(child, ast.Name) \
                    and isinstance(child.ctx, (ast.Store, ast.Del)):
                names.add(child.id)
            elif isinstance(child, ast.alias):
                names.add((child.asname or child.name).split(".")[0])
            elif isinstance(child, ast.ExceptHandler) and child.name:
                names.add(child.name)
            visit(child)

    visit(fn)
    return names


def enclosing_function_params(tree: ast.Module
                              ) -> Dict[ast.AST, FunctionNode]:
    """Map every node to its nearest enclosing function def (if any)."""
    owner: Dict[ast.AST, FunctionNode] = {}

    def visit(node: ast.AST, current: Optional[FunctionNode]) -> None:
        if current is not None:
            owner[node] = current
        nxt = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) else current
        for child in ast.iter_child_nodes(node):
            visit(child, nxt)

    visit(tree, None)
    return owner


# ---------------------------------------------------------------------------
# collective analysis (unbound-axis, collective-in-divergent-branch)
# ---------------------------------------------------------------------------

#: SPMD collectives whose axis argument names a mesh/pmap axis.  The
#: leaf spelling is what matters: ``lax.psum``, ``jax.lax.psum`` and the
#: repo's own ``parallel/collectives.py`` wrappers all end in these.
COLLECTIVE_FNS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                  "psum_scatter", "all_to_all", "ppermute", "pshuffle",
                  "axis_index"}

#: the package-wide axis vocabulary (parallel/mesh.py ALL_AXES).  An
#: axis literal outside this set must be bound by an explicit
#: pmap/vmap/shard_map ``axis_name`` somewhere in the module or the
#: collective is a silent no-op / NameError waiting for eager mode.
MESH_AXIS_VOCAB = {"data", "model", "pipe", "seq", "expert"}

#: callables whose ``axis_name``/``axis_names`` kwarg BINDS an axis
_AXIS_BINDERS = {"pmap", "vmap", "xmap", "shard_map", "Mesh",
                 "make_mesh"}


def is_collective_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name is not None \
        and name.rsplit(".", 1)[-1] in COLLECTIVE_FNS


def collective_axis_expr(call: ast.Call) -> Optional[ast.AST]:
    """The axis-NAME expression of a collective call: the ``axis_name``
    keyword if present, else the conventional positional slot
    (``psum(x, axis)`` — slot 1; ``axis_index(axis)`` — slot 0).  The
    integer ``axis=`` kwarg of ``all_gather`` is a gather DIMENSION,
    not an axis name, and is never returned."""
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    name = dotted_name(call.func) or ""
    leaf = name.rsplit(".", 1)[-1]
    pos = 0 if leaf == "axis_index" else 1
    if len(call.args) > pos:
        return call.args[pos]
    return None


def bound_axis_names(tree: ast.Module) -> Set[str]:
    """Axis names the module BINDS beyond the mesh vocabulary: literal
    ``axis_name=``/``axis_names=`` kwargs of pmap/vmap/xmap/shard_map/
    Mesh calls, plus literal Mesh axis tuples (``Mesh(devs, ("x",))``)."""
    out: Set[str] = set(MESH_AXIS_VOCAB)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None or name.rsplit(".", 1)[-1] not in _AXIS_BINDERS:
            continue
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis_names"):
                out |= _literal_strs(kw.value)
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("Mesh", "make_mesh") and len(node.args) > 1:
            out |= _literal_strs(node.args[1])
    return out


def _imported_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                out.add((a.asname or a.name).split(".")[0])
    return out


def _default_for_param(fn_or_lambda, name: str) -> Optional[ast.AST]:
    """The default-value expression for parameter ``name``, if any."""
    a = fn_or_lambda.args
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        if p.arg == name:
            return d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg == name and d is not None:
            return d
    return None


def resolve_axis_literal(expr: ast.AST, tree: ast.Module,
                         enclosing: List[ast.AST]) -> Optional[Set[str]]:
    """Best-effort resolution of an axis expression to its literal
    string value(s).  ``enclosing`` is the chain of function/lambda
    nodes around the call site, innermost last.  Returns None when the
    value cannot be known statically (a parameter without a literal
    default, an imported constant, an attribute read) — unresolvable
    axes are the CALLER's contract, not this module's."""
    strs = _literal_strs(expr)
    if strs:
        return strs
    if not isinstance(expr, ast.Name):
        return None
    name = expr.id
    if name in _imported_names(tree):
        return None                 # bound elsewhere; trust the exporter
    # innermost enclosing function that declares it as a parameter wins
    for fn in reversed(enclosing):
        a = fn.args
        params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        if name in params:
            d = _default_for_param(fn, name)
            if d is not None:
                got = _literal_strs(d)
                return got or None
            return None
    # a single unambiguous literal binding VISIBLE from the call site:
    # module top-level plus the enclosing function scopes — a same-named
    # variable local to an unrelated function must not leak in
    values: Set[str] = set()
    opaque = False

    def _own_scope_nodes(body):
        stack = list(body)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue        # nested scope: its bindings aren't ours
            for c in ast.iter_child_nodes(n):
                stack.append(c)

    scopes = [list(tree.body)]
    scopes += [list(fn.body) for fn in enclosing if hasattr(fn, "body")
               and isinstance(fn.body, list)]
    for body in scopes:
        for node in _own_scope_nodes(body):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == name
                            for t in node.targets):
                got = _literal_strs(node.value)
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                got = _literal_strs(node.value)
            elif isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                # ``for axis in ("data", "model")`` binds each element
                got = _literal_strs(node.iter)
            else:
                continue
            if got:
                values |= got
            else:
                opaque = True
    if values and not opaque:
        return values
    return None


def enclosing_chain(tree: ast.Module) -> Dict[int, List[ast.AST]]:
    """id(node) -> the function/lambda nodes lexically enclosing it,
    outermost first.  The collective rules resolve parameter defaults
    against this chain."""
    out: Dict[int, List[ast.AST]] = {}

    def visit(node: ast.AST, stack: List[ast.AST]) -> None:
        out[id(node)] = list(stack)
        nxt = stack + [node] if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) \
            else stack
        for child in ast.iter_child_nodes(node):
            visit(child, nxt)

    visit(tree, [])
    return out


# ---------------------------------------------------------------------------
# class-scoped concurrency analysis (unlocked-shared-mutation,
# blocking-under-lock, impure-signal-handler)
# ---------------------------------------------------------------------------

#: threading constructors, by leaf name, bucketed by how a rule must
#: treat an attribute built from them
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_RLOCK_CTORS = {"RLock"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_THREAD_CTORS = {"Thread", "Timer"}
_EVENT_CTORS = {"Event"}
_SEM_CTORS = {"Semaphore", "BoundedSemaphore"}


def _ctor_leaf(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.Call):
        name = dotted_name(value.func)
        if name is not None:
            return name.rsplit(".", 1)[-1]
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (else None)."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@dataclass
class ClassInfo:
    """Everything the concurrency rules need to know about one class."""
    node: ast.ClassDef
    methods: Dict[str, FunctionNode] = field(default_factory=dict)
    lock_attrs: Set[str] = field(default_factory=set)
    rlock_attrs: Set[str] = field(default_factory=set)
    cond_attrs: Set[str] = field(default_factory=set)
    queue_attrs: Set[str] = field(default_factory=set)
    thread_attrs: Set[str] = field(default_factory=set)
    event_attrs: Set[str] = field(default_factory=set)
    sem_attrs: Set[str] = field(default_factory=set)
    #: method names passed as a Thread/Timer ``target=`` (directly or
    #: as a bare ``self.m`` reference handed to a spawner)
    thread_targets: Set[str] = field(default_factory=set)
    #: thread_targets closed under the self-call graph: every method a
    #: worker thread can reach via ``self.m()``
    worker_methods: Set[str] = field(default_factory=set)

    def owns_thread(self) -> bool:
        return bool(self.thread_targets)


def _self_call_edges(fn: FunctionNode) -> Set[str]:
    """Names of methods this method calls as ``self.m(...)``."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            attr = self_attr(node.func)
            if attr is not None:
                out.add(attr)
    return out


def class_infos(tree: ast.Module) -> List[ClassInfo]:
    """One ``ClassInfo`` per class in the module (nested classes
    included), with attribute typing seeded from every ``self.X = ctor``
    assignment anywhere in the class body and thread targets resolved
    through ``Thread(target=self.m)`` keyword and positional forms."""
    infos: List[ClassInfo] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        info = ClassInfo(cls)
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[stmt.name] = stmt
        buckets = ((_LOCK_CTORS, info.lock_attrs),
                   (_RLOCK_CTORS, info.rlock_attrs),
                   ({"Condition"}, info.cond_attrs),
                   (_QUEUE_CTORS, info.queue_attrs),
                   (_THREAD_CTORS, info.thread_attrs),
                   (_EVENT_CTORS, info.event_attrs),
                   (_SEM_CTORS, info.sem_attrs))
        for node in ast.walk(cls):
            # attribute typing: self.X = threading.Lock() / queue.Queue()
            if isinstance(node, ast.Assign):
                leaf = _ctor_leaf(node.value)
                if leaf is not None:
                    for tgt in node.targets:
                        attr = self_attr(tgt)
                        if attr is None:
                            continue
                        for ctors, bucket in buckets:
                            if leaf in ctors:
                                bucket.add(attr)
            # thread-target resolution: Thread(target=self.m, ...) /
            # Timer(interval, self.m) in ANY expression position —
            # assignments, comprehensions
            # (``[Thread(target=self._worker_loop) for ...]``),
            # bare ``Thread(...).start()`` chains.  The positional slot
            # is ctor-specific: Thread's args[0] is ``group`` and
            # Timer's is ``interval`` — the callable rides at index 1
            # for both (Timer spells its keyword ``function``).
            ctor = _ctor_leaf(node) if isinstance(node, ast.Call) else None
            if ctor in _THREAD_CTORS:
                target_kw = "function" if ctor == "Timer" else "target"
                for kw in node.keywords:
                    if kw.arg == target_kw:
                        attr = self_attr(kw.value)
                        if attr is not None:
                            info.thread_targets.add(attr)
                if len(node.args) > 1:
                    attr = self_attr(node.args[1])
                    if attr is not None:
                        info.thread_targets.add(attr)
        # close thread targets over the self-call graph
        edges = {name: _self_call_edges(fn)
                 for name, fn in info.methods.items()}
        seen = set()
        frontier = [t for t in info.thread_targets if t in info.methods]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            for callee in edges.get(m, ()):
                if callee in info.methods and callee not in seen:
                    frontier.append(callee)
        info.worker_methods = seen
        infos.append(info)
    return infos


def _with_lock_names(stmt: ast.With, lockish: Set[str],
                     local_locks: Set[str]) -> Set[str]:
    """Lock identifiers a ``with`` statement acquires: ``self.X`` where
    X is a known lock/condition attr (returned as ``"self.X"``), or a
    bare local name known to hold a lock (returned as-is)."""
    held: Set[str] = set()
    for item in stmt.items:
        expr = item.context_expr
        attr = self_attr(expr)
        if attr is not None and attr in lockish:
            held.add(f"self.{attr}")
        elif isinstance(expr, ast.Name) and expr.id in local_locks:
            held.add(expr.id)
    return held


def lock_regions(fn: FunctionNode, lockish: Set[str],
                 module_locks: Optional[Set[str]] = None
                 ) -> Dict[int, Set[str]]:
    """id(node) -> the set of lock identifiers lexically HELD there.

    ``lockish`` is the class's lock+condition attribute names;
    ``module_locks`` adds module-level lock variables (``with _LOCK:``).
    Nested function bodies are excluded — a closure defined under a
    lock does not run under it."""
    local_locks = set(module_locks or ())
    # locals assigned from a lock ctor inside this function body
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) \
                and _ctor_leaf(node.value) in (_LOCK_CTORS | {"Condition"}):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    local_locks.add(tgt.id)
    out: Dict[int, Set[str]] = {}

    def visit(node: ast.AST, held: Set[str], top: bool) -> None:
        out[id(node)] = set(held)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and not top:
            return                      # closures don't inherit the lock
        nxt = held
        if isinstance(node, ast.With):
            nxt = held | _with_lock_names(node, lockish, local_locks)
        for child in ast.iter_child_nodes(node):
            visit(child, nxt, False)

    visit(fn, set(), True)
    return out


def module_lock_names(tree: ast.Module) -> Set[str]:
    """Module-level ``NAME = threading.Lock()``-style bindings."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) \
                and _ctor_leaf(stmt.value) in (_LOCK_CTORS | {"Condition"}):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


# ---------------------------------------------------------------------------
# callable resolution (impure-signal-handler, donation factories)
# ---------------------------------------------------------------------------

def module_functions(tree: ast.Module) -> Dict[str, FunctionNode]:
    """Top-level (module-scope) function defs by name."""
    return {stmt.name: stmt for stmt in tree.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))}


def resolve_callable(expr: ast.AST, tree: ast.Module,
                     cls: Optional[ast.ClassDef]) -> Optional[FunctionNode]:
    """Resolve a callable REFERENCE to its definition, where statically
    possible: a bare name -> module-level def, ``self.m`` -> method of
    the enclosing class.  Anything else (imported callables, attributes
    of other objects) returns None."""
    if isinstance(expr, ast.Name):
        return module_functions(tree).get(expr.id)
    attr = self_attr(expr)
    if attr is not None and cls is not None:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name == attr:
                return stmt
    return None


def enclosing_class(tree: ast.Module) -> Dict[int, ast.ClassDef]:
    """id(node) -> nearest enclosing ClassDef."""
    out: Dict[int, ast.ClassDef] = {}

    def visit(node: ast.AST, current: Optional[ast.ClassDef]) -> None:
        if current is not None:
            out[id(node)] = current
        nxt = node if isinstance(node, ast.ClassDef) else current
        for child in ast.iter_child_nodes(node):
            visit(child, nxt)

    visit(tree, None)
    return out
