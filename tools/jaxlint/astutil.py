"""Shared AST analysis: what counts as "jitted"/"hot" code.

Three rules (host-sync-in-hot-path, impure-jit, use-after-donate) need
the same answers — which callables end up traced by XLA, which of their
parameters are static, and which names a function binds locally — so
the answers live here once.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

#: callables that hand their first argument to the XLA tracer
_JIT_NAMES = {"jit", "pjit", "cached_jit"}
_TRACING_WRAPPERS = {"shard_map", "checkpoint", "remat"}

#: attribute reads that touch only trace-time METADATA — static under
#: jit (shape specialization) and legal on a donated array (JAX frees
#: the buffer, the aval survives)
METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}


def metadata_only_names(nodes) -> Set[int]:
    """ids of Name nodes read solely as ``name.<metadata attr>``."""
    return {id(n.value) for n in nodes
            if isinstance(n, ast.Attribute)
            and n.attr in METADATA_ATTRS
            and isinstance(n.value, ast.Name)}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.experimental.shard_map`` -> that string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_reference(node: ast.AST) -> bool:
    """Does this expression name a jit-like compiler (``jax.jit``,
    bare ``jit``/``pjit``, any ``*.cached_jit``)?"""
    name = dotted_name(node)
    if name is None:
        return False
    leaf = name.rsplit(".", 1)[-1]
    return leaf in _JIT_NAMES


def _is_partial(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "partial"


def _literal_ints(node: Optional[ast.AST]) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
    return out


def _literal_strs(node: Optional[ast.AST]) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def jit_static_info(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static_argnums, static_argnames) literals from a jit-ish call."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _literal_ints(kw.value)
        elif kw.arg == "static_argnames":
            names |= _literal_strs(kw.value)
    return nums, names


def donated_argnums(call: ast.Call) -> Set[int]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _literal_ints(kw.value)
    return set()


def positional_params(fn: FunctionNode) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def dynamic_param_names(fn: FunctionNode, static_argnums: Set[int],
                        static_argnames: Set[str]) -> Set[str]:
    """Parameters that are TRACERS inside ``fn`` when jitted: positional
    params minus declared statics.  Keyword-only params are excluded —
    jitted code in this repo only ever passes them via
    ``static_argnames`` (a kw-only tracer would already be a bug the
    tracer itself reports)."""
    pos = positional_params(fn)
    out = {p for i, p in enumerate(pos) if i not in static_argnums}
    out -= static_argnames
    out -= {"self", "cls"}
    return out


@dataclass
class HotInfo:
    """Why a function is considered traced, and what we know about it."""
    reason: str
    static_argnums: Set[int] = field(default_factory=set)
    static_argnames: Set[str] = field(default_factory=set)


def _first_arg_names_of_tracing_calls(tree: ast.Module
                                      ) -> Dict[str, HotInfo]:
    """Names passed (by identifier) as the traced function of a jit-like
    or tracing-wrapper call anywhere in the module: ``cached_jit(step,
    ...)``, ``jax.jit(step_fn, ...)``, ``shard_map(round_fn, ...)``."""
    out: Dict[str, HotInfo] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        callee = dotted_name(node.func)
        if callee is None:
            continue
        leaf = callee.rsplit(".", 1)[-1]
        if leaf in _JIT_NAMES:
            reason = f"passed to {callee}"
        elif leaf in _TRACING_WRAPPERS:
            reason = f"wrapped by {callee}"
        else:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name):
            nums, names = jit_static_info(node)
            out[target.id] = HotInfo(reason, nums, names)
    return out


def _decorator_hotness(fn: FunctionNode) -> Optional[HotInfo]:
    for dec in fn.decorator_list:
        if is_jit_reference(dec):
            return HotInfo(f"decorated @{dotted_name(dec)}")
        if isinstance(dec, ast.Call):
            if is_jit_reference(dec.func):
                nums, names = jit_static_info(dec)
                return HotInfo(f"decorated @{dotted_name(dec.func)}(...)",
                               nums, names)
            if _is_partial(dec.func) and dec.args \
                    and is_jit_reference(dec.args[0]):
                nums, names = jit_static_info(dec)
                return HotInfo("decorated @partial(jit, ...)", nums, names)
    return None


def hot_functions(tree: ast.Module) -> Dict[FunctionNode, HotInfo]:
    """Every function the analyzer treats as XLA-traced ("hot"):

    - decorated with ``jax.jit`` / ``pjit`` / ``cached_jit`` (directly or
      via ``partial``);
    - passed by name as the traced argument of such a call (or of
      ``shard_map``/``checkpoint``/``remat``) anywhere in the module;
    - named ``*_step`` — the repo's step-function convention — unless the
      name starts with ``make_`` (factories RETURN steps, they aren't
      steps);
    - lexically nested inside a hot function (the tracer runs nested
      bodies too).
    """
    by_call = _first_arg_names_of_tracing_calls(tree)
    hot: Dict[FunctionNode, HotInfo] = {}

    def visit(node: ast.AST, inside_hot: bool) -> None:
        here_hot = inside_hot
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info: Optional[HotInfo] = None
            dec = _decorator_hotness(node)
            if dec is not None:
                info = dec
            elif node.name in by_call:
                info = by_call[node.name]
            elif node.name.endswith("_step") \
                    and not node.name.startswith("make_"):
                info = HotInfo("named *_step")
            elif inside_hot:
                info = HotInfo("nested in a traced function")
            if info is not None:
                hot[node] = info
                here_hot = True
            else:
                here_hot = False
        for child in ast.iter_child_nodes(node):
            visit(child, here_hot)

    visit(tree, False)
    return hot


def hot_roots(hot: Dict[FunctionNode, HotInfo]
              ) -> List[Tuple[FunctionNode, HotInfo]]:
    """Hot functions not nested inside another hot function — walking
    each root's whole subtree visits every hot body exactly once."""
    spans = [(fn.lineno, fn.end_lineno or fn.lineno) for fn in hot]
    roots = []
    for fn, info in hot.items():
        enclosed = any(s < fn.lineno and (fn.end_lineno or fn.lineno) <= e
                       for s, e in spans
                       if (s, e) != (fn.lineno, fn.end_lineno or fn.lineno))
        if not enclosed:
            roots.append((fn, info))
    return sorted(roots, key=lambda p: p[0].lineno)


def local_bindings(fn: FunctionNode) -> Set[str]:
    """Names ``fn`` binds locally: params plus every Store-context name
    in its own body (nested function bodies excluded — those are their
    own scopes)."""
    a = fn.args
    names: Set[str] = {p.arg for p in
                       a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    names.add(child.name)
                continue
            if isinstance(child, ast.Name) \
                    and isinstance(child.ctx, (ast.Store, ast.Del)):
                names.add(child.id)
            elif isinstance(child, ast.alias):
                names.add((child.asname or child.name).split(".")[0])
            elif isinstance(child, ast.ExceptHandler) and child.name:
                names.add(child.name)
            visit(child)

    visit(fn)
    return names


def enclosing_function_params(tree: ast.Module
                              ) -> Dict[ast.AST, FunctionNode]:
    """Map every node to its nearest enclosing function def (if any)."""
    owner: Dict[ast.AST, FunctionNode] = {}

    def visit(node: ast.AST, current: Optional[FunctionNode]) -> None:
        if current is not None:
            owner[node] = current
        nxt = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) else current
        for child in ast.iter_child_nodes(node):
            visit(child, nxt)

    visit(tree, None)
    return owner
