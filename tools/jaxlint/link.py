"""Linking pass — pass 2 of the jaxlint v4 two-pass analyzer.

Pass 1 (``summary.py``) extracted one export summary per module, purely
locally.  This module turns the pile of summaries into linked facts:

- the intra-repo **import graph** and each module's transitive
  dependency closure (what a result-cache entry must fingerprint);
- the **donation fixpoint**: a function donates param ``i`` if its own
  body does, or if it forwards ``i`` positionally into a callee whose
  summary donates that slot — closed iteratively, so import cycles
  converge (the closure is monotone) instead of recursing;
- the **purity fixpoint**: a cache-key helper is impure if its own body
  trips the ``key_impurities`` walker or any intra-repo callee is
  impure — same monotone iteration, with the originating reason
  threaded through for the finding message.

Cross-module rules subclass :class:`tools.jaxlint.core.Rule` with
``family = "cross-module"`` and ``requires_link = True``, and implement
``check_linked(tree, posix_path, ctx)``; without a :class:`LinkContext`
(single-module API calls, ``check_source`` in tests) they simply don't
run.  ``link_sources`` links a dict of in-memory fixture sources so
rule tests never touch disk.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.jaxlint import summary as summary_mod
from tools.jaxlint.summary import Resolver

#: fixpoint iteration cap — a safety net only; both closures are
#: monotone over finite sets, so they converge in <= |functions| rounds
_MAX_ROUNDS = 64


def _split_ref(ref: str) -> Tuple[str, str]:
    mod, _, name = ref.partition(":")
    return mod, name


def resolve(summaries: Dict[str, Dict]) -> Dict[str, Dict]:
    """Close the raw summaries into LINKED summaries (a new dict; the
    inputs are not mutated).  Adds, per function:

    - ``donates_linked`` — ``donates`` closed over donation forwards;
    - ``key_pure`` / ``key_impure_reason`` — the purity verdict and a
      human reason carrying provenance through call chains.
    """
    linked: Dict[str, Dict] = {}
    for mod, s in summaries.items():
        fns = {}
        for name, f in s.get("functions", {}).items():
            g = dict(f)
            g["donates_linked"] = sorted(f.get("donates", []))
            impure = list(f.get("key_impure", []))
            g["key_pure"] = not impure
            g["key_impure_reason"] = impure[0] if impure else None
            fns[name] = g
        t = dict(s)
        t["functions"] = fns
        linked[mod] = t

    def fn_entry(ref: str) -> Optional[Dict]:
        mod, name = _split_ref(ref)
        s = linked.get(mod)
        if s is None:
            return None
        return s.get("functions", {}).get(name)

    for _ in range(_MAX_ROUNDS):
        changed = False
        for s in linked.values():
            for f in s.get("functions", {}).values():
                # donation closure
                donates: Set[int] = set(f["donates_linked"])
                for param_idx, ref, pos in f.get("donation_forwards", []):
                    callee = fn_entry(ref)
                    if callee is not None \
                            and pos in callee["donates_linked"] \
                            and param_idx not in donates:
                        donates.add(param_idx)
                if donates != set(f["donates_linked"]):
                    f["donates_linked"] = sorted(donates)
                    changed = True
                # purity closure
                if f["key_pure"]:
                    for ref in f.get("key_calls", []):
                        callee = fn_entry(ref)
                        if callee is not None and not callee["key_pure"]:
                            f["key_pure"] = False
                            why = callee["key_impure_reason"] \
                                or "transitively impure"
                            mod, name = _split_ref(ref)
                            f["key_impure_reason"] = \
                                f"calls {name}() ({mod}): {why}"
                            changed = True
                            break
        if not changed:
            break
    return linked


def import_graph(summaries: Dict[str, Dict]) -> Dict[str, List[str]]:
    """module -> its direct intra-repo imports (only edges into modules
    we hold a summary for — stdlib/jax edges were already filtered by
    the resolver in pass 1)."""
    return {mod: sorted(d for d in s.get("imports", [])
                        if d in summaries)
            for mod, s in summaries.items()}


def dependency_closure(graph: Dict[str, List[str]]
                       ) -> Dict[str, List[str]]:
    """module -> its TRANSITIVE dependency set (sorted, self excluded).
    Iterative worklist, so cycles terminate trivially.  This is the set
    whose summary fingerprints a result-cache entry must record: a
    change anywhere in the closure can change what linking concludes
    about the importer."""
    out: Dict[str, List[str]] = {}
    for mod in graph:
        seen: Set[str] = set()
        frontier = list(graph.get(mod, []))
        while frontier:
            d = frontier.pop()
            if d in seen or d == mod:
                continue
            seen.add(d)
            frontier.extend(graph.get(d, []))
        out[mod] = sorted(seen)
    return out


@dataclass
class LinkContext:
    """Everything a cross-module rule needs at one file's check time."""
    module: str
    is_package: bool
    resolver: Resolver
    #: LINKED summaries (post-:func:`resolve`) for every module in the
    #: run's closure — rules index it by the callee's dotted module
    summaries: Dict[str, Dict] = field(default_factory=dict)

    def bindings(self, tree: ast.Module
                 ) -> Dict[str, Tuple[str, Optional[str]]]:
        return summary_mod.import_bindings(
            tree, self.module, self.is_package, self.resolver)

    def function_summary(self, module: str, name: str) -> Optional[Dict]:
        s = self.summaries.get(module)
        if s is None:
            return None
        return s.get("functions", {}).get(name)

    def class_protocol(self, module: str, cls: str) -> Optional[Dict]:
        s = self.summaries.get(module)
        if s is None:
            return None
        return s.get("classes", {}).get(cls)


def link_sources(sources: Dict[str, str]
                 ) -> Dict[str, Tuple[ast.Module, LinkContext]]:
    """Link a dict of in-memory sources (posix relpath -> source), for
    tests: ``{"pkg/a.py": ..., "pkg/b.py": ...}`` behaves like a tree
    rooted at a virtual root.  Returns path -> (tree, LinkContext)."""
    modules: Dict[str, Tuple[str, ast.Module, bool]] = {}
    names: Set[str] = set()
    for path, src in sources.items():
        parts = path.split("/")
        is_pkg = parts[-1] == "__init__.py"
        mod_parts = parts[:-1] if is_pkg \
            else parts[:-1] + [parts[-1][:-3]]
        mod = ".".join(mod_parts)
        names.add(mod)
        # parents are importable packages too (``from pkg import dep``)
        for i in range(1, len(mod_parts)):
            names.add(".".join(mod_parts[:i]))
        modules[path] = (mod, ast.parse(src, filename=path), is_pkg)
    resolver = Resolver(roots=[], known=names)
    raw: Dict[str, Dict] = {}
    for path, (mod, tree, is_pkg) in modules.items():
        raw[mod] = summary_mod.extract(tree, mod, is_pkg, resolver)
    linked = resolve(raw)
    out: Dict[str, Tuple[ast.Module, LinkContext]] = {}
    for path, (mod, tree, is_pkg) in modules.items():
        out[path] = (tree, LinkContext(module=mod, is_package=is_pkg,
                                       resolver=resolver,
                                       summaries=linked))
    return out


def check_linked_sources(sources: Dict[str, str],
                         rules: Optional[List] = None
                         ) -> Dict[str, List]:
    """Convenience for tests: link ``sources`` and run the full rule
    set (or ``rules``) over each file WITH its LinkContext.  Returns
    path -> findings."""
    from tools.jaxlint.core import check_source
    ctxs = link_sources(sources)
    return {path: check_source(src, path, rules=rules,
                               link_ctx=ctxs[path][1])
            for path, src in sources.items()}
