"""collective-in-divergent-branch: replicas must reach collectives
together.

A collective (psum/pmean/all_gather/...) is a RENDEZVOUS: every replica
along the axis must dispatch it, in the same order, or the mesh
deadlocks — the class of bug the PR 5 sharded fit designed around by
deciding guard skips from COLLECTIVE values ("so every replica skips
identically and replicated params never diverge",
parallel/sharded_fit.py).  The dangerous shape is a Python ``if`` (or
``while``) on a PER-REPLICA traced value with a collective reachable
under it: each shard branches on its own data, some enter the psum and
some don't, and the program hangs on hardware after passing every
single-device test.

The check is a linear taint pass over each hot function (see
``astutil.hot_functions``): tracer parameters are per-replica; a value
assigned from a per-replica value stays per-replica; a value that
flowed THROUGH a collective is replica-uniform again (psum launders the
taint — branching on a post-psum score is exactly the sanctioned
pattern).  A branch whose test reads a tainted name flags every
collective call in its subtree.  Reads via metadata attributes
(``.shape``/``.ndim``/...) are trace-static and never taint.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Set

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _walk_no_scopes(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a subtree without descending into nested function/class
    bodies — a nested def under the branch is not executed by it."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, _SCOPES):
                stack.append(child)


def _tainted_read(expr: ast.AST, taint: Set[str]) -> Optional[str]:
    """First tainted name the expression reads as a VALUE (metadata
    attribute reads are trace-static and don't count)."""
    nodes = list(ast.walk(expr))
    metadata = astutil.metadata_only_names(nodes)
    for node in nodes:
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in taint and id(node) not in metadata:
            return node.id
    return None


def _contains_collective(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and astutil.is_collective_call(n)
               for n in _walk_no_scopes(expr))


def _target_names(target: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(target)
            if isinstance(n, ast.Name)
            and isinstance(n.ctx, (ast.Store, ast.Del))}


@register
class CollectiveInDivergentBranchRule(Rule):
    name = "collective-in-divergent-branch"
    severity = "error"
    family = "collective"
    description = ("collective reachable under a branch on a per-replica "
                   "traced value — replicas diverge and the mesh "
                   "deadlocks at the rendezvous")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        hot = astutil.hot_functions(tree)
        for fn, info in hot.items():
            taint = astutil.dynamic_param_names(
                fn, info.static_argnums, info.static_argnames)
            # one flag per collective call even when branches nest
            seen: Set[int] = set()
            yield from self._scan(fn.body, set(taint), posix_path, seen)

    def _scan(self, stmts: List[ast.stmt], taint: Set[str],
              path: str, seen: Set[int]) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, _SCOPES):
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                hit = _tainted_read(stmt.test, taint)
                if hit is not None:
                    yield from self._flag_collectives(stmt, hit, path,
                                                      seen)
                for group in (stmt.body, stmt.orelse):
                    yield from self._scan(group, taint, path, seen)
            elif isinstance(stmt, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                names: Set[str] = set()
                for t in targets:
                    names |= _target_names(t)
                value = stmt.value
                if value is not None and _contains_collective(value):
                    # flowed through a collective: replica-uniform again
                    taint -= names
                elif (value is not None
                      and _tainted_read(value, taint) is not None) \
                        or (isinstance(stmt, ast.AugAssign)
                            and names & taint):
                    # an AugAssign taints only when the prior target or
                    # the operand was already per-replica — a
                    # trace-static counter (``depth += 1``) stays clean
                    taint |= names
                else:
                    taint -= names
            elif isinstance(stmt, ast.For):
                if _tainted_read(stmt.iter, taint) is not None:
                    taint |= _target_names(stmt.target)
                for group in (stmt.body, stmt.orelse):
                    yield from self._scan(group, taint, path, seen)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._scan(stmt.body, taint, path, seen)
            elif isinstance(stmt, ast.Try):
                for group in ([stmt.body, stmt.orelse, stmt.finalbody]
                              + [h.body for h in stmt.handlers]):
                    yield from self._scan(group, taint, path, seen)
            elif isinstance(stmt, ast.Match):
                hit = _tainted_read(stmt.subject, taint)
                for case in stmt.cases:
                    if hit is not None:
                        for finding in self._flag_stmts(
                                case.body, hit, path, seen,
                                line=stmt.lineno):
                            yield finding
                    yield from self._scan(case.body, taint, path, seen)

    def _flag_collectives(self, branch: ast.stmt, tainted_name: str,
                          path: str, seen: Set[int]) -> Iterator[Finding]:
        yield from self._flag_stmts(
            list(branch.body) + list(getattr(branch, "orelse", [])),
            tainted_name, path, seen, line=branch.lineno)

    def _flag_stmts(self, stmts: List[ast.stmt], tainted_name: str,
                    path: str, seen: Set[int],
                    line: Optional[int] = None) -> Iterator[Finding]:
        for stmt in stmts:
            for node in _walk_no_scopes(stmt):
                if isinstance(node, ast.Call) \
                        and astutil.is_collective_call(node) \
                        and id(node) not in seen:
                    seen.add(id(node))
                    leaf = (astutil.dotted_name(node.func) or "collective"
                            ).rsplit(".", 1)[-1]
                    at = f" at line {line}" if line is not None else ""
                    yield self.finding(
                        path, node,
                        f"{leaf}() reached under a branch{at} on "
                        f"per-replica value {tainted_name!r} — replicas "
                        "that skip the branch never join the collective; "
                        "decide with a post-psum (collective) value or "
                        "jnp.where instead")
