"""unstable-cache-key: compile-cache keys must be stable values.

The whole serving/training stack hangs its zero-steady-state-compile
invariant (CI-gated since PR 6, extended through PRs 7/11/12/14) on
one property: two logically identical programs build EQUAL engine
keys, so the second caller hits the first caller's executable.  Every
shipped key is a canonical conf JSON, a ``mesh_signature``, a quant
mode — stable across calls, threads, and processes.  A key (or engine
label, which becomes the per-label compile counter the gates assert
on) built from

- ``id(x)``/``hash(x)``/``object()`` — per-process identity (``hash``
  of a str is salted per interpreter),
- ``time.*``/``uuid.*``/``random.*``/``datetime.*`` calls,
- f-string ``!r`` interpolation (an object repr embeds its id) or
  float interpolation (measurement noise becomes key churn)

never matches an existing entry: every dispatch "misses" into a fresh
trace+XLA compile, and the zero-compile gates read a compile storm as
traffic.  This is lexically detectable at the ``cached_jit``/
``get_or_build`` call site, so it is a rule
(``astutil.key_impurities`` is the shared purity walker).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_ENGINE_CALLS = {"cached_jit", "get_or_build"}


@register
class UnstableCacheKeyRule(Rule):
    name = "unstable-cache-key"
    severity = "error"
    family = "compile-stability"
    description = ("compile-cache key/engine label built from id()/"
                   "time/uuid/random or !r/float f-string interpolation "
                   "— every dispatch misses into a fresh XLA compile, "
                   "silently defeating the zero-compile invariant")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.dotted_name(node.func)
            if name is None:
                continue
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in _ENGINE_CALLS:
                continue
            key_exprs: List[ast.AST] = []
            if leaf == "get_or_build" and node.args:
                key_exprs.append(node.args[0])
            for kw in node.keywords:
                if kw.arg in ("key", "label"):
                    key_exprs.append(kw.value)
            for expr in key_exprs:
                for bad, why in astutil.key_impurities(expr):
                    yield self.finding(
                        posix_path, bad,
                        f"unstable compile-cache key for {leaf}(): {why} "
                        "— the key never matches an existing entry, so "
                        "steady state recompiles per call; key on stable "
                        "identity (conf JSON, mesh_signature, mode "
                        "strings) instead")
