"""unknown-axis-in-partition-spec: every PartitionSpec axis must be in
the mesh vocabulary.

GSPMD never validates axis NAMES at spec-construction time: a
``P(None, "modle")`` builds fine and fails only when a
``NamedSharding`` over a real mesh finally consumes it — deep inside
``jax.device_put``/compilation, on the pod, with an error that names
neither the spec literal nor the file it came from.  The repo fixes
its axis vocabulary package-wide (``parallel/mesh.ALL_AXES``:
``data``/``model``/``pipe``/``seq``/``expert``) and spells specs with
the exported constants (``P(None, MODEL_AXIS)``), so a spec literal
can be validated statically — this is PR 12's weight-layout contract
(``transformer.shard_specs`` and friends) as a machine check.

Every entry of a ``P(...)``/``PartitionSpec(...)`` literal in the
model zoo, the sharded-fit builders, and the decode engine is resolved
(string literal, mesh axis constant, local alias, parameter default —
the PR 10 axis-literal resolver plus the constant layer) and flagged
when it resolves outside the vocabulary and nothing in the module
binds it.  Unresolvable entries (a parameter without a default, a
foreign import) stay silent — the caller's contract, as ever.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_SCOPE_HINTS = ("models/", "parallel/sharded_fit.py", "serving/decode.py")


@register
class UnknownAxisInPartitionSpecRule(Rule):
    name = "unknown-axis-in-partition-spec"
    severity = "error"
    family = "sharding-layout"
    description = ("PartitionSpec entry resolves to an axis name outside "
                   "the parallel/mesh vocabulary — the layout fails at "
                   "device_put on the pod, not at build time")

    def applies_to(self, posix_path: str) -> bool:
        return any(h in posix_path for h in _SCOPE_HINTS)

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        calls = astutil.partition_spec_calls(tree)
        if not calls:
            return
        bound = astutil.bound_axis_names(tree)
        chain = astutil.enclosing_chain(tree)
        for call in calls:
            for entry in astutil.partition_spec_entries(call):
                values = astutil.resolve_axis_entry(
                    entry, tree, chain.get(id(entry), []))
                if values is None:
                    continue
                loose = sorted(v for v in values if v not in bound)
                if loose:
                    yield self.finding(
                        posix_path, call,
                        f"PartitionSpec names axis {loose[0]!r}, which is "
                        "not in the parallel/mesh vocabulary "
                        f"({', '.join(sorted(astutil.MESH_AXIS_VOCAB))}) "
                        "and nothing in this module binds — the spec "
                        "builds fine and fails at device_put/compile "
                        "time on the target mesh")
