"""unlocked-shared-mutation: worker-thread state needs its lock.

Every threaded component in this repo (DynamicBatcher, the PR 7
ContinuousBatcher, the PR 8 AsyncCheckpointer, DistributedRunner)
follows one discipline, hardened twice in review: state shared between
the background worker and the public API is mutated only under the
instance's lock/Condition.  A mutation that skips the lock is the
classic intermittent bug — a request list appended mid-``pop``, a
``_placed`` map resized during iteration — that passes every test until
a production burst hits the window.

The rule is class-scoped and seeded from the class's own lock fields
(``self._lock = threading.Lock()`` / ``Condition()`` — see
``astutil.class_infos``): in a class that starts a thread on one of its
methods (``Thread(target=self._worker)``, resolved transitively through
``self.m()`` calls) AND owns a lock, any ``self.*`` attribute mutated
both from the worker-method set and from a non-worker (publicly
callable) method must hold a COMMON lock at every mutation site.
``__init__`` is exempt (it runs before the thread exists), as are the
lock/semaphore fields themselves.  Thread-safe primitives' own methods
(``Event.set``, ``Queue.put``) are not attribute mutations and never
flag.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

#: in-place container mutation methods (same vocabulary as impure-jit)
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "add", "discard", "setdefault", "sort", "reverse", "popitem"}

#: methods that run before/after the thread's lifetime by construction
_EXEMPT_METHODS = {"__init__", "__new__", "__del__"}

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _walk_own_body(fn) -> List[ast.AST]:
    """Nodes of the method's own body, nested function/class scopes
    excluded — a closure's thread affinity is not the method's."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, _SCOPES):
                stack.append(child)
    return out


def _mutated_attr(node: ast.AST) -> Optional[str]:
    """The ``self.X`` attribute this node mutates, if any."""
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in targets:
            attr = astutil.self_attr(tgt)
            if attr is not None:
                return attr
            if isinstance(tgt, ast.Subscript):
                attr = astutil.self_attr(tgt.value)
                if attr is not None:
                    return attr
    elif isinstance(node, ast.Delete):
        for tgt in node.targets:
            attr = astutil.self_attr(tgt)
            if attr is None and isinstance(tgt, ast.Subscript):
                attr = astutil.self_attr(tgt.value)
            if attr is not None:
                return attr
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        return astutil.self_attr(node.func.value)
    return None


#: one mutation site: (attr, method name, node, locks held)
Site = Tuple[str, str, ast.AST, Set[str]]


@register
class UnlockedSharedMutationRule(Rule):
    name = "unlocked-shared-mutation"
    severity = "error"
    family = "concurrency"
    description = ("self.* attribute mutated from both a thread worker "
                   "and a public method without a common held lock")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for info in astutil.class_infos(tree):
            if not info.owns_thread():
                continue
            lockish = info.lock_attrs | info.cond_attrs
            if not lockish:
                continue        # lock-free by design; nothing to seed from
            exempt_attrs = (lockish | info.sem_attrs)
            sites: List[Site] = []
            for mname, fn in info.methods.items():
                if mname in _EXEMPT_METHODS:
                    continue
                regions = astutil.lock_regions(fn, lockish)
                for node in _walk_own_body(fn):
                    attr = _mutated_attr(node)
                    if attr is None or attr in exempt_attrs:
                        continue
                    sites.append((attr, mname, node,
                                  regions.get(id(node), set())))
            yield from self._judge(info, sites, posix_path)

    def _judge(self, info: astutil.ClassInfo, sites: List[Site],
               posix_path: str) -> Iterable[Finding]:
        by_attr: Dict[str, List[Site]] = {}
        for site in sites:
            by_attr.setdefault(site[0], []).append(site)
        for attr, group in sorted(by_attr.items()):
            worker = [s for s in group
                      if s[1] in info.worker_methods]
            public = [s for s in group
                      if s[1] not in info.worker_methods]
            if not worker or not public:
                continue        # single-threaded access pattern
            common = set.intersection(*(s[3] for s in group))
            if common:
                continue
            # the lock most sites already hold is the intended guard;
            # flag the sites that miss it (all of them when none locks)
            counts = Counter(l for s in group for l in s[3])
            guard = counts.most_common(1)[0][0] if counts else None
            wm = sorted({s[1] for s in worker})[0]
            pm = sorted({s[1] for s in public})[0]
            for _, mname, node, held in group:
                if guard is not None and guard in held:
                    continue
                want = guard or "self." + sorted(
                    info.lock_attrs | info.cond_attrs)[0]
                yield self.finding(
                    posix_path, node,
                    f"'self.{attr}' is mutated from worker method "
                    f"'{wm}' (thread target of "
                    f"{info.node.name}) and public method '{pm}' but "
                    f"this site in '{mname}' does not hold {want} — "
                    "take the lock (or annotate why the race is benign)")
