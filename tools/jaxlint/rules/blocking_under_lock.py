"""blocking-under-lock: never park a thread while holding a lock.

The drain/close contracts of the serving and checkpoint stacks
(PR 7 ``ContinuousBatcher.close``, PR 8 ``AsyncCheckpointer.
wait_until_finished``) were each review-hardened into the same shape:
release the instance lock FIRST, then block.  A ``Future.result()``,
``Thread.join()``, ``block_until_ready()``, semaphore acquire or
blocking queue ``get``/``put`` executed while a lock is held stalls
every thread that needs that lock for as long as the wait lasts — and
when the waited-on thread itself needs the lock to make progress
(worker books a metric under it, producer appends under it), the stall
is a deadlock.  The same goes for re-entering a NON-re-entrant
``threading.Lock``/``Condition`` already held by the enclosing ``with``.

Lock regions are lexical (``astutil.lock_regions``): ``with self._lock``
/ ``with self._cv`` on the class's known lock/Condition fields, local
lock variables, and module-level locks.  ``Condition.wait``/``wait_for``
on the HELD condition is the sanctioned pattern (it releases the lock
while parked) and never flags.

``join``/``get``/``put``/``acquire`` are receiver-typed (thread attrs,
queue attrs, lock/semaphore fields) so ``", ".join(...)`` and friends
never false-positive.  ``.result()`` is DELIBERATELY receiver-agnostic:
futures cross so many hands (returned, stored, passed) that static
receiver typing would miss most of them, the method name has no common
non-blocking homonym in this codebase, and a rare benign hit is exactly
what the inline suppression-with-reason exists for.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _local_ctor_names(fn, ctors: Set[str]) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            name = astutil.dotted_name(node.value.func)
            if name is not None and name.rsplit(".", 1)[-1] in ctors:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def _kw_false(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name and isinstance(kw.value, ast.Constant)
               and kw.value.value is False for kw in call.keywords)


@register
class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    severity = "error"
    family = "concurrency"
    description = ("blocking wait (.result()/.join()/block_until_ready/"
                   "queue get/put/semaphore) or re-entrant acquire "
                   "inside a held-lock region")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        module_locks = astutil.module_lock_names(tree)
        infos = astutil.class_infos(tree)
        checked: Set[int] = set()
        for info in infos:
            lockish = info.lock_attrs | info.cond_attrs
            for fn in info.methods.values():
                checked.add(id(fn))
                yield from self._check_fn(fn, info, lockish,
                                          module_locks, posix_path)
        for fn in astutil.module_functions(tree).values():
            if id(fn) not in checked:
                yield from self._check_fn(fn, None, set(), module_locks,
                                          posix_path)

    def _check_fn(self, fn, info: Optional[astutil.ClassInfo],
                  lockish: Set[str], module_locks: Set[str],
                  posix_path: str) -> Iterable[Finding]:
        regions = astutil.lock_regions(fn, lockish, module_locks)
        local_threads = _local_ctor_names(fn, {"Thread", "Timer"})
        local_queues = _local_ctor_names(
            fn, {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"})
        for node in ast.walk(fn):
            held = regions.get(id(node))
            if not held:
                continue
            if isinstance(node, ast.With):
                yield from self._check_reentry(node, info, lockish,
                                               module_locks, held,
                                               posix_path)
            if not isinstance(node, ast.Call):
                continue
            yield from self._check_call(node, info, held, local_threads,
                                        local_queues, posix_path)

    def _check_reentry(self, node: ast.With, info, lockish: Set[str],
                       module_locks: Set[str], held: Set[str],
                       posix_path: str) -> Iterable[Finding]:
        """``with self._lock`` nested under an already-held ``with
        self._lock`` — instant deadlock unless the lock is an RLock."""
        rlocks = info.rlock_attrs if info is not None else set()
        for item in node.items:
            expr = item.context_expr
            attr = astutil.self_attr(expr)
            key = None
            if attr is not None and attr in lockish:
                key = f"self.{attr}"
                if attr in rlocks:
                    continue
            elif isinstance(expr, ast.Name) and expr.id in module_locks:
                key = expr.id
            if key is not None and key in held:
                yield self.finding(
                    posix_path, node,
                    f"re-entrant `with {key}` while {key} is already "
                    "held — threading.Lock/Condition are not re-entrant; "
                    "this deadlocks the thread against itself")

    def _check_call(self, node: ast.Call, info, held: Set[str],
                    local_threads: Set[str], local_queues: Set[str],
                    posix_path: str) -> Iterable[Finding]:
        func = node.func
        locks = " + ".join(sorted(held))
        if not isinstance(func, ast.Attribute):
            return
        attr = func.attr
        recv = func.value
        recv_attr = astutil.self_attr(recv)
        if attr == "result":
            yield self.finding(
                posix_path, node,
                f".result() while holding {locks} — the future may need "
                "that lock (or its worker) to resolve; wait after "
                "releasing")
        elif attr == "block_until_ready":
            yield self.finding(
                posix_path, node,
                f"block_until_ready() while holding {locks} — a device "
                "sync under a lock stalls every thread that needs it")
        elif attr == "join":
            thread_recv = (recv_attr is not None and info is not None
                           and recv_attr in info.thread_attrs) \
                or (isinstance(recv, ast.Name) and recv.id in local_threads)
            if thread_recv:
                yield self.finding(
                    posix_path, node,
                    f"Thread.join() while holding {locks} — if the "
                    "worker needs the lock to finish, this never returns")
        elif attr in ("get", "put"):
            queue_recv = (recv_attr is not None and info is not None
                          and recv_attr in info.queue_attrs) \
                or (isinstance(recv, ast.Name) and recv.id in local_queues)
            if queue_recv and not _kw_false(node, "block"):
                yield self.finding(
                    posix_path, node,
                    f"blocking queue .{attr}() while holding {locks} — "
                    "use the _nowait form or move the wait outside the "
                    "lock")
        elif attr == "acquire":
            sem_recv = recv_attr is not None and info is not None \
                and recv_attr in info.sem_attrs
            lock_key = f"self.{recv_attr}" if recv_attr is not None else \
                (recv.id if isinstance(recv, ast.Name) else None)
            if sem_recv and not _kw_false(node, "blocking"):
                yield self.finding(
                    posix_path, node,
                    f"semaphore .acquire() while holding {locks} — the "
                    "release may need the held lock; backpressure waits "
                    "belong outside it")
            elif lock_key is not None and lock_key in held \
                    and not (info is not None and recv_attr is not None
                             and recv_attr in info.rlock_attrs):
                yield self.finding(
                    posix_path, node,
                    f"re-entrant .acquire() of already-held {lock_key} — "
                    "threading.Lock is not re-entrant")
        elif attr == "wait" and recv_attr is not None and info is not None \
                and recv_attr in info.event_attrs:
            yield self.finding(
                posix_path, node,
                f"Event.wait() while holding {locks} — the setter may "
                "need the lock; wait after releasing")
