"""uncommitted-coordinator-write: manifest/gc writes in cluster
protocol code must be coordinator-gated.

The PR 13 cluster-commit protocol hangs its crash-safety on WHO writes
what: every member lands its own data shards, but the manifest (the
commit marker) is written by the COORDINATOR alone, after a barrier
proved every member's bytes durable — and ``_gc`` runs on the
coordinator alone, because two members sweeping the same directory
race each other's deletes (``runtime/checkpoint.py::_save_cluster``).
A manifest/gc/commit-marker write that ANY member can reach either
commits a snapshot some member hasn't finished writing (torn commit) or
double-writes the marker with divergent contents (whichever member's
``os.replace`` lands last wins).

Scope: functions that themselves perform a cluster rendezvous (a
``barrier``/``any_flag``/``gather``/``agree_lost_ids``/``shrink``
call) — i.e. code actively inside a cross-host protocol.  The
single-process ``save()`` path calls the same ``_commit_manifest``
with no cluster in sight and stays out of scope by construction.  A
write is GATED when it sits in the true branch of an
``is_coordinator`` test (or the false branch of its negation, or
after a ``if not cl.is_coordinator: return`` early exit, or in the
coordinator arm of a ternary).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_SCOPES = astutil.SCOPE_NODES

#: call leaf names that write the commit protocol's shared artifacts
_WRITE_LEAVES = {"_commit_manifest", "commit_manifest", "_gc",
                 "gc_checkpoints"}


def _is_write_call(call: ast.Call) -> bool:
    name = astutil.dotted_name(call.func)
    return name is not None and name.rsplit(".", 1)[-1] in _WRITE_LEAVES


@register
class UncommittedCoordinatorWriteRule(Rule):
    name = "uncommitted-coordinator-write"
    severity = "error"
    family = "distributed-protocol"
    description = ("manifest/gc/commit-marker write in cluster protocol "
                   "code not gated on is_coordinator — every member "
                   "writes it, racing the commit")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not any(isinstance(n, ast.Call)
                       and astutil.is_cluster_sync_call(n)
                       for n in ast.walk(node)):
                continue
            yield from self._scan(node.body, posix_path, gated=False)

    def _scan(self, stmts: List[ast.stmt], path: str,
              gated: bool) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, _SCOPES):
                continue
            if isinstance(stmt, ast.If):
                coord = astutil.is_coordinator_test(stmt.test)
                yield from self._scan(stmt.body, path,
                                      gated or coord is True)
                yield from self._scan(stmt.orelse, path,
                                      gated or coord is False)
                if coord is False and astutil.can_exit_suite(stmt.body):
                    # ``if not cl.is_coordinator: return`` — the rest of
                    # this suite runs on the coordinator only
                    gated = True
                continue
            groups = self._subgroups(stmt)
            if groups:
                for group in groups:
                    yield from self._scan(group, path, gated)
                continue
            for node in astutil.walk_no_scopes(stmt):
                if isinstance(node, ast.Call) and _is_write_call(node) \
                        and not gated \
                        and not self._in_coordinator_ifexp(stmt, node):
                    leaf = (astutil.dotted_name(node.func) or "write"
                            ).rsplit(".", 1)[-1]
                    yield self.finding(
                        path, node,
                        f"{leaf}() in cluster protocol code without an "
                        "is_coordinator gate — every member writes the "
                        "commit artifact, so a member that hasn't landed "
                        "its data can still commit (torn snapshot) and "
                        "concurrent writers race the marker; gate the "
                        "write (not the barrier) on cl.is_coordinator")

    @staticmethod
    def _subgroups(stmt: ast.stmt) -> List[List[ast.stmt]]:
        if isinstance(stmt, (ast.For, ast.While, ast.With, ast.AsyncWith)):
            return [stmt.body] + ([stmt.orelse]
                                  if getattr(stmt, "orelse", None) else [])
        if isinstance(stmt, ast.Try):
            return [stmt.body, stmt.orelse, stmt.finalbody] \
                + [h.body for h in stmt.handlers]
        if isinstance(stmt, ast.Match):
            return [c.body for c in stmt.cases]
        return []

    @staticmethod
    def _in_coordinator_ifexp(stmt: ast.stmt, call: ast.Call) -> bool:
        """Is ``call`` inside the coordinator arm of a ternary
        (``files = save(...) if cl.is_coordinator else {}``)?"""
        for node in astutil.walk_no_scopes(stmt):
            if not isinstance(node, ast.IfExp):
                continue
            coord = astutil.is_coordinator_test(node.test)
            if coord is None:
                continue
            arm = node.body if coord else node.orelse
            if any(n is call for n in ast.walk(arm)):
                return True
        return False
