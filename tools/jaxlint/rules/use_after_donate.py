"""use-after-donate: a buffer passed in a donated position is dead.

``donate_argnums`` lets XLA reuse an argument's HBM for the output —
after the call the Python variable still points at a DELETED buffer, and
touching it raises (best case) or reads garbage on some backends (worst
case).  The engine's contract (runtime/compile_cache.py docstring) is
copy-on-entry at API boundaries; this rule catches the scope-local
version of the bug the copy guards exist for:

    step = cached_jit(body, donate_argnums=(0,))
    out = step(params, batch)
    loss(params)            # <-- params' buffer was donated away

Tracked donating callables (literal ``donate_argnums`` only):
- names assigned from ``cached_jit(...)`` / ``jax.jit(...)`` — at module
  scope or locally;
- functions decorated ``@partial(jax.jit, donate_argnums=...)``.

A read is flagged when the donated argument was a plain name and that
name is read again later in the same scope before being rebound.  The
scan is lexical (statement order, assignment targets kill the taint), so
loop-carried rebinding like ``x = step(x)`` stays clean; reads hidden
behind back-edges of a loop are out of scope for a linter.  Two
refinements: metadata reads (``.shape``/``.ndim``/``.dtype``/``.size``)
of a donated name are legal — JAX deletes the buffer, not the aval —
and a rebind inside any branch that does not already enclose the
donating call (a sibling ``if``, a deeper ``if``, a loop body) is
conditional, so it does not clear the taint; rebind on the call's own
unconditional continuation (or suppress with a reason) to satisfy the
rule.  Reads in branches mutually exclusive with the call's (the other
arm of its ``if``/``match``) are never flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

ScopeNode = ast.AST  # Module | FunctionDef | AsyncFunctionDef


#: per-scope entry: name -> (donated positions, the BINDING statement)
DonationTable = Dict[str, Tuple[Set[int], ast.stmt]]


def _donation_tables(tree: ast.Module) -> Dict[ScopeNode, DonationTable]:
    """Per-scope tables (the Module node is a scope like any other):
    name -> (donated argument positions, binding statement).  The
    binding statement lets the checker ignore entries superseded by a
    later rebind of the same name."""
    tbls: Dict[ScopeNode, DonationTable] = {}

    def scan(scope: ScopeNode) -> None:
        table = tbls.setdefault(scope, {})
        for stmt, _depth in _scope_statements(scope):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call) \
                    and astutil.is_jit_reference(stmt.value.func):
                donated = astutil.donated_argnums(stmt.value)
                if donated:
                    table[stmt.targets[0].id] = (donated, stmt)
            elif isinstance(stmt, ast.ClassDef):
                # descend so METHOD bodies get their own local tables
                # (class-level donating assigns are only callable via
                # attribute access, which this rule doesn't track)
                scan(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in stmt.decorator_list:
                    if isinstance(dec, ast.Call):
                        jit_like = astutil.is_jit_reference(dec.func) or (
                            astutil.dotted_name(dec.func) is not None
                            and astutil.dotted_name(dec.func)
                            .rsplit(".", 1)[-1] == "partial"
                            and dec.args
                            and astutil.is_jit_reference(dec.args[0]))
                        if jit_like:
                            donated = astutil.donated_argnums(dec)
                            if donated:
                                table[stmt.name] = (donated, stmt)
                scan(stmt)

    scan(tree)
    return tbls


def _binds_name(stmt: ast.stmt, name: str) -> bool:
    """Does this statement (re)bind ``name`` in ITS OWN scope?  Nested
    function/class bodies are separate scopes and don't count (a def's
    NAME binding does)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return stmt.name == name
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Name) and node.id == name \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        if isinstance(node, ast.alias):
            if (node.asname or node.name).split(".")[0] == name:
                return True
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)
    return False


def _child_stmt_groups(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """The statement lists nested one level under ``stmt`` (if/loop
    bodies, else branches, try handlers/finally, match case bodies)."""
    groups: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        group = getattr(stmt, field, None)
        if group:
            groups.append(list(group))
    for handler in getattr(stmt, "handlers", []) or []:
        groups.append(list(handler.body))
    for case in getattr(stmt, "cases", []) or []:
        groups.append(list(case.body))
    return groups


def _subtree_statements(stmts: List[ast.stmt]) -> Set[int]:
    """ids of every statement nested anywhere under ``stmts``."""
    out: Set[int] = set()
    stack = list(stmts)
    while stack:
        s = stack.pop()
        out.add(id(s))
        if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            for group in _child_stmt_groups(s):
                stack.extend(group)
    return out


def _ancestor_map(body: List[ast.stmt]) -> Dict[int, Set[int]]:
    """id(stmt) -> ids of the compound statements enclosing it (within
    this scope).  A later write KILLS the donation taint only when its
    ancestors are a subset of the call's — i.e. it sits on the call's
    own continuation, not inside some new branch that may not run."""
    out: Dict[int, Set[int]] = {}

    def build(stmts: List[ast.stmt], stack: Set[int]) -> None:
        for s in stmts:
            out[id(s)] = set(stack)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for group in _child_stmt_groups(s):
                build(group, stack | {id(s)})

    build(body, set())
    return out


def _exclusive_with(body: List[ast.stmt], call_stmt: ast.stmt) -> Set[int]:
    """ids of statements in branches MUTUALLY EXCLUSIVE with the one
    holding ``call_stmt``: the other arm of every enclosing ``if`` and
    the other cases of every enclosing ``match``.  A read there runs
    only when the donating call didn't, so it must not be flagged."""
    excluded: Set[int] = set()

    def visit(stmts: List[ast.stmt]) -> bool:
        found = False
        for s in stmts:
            if s is call_stmt:
                found = True
                continue
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            groups = _child_stmt_groups(s)
            hits = [visit(g) for g in groups]
            if any(hits):
                found = True
                if isinstance(s, (ast.If, ast.Match)):
                    for g, hit in zip(groups, hits):
                        if not hit:
                            excluded.update(_subtree_statements(g))
        return found

    visit(body)
    return excluded


def _scope_statements(scope: ScopeNode
                      ) -> Iterator[Tuple[ast.stmt, int]]:
    """All (statement, nesting depth) of ``scope`` in source order,
    descending into compound statements but NOT into nested
    function/class scopes.  Depth 0 is the scope's own body; each
    if/for/while/try body adds one."""
    body = scope.body if hasattr(scope, "body") else []
    stack: List[Tuple[ast.stmt, int]] = [(s, 0) for s in reversed(body)]
    while stack:
        stmt, depth = stack.pop()
        yield stmt, depth
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        children: List[ast.stmt] = []
        for group in _child_stmt_groups(stmt):
            children.extend(group)
        stack.extend((c, depth + 1) for c in reversed(children))


def _immediate_walk(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Walk ``stmt`` at THIS-statement granularity.

    Child statements of compound statements (for/if/while/try bodies)
    are their own entries in the scope statement list, so descending
    into them here would attribute their reads/calls to the header
    statement too.  Nested function/class bodies, by contrast, are NOT
    separate entries — a nested def is one statement whose closure
    captures names — so once a scope node is entered the walk covers
    its whole subtree.
    """
    scope_types = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Lambda)
    stack: List[Tuple[ast.AST, bool]] = [(stmt, False)]
    while stack:
        node, inside_scope = stack.pop()
        yield node
        entering = inside_scope or isinstance(node, scope_types)
        for child in ast.iter_child_nodes(node):
            if not entering and isinstance(child, ast.stmt):
                continue
            stack.append((child, entering))


def _name_events(stmt: ast.stmt, name: str) -> Tuple[bool, bool]:
    """(reads, writes) of ``name`` attributable to this statement; a
    nested def capturing a dead buffer counts as a read, a bare
    metadata access (``name.shape`` — JAX frees the buffer, not the
    aval) does not."""
    nodes = list(_immediate_walk(stmt))
    metadata = astutil.metadata_only_names(nodes)
    reads = writes = False
    for node in nodes:
        if isinstance(node, ast.Name) and node.id == name:
            if isinstance(node.ctx, ast.Load):
                reads = reads or id(node) not in metadata
            else:
                writes = True
    return reads, writes


@register
class UseAfterDonateRule(Rule):
    name = "use-after-donate"
    severity = "error"
    description = ("variable read after being passed in a donated "
                   "argument position (its buffer is deleted)")
    #: whether to also check the direct call form
    #: ``cached_jit(f, donate_argnums=...)(x)`` — the subclassing
    #: donation-across-collective rule turns this off (the base rule
    #: already owns that form; double-reporting helps nobody)
    direct_form = True

    def _build_tables(self, tree: ast.Module) -> Dict[ScopeNode,
                                                      DonationTable]:
        """Hook: per-scope donation tables.  Subclasses (the
        collective-factory form) supply their own construction and
        inherit the read-after-donate dataflow unchanged."""
        return _donation_tables(tree)

    def _message(self, name: str, label: str, line: int) -> str:
        return (f"{name!r} read after being donated to {label}() at "
                f"line {line} — the buffer is deleted; copy "
                "before the call or rebind from the result")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        tbls = self._build_tables(tree)
        scopes: List[ScopeNode] = [tree]
        scopes.extend(n for n in ast.walk(tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)))
        for scope in scopes:
            local = tbls.get(scope, {})
            outer = tbls.get(tree, {}) if scope is not tree else {}
            # even with no tracked names the scope can contain the
            # direct form cached_jit(f, donate_argnums=...)(x)
            yield from self._check_scope(scope, local, outer, posix_path)

    def _check_scope(self, scope: ScopeNode, local: DonationTable,
                     outer: DonationTable, posix_path: str
                     ) -> Iterator[Finding]:
        stmts = list(_scope_statements(scope))
        shadowed = astutil.local_bindings(scope) \
            if not isinstance(scope, ast.Module) else set()
        for i, (stmt, _depth) in enumerate(stmts):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for call in _immediate_walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                if isinstance(call.func, ast.Name):
                    donated = self._resolve_donation(
                        call.func.id, stmts, i, stmt, local, outer,
                        shadowed)
                    if donated is None:
                        continue
                    label = call.func.id
                elif self.direct_form and isinstance(call.func, ast.Call) \
                        and astutil.is_jit_reference(call.func.func):
                    # direct form: cached_jit(f, donate_argnums=...)(x)
                    donated = astutil.donated_argnums(call.func)
                    label = astutil.dotted_name(call.func.func) or "jit"
                    if not donated:
                        continue
                else:
                    continue
                for pos, arg in enumerate(call.args):
                    if pos in donated and isinstance(arg, ast.Name):
                        yield from self._track(
                            stmts, i, stmt, call, label, arg.id,
                            posix_path)

    @staticmethod
    def _resolve_donation(name: str, stmts, call_idx: int,
                          call_stmt: ast.stmt, local: DonationTable,
                          outer: DonationTable, shadowed: Set[str]
                          ) -> Optional[Set[int]]:
        """Donated positions for calling ``name`` here, or None.

        The table entry only holds if its binding statement is the LAST
        binding of the name before the call — a rebind to a plain
        callable supersedes it.  A module-level entry applies only when
        the name is not shadowed by any local binding (params included;
        Python scoping makes the name local for the whole function the
        moment it's assigned anywhere in it).
        """
        entry = local.get(name)
        if entry is not None:
            donated, binder = entry
            last = None
            for stmt, _d in stmts[:call_idx]:
                if _binds_name(stmt, name):
                    last = stmt
            return donated if last is binder else None
        entry = outer.get(name)
        if entry is not None and name not in shadowed:
            return entry[0]
        return None

    def _track(self, stmts: List[Tuple[ast.stmt, int]], call_idx: int,
               call_stmt: ast.stmt, call: ast.Call, label: str,
               name: str, posix_path: str) -> Iterator[Finding]:
        # reads in the SAME statement that evaluate after the call —
        # Python evaluates left to right, so a load positioned past the
        # call's end (``out = step(params, b) + loss(params)``) reads
        # the already-deleted buffer even though the statement may also
        # rebind the name afterwards
        call_end = (call.end_lineno or call.lineno,
                    call.end_col_offset or call.col_offset)
        in_call = {id(n) for n in ast.walk(call)}
        stmt_nodes = list(_immediate_walk(call_stmt))
        metadata = astutil.metadata_only_names(stmt_nodes)
        for node in stmt_nodes:
            if isinstance(node, ast.Name) and node.id == name \
                    and isinstance(node.ctx, ast.Load) \
                    and id(node) not in in_call \
                    and id(node) not in metadata \
                    and (node.lineno, node.col_offset) > call_end:
                yield self.finding(posix_path, node,
                                   self._message(name, label, call.lineno))
                return
        # the donating statement's own assignment targets rebind the name
        # (the loop-threading idiom: ``x, s = step(x, s)``)
        if isinstance(call_stmt, ast.Assign):
            for tgt in call_stmt.targets:
                for node in ast.walk(tgt):
                    if isinstance(node, ast.Name) and node.id == name:
                        return
        elif isinstance(call_stmt, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(call_stmt.target, ast.Name) \
                and call_stmt.target.id == name:
            return
        top = [s for s, d in stmts if d == 0]
        exclusive = _exclusive_with(top, call_stmt)
        ancestors = _ancestor_map(top)
        call_anc = ancestors.get(id(call_stmt), set())
        for later, _depth in stmts[call_idx + 1:]:
            if id(later) in exclusive:
                continue
            reads, writes = _name_events(later, name)
            if reads:
                yield self.finding(posix_path, later,
                                   self._message(name, label, call.lineno))
                return
            if writes and ancestors.get(id(later), set()) <= call_anc:
                # a rebind inside ANY branch not already enclosing the
                # call (a sibling if, a deeper if, a loop body) is
                # conditional — the taint survives the branch-not-taken
                # path; only a write on the call's own unconditional
                # continuation clears it
                return
