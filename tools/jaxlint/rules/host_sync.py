"""host-sync-in-hot-path: jitted step functions must not sync the host.

Inside a traced ("hot" — see ``astutil.hot_functions``) function,
``.item()``, ``float(x)``/``int(x)``/``bool(x)`` on a tracer,
``np.asarray``/``np.array``, and Python ``if``/``while`` on a traced
value either fail tracing outright (ConcretizationTypeError at best) or
— worse, when the value happens to be concrete at trace time — silently
bake a constant into the compiled program and force a device→host
round-trip per call.  Under a tunneled TPU that round-trip is 10–100+ ms,
dwarfing small-step compute (the dispatch-latency wall PR 1 exists to
remove).

Parameters declared static (``static_argnums``/``static_argnames``
literals on the jit call or decorator, and keyword-only params) are NOT
treated as tracers, so shape-style branching on statics stays clean.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_CASTS = {"float", "int", "bool"}
_NP_NAMES = {"np", "numpy", "onp"}
_NP_MATERIALIZERS = {"asarray", "array"}


@register
class HostSyncRule(Rule):
    name = "host-sync-in-hot-path"
    severity = "warning"
    description = ("device→host sync (.item(), float()/int()/bool() on a "
                   "tracer, np.asarray, if-on-tracer) inside a jitted "
                   "step function")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        hot = astutil.hot_functions(tree)
        if not hot:
            return
        owner = astutil.enclosing_function_params(tree)
        # tracer params per hot function (statics excluded)
        tracers = {fn: astutil.dynamic_param_names(
            fn, info.static_argnums, info.static_argnames)
            for fn, info in hot.items()}

        for root, _ in astutil.hot_roots(hot):
            for node in ast.walk(root):
                yield from self._check_node(node, posix_path, hot, owner,
                                            tracers)

    def _check_node(self, node, posix_path, hot, owner, tracers
                    ) -> Iterable[Finding]:
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                    and not node.args and not node.keywords:
                yield self.finding(
                    posix_path, node,
                    ".item() forces a device→host sync inside a traced "
                    "function")
            elif isinstance(fn, ast.Name) and fn.id in _CASTS \
                    and len(node.args) == 1 and not node.keywords \
                    and self._tracer_in_test(
                        node.args[0],
                        tracers.get(owner.get(node), set())) is not None:
                # only casts whose argument READS a tracer param — a
                # float() of a host scalar in a hot function is fine
                yield self.finding(
                    posix_path, node,
                    f"{fn.id}() on a traced value syncs the host (use "
                    f"jnp casts / lax.convert_element_type on device)")
            elif isinstance(fn, ast.Attribute) \
                    and fn.attr in _NP_MATERIALIZERS \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id in _NP_NAMES:
                yield self.finding(
                    posix_path, node,
                    f"np.{fn.attr}() materializes a device array on host "
                    "inside a traced function (use jnp)")
        elif isinstance(node, (ast.If, ast.While)):
            enclosing = owner.get(node)
            if enclosing not in hot:
                return
            params = tracers.get(enclosing, set())
            hit = self._tracer_in_test(node.test, params)
            if hit is not None:
                kw = "if" if isinstance(node, ast.If) else "while"
                yield self.finding(
                    posix_path, node,
                    f"Python `{kw}` on traced value {hit!r} — branch on "
                    "device with jnp.where/lax.cond instead")

    @staticmethod
    def _tracer_in_test(test: ast.AST, params: Set[str]):
        """First parameter name the expression reads as a traced VALUE.
        Reads reached only through metadata attributes (``.shape``/
        ``.ndim``/... — astutil.METADATA_ATTRS) are static at trace
        time and don't count."""
        nodes = list(ast.walk(test))
        static_bases = astutil.metadata_only_names(nodes)
        for sub in nodes:
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in params and id(sub) not in static_bases:
                return sub.id
        return None
