"""blocking-in-health-monitor: the watchdog must never be wedgeable.

The serving fleet's health monitor (PR 17,
``AutoscalingRouter._monitor_loop``) exists to detect replicas wedged
by dead workers, dispatch-error streaks, and stalls.  A monitor that
itself blocks unboundedly — an untimed ``Condition.wait()``, a
``join()`` with no timeout, a bare ``Future.result()`` — or that
fetches device values (``.item()``, single-arg ``np.asarray``,
``jax.device_get``, ``block_until_ready``) can be wedged by the very
failure it exists to detect: a dead decode worker never notifies, and
a poisoned dispatch can leave a device value that never resolves.  The
monitor's contract is HOST-side signals and TIMED waits only; this
rule machine-checks it.

Attribution: methods spawned as a Thread target whose thread ``name=``
or method name mentions "monitor"/"health", closed over the method's
same-class ``self.m()`` call graph (the monitor's replacement path —
``replace_replica``, ``_scale_up`` — runs on the monitor thread too).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_NP_NAMES = {"np", "numpy", "onp"}

#: attribute calls that block forever without a timeout argument
_UNTIMED_BLOCKERS = {"wait", "join", "result"}


def _is_np_asarray(node: ast.AST) -> bool:
    name = astutil.dotted_name(node)
    return name is not None and "." in name \
        and name.split(".", 1)[0] in _NP_NAMES \
        and name.rsplit(".", 1)[-1] == "asarray"


def _is_device_get(node: ast.AST) -> bool:
    name = astutil.dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "device_get"


def _self_calls(fn) -> Set[str]:
    """Names of ``self.m(...)`` calls in ``fn``'s own body."""
    out: Set[str] = set()
    for node in astutil.walk_own_body(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.add(node.func.attr)
    return out


def _monitor_functions(tree: ast.Module) -> List[Tuple[ast.AST, str]]:
    """(function, attribution label) for every method running on a
    health-monitor thread: Thread targets named like a monitor, plus
    their same-class self-call closure."""
    out: List[Tuple[ast.AST, str]] = []
    for info in astutil.class_infos(tree):
        roots: Set[str] = set()
        for fn in info.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                ctor = astutil.dotted_name(node.func)
                if ctor is None or ctor.rsplit(".", 1)[-1] \
                        not in ("Thread", "Timer"):
                    continue
                target, tname = None, ""
                for kw in node.keywords:
                    if kw.arg in ("target", "function"):
                        target = kw.value
                    elif kw.arg == "name" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        tname = kw.value.value
                m = astutil.self_attr(target) if target is not None \
                    else None
                if m is None:
                    continue
                hay = f"{tname} {m}".lower()
                if "monitor" in hay or "health" in hay:
                    roots.add(m)
        seen: Set[str] = set()
        stack = sorted(roots)
        while stack:
            m = stack.pop()
            if m in seen or m not in info.methods:
                continue
            seen.add(m)
            fn = info.methods[m]
            why = (f"the health-monitor thread of {info.node.name}"
                   if m in roots else
                   f"the health monitor via {info.node.name}.{m}()")
            out.append((fn, why))
            stack.extend(_self_calls(fn))
    return sorted(out, key=lambda p: p[0].lineno)


@register
class BlockingInHealthMonitorRule(Rule):
    name = "blocking-in-health-monitor"
    severity = "error"
    family = "concurrency"
    description = ("unbounded wait/join/result or device→host fetch on "
                   "a replica health-monitor thread — the watchdog must "
                   "not be wedgeable by the failures it exists to "
                   "detect (host-side signals, timed waits only)")

    def applies_to(self, posix_path: str) -> bool:
        return "serving/" in posix_path

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for fn, why in _monitor_functions(tree):
            for node in astutil.walk_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in _UNTIMED_BLOCKERS \
                        and not node.args \
                        and not any(kw.arg == "timeout"
                                    for kw in node.keywords):
                    yield self.finding(
                        posix_path, node,
                        f".{func.attr}() with no timeout on {why} — an "
                        "unbounded block wedges the watchdog on exactly "
                        "the failure it should be detecting; pass a "
                        "timeout")
                elif isinstance(func, ast.Attribute) \
                        and func.attr == "item" \
                        and not node.args and not node.keywords:
                    yield self.finding(
                        posix_path, node,
                        f".item() on {why} — a device→host sync can "
                        "block forever behind a poisoned dispatch; the "
                        "monitor reads host-side signals only")
                elif _is_np_asarray(func) and len(node.args) == 1 \
                        and not node.keywords:
                    yield self.finding(
                        posix_path, node,
                        f"single-arg np.asarray() on {why} — the "
                        "device-fetch form; the monitor reads host-side "
                        "signals only")
                elif _is_device_get(func):
                    yield self.finding(
                        posix_path, node,
                        f"jax.device_get() on {why} — blocks the "
                        "watchdog on a device transfer")
                elif isinstance(func, ast.Attribute) \
                        and func.attr == "block_until_ready":
                    yield self.finding(
                        posix_path, node,
                        f"block_until_ready on {why} — waits out a "
                        "dispatch the monitor should only be observing")
