"""page-refcount-balance: acquired KV pages must be released on every
exit path of the acquiring scope.

``serving/decode.PageAllocator`` hands out paged-KV page ids with a
refcount protocol — ``alloc``/``share`` take a reference, ``free``
drops one — and a slot admission bug shipped exactly once: a dispatch
path alloc'd pages, hit the capacity ``raise`` inside an ``except``
handler, and re-raised without freeing, bleeding the page pool one
request per failure until the server OOM-killed.  The fix was a
``finally``; this rule is that incident as a lint, generalized through
the export summaries so it fires across module boundaries.

Pass 1 records, per class, which methods match the refcount protocol
by name convention (at least one of ``alloc``/``acquire``/``admit``
AND one of ``free``/``release``/``recycle``; ``share`` where present).
This rule then types receivers in the CONSUMING module — constructor
assignments, annotations (params, AnnAssign), ``self.x`` attributes
set from a typed constructor or parameter — and tracks each
scope-local acquisition::

    pages = pool.alloc(n)        # acquire: 'pages' owns refs
    pool.share(pages)            # acquire: an extra ref on 'pages'

to one of three verdicts:

- **ownership transferred** (silent): the pages are returned/yielded,
  stored into an attribute/subscript/container, or aliased — someone
  else's problem now.  Passing the bare name as a CALL ARGUMENT is
  NOT a transfer; ``dispatch(pages)`` then falling off the end is the
  original leak shape.
- **balanced** (silent): a matching ``free``/``release``/``recycle``
  on the same receiver covers the normal exit, and every
  ``return``/``raise`` after the acquisition either runs after a free
  on its own path, or sits under a ``try`` whose ``finally`` frees.
- **leaked** (flagged): never released, released only on some
  branches, discarded without binding, or — the incident shape — an
  exception path (an ``except`` handler's ``raise``/``return``)
  escapes while the only free sits in the ``try`` body the exception
  just aborted.

The lexical path model is shared with use-after-donate: statement
order, located ancestors, mutually exclusive branches.  ``try`` and
``with`` bodies and ``finally`` blocks count as unconditional on the
normal path; ``if``/loop/handler bodies are conditional.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from tools.jaxlint import astutil, summary as summary_mod
from tools.jaxlint.core import Finding, Rule, register
from tools.jaxlint.rules.use_after_donate import (_exclusive_with,
                                                  _immediate_walk,
                                                  _scope_statements)

#: container mutators that take ownership of an argument
_SINK_METHODS = {"append", "extend", "add", "insert", "put",
                 "setdefault", "push"}

#: (module, class name, protocol dict from the class summary)
ProtoRef = Tuple[str, str, Dict[str, List[str]]]

#: located ancestor: (id of compound stmt, field tag)
_Loc = Tuple[int, str]


def _located_ancestors(body: List[ast.stmt]
                       ) -> Tuple[Dict[int, Set[_Loc]],
                                  Dict[int, ast.stmt]]:
    """id(stmt) -> {(id(compound), field)} for every enclosing compound
    statement WITH the field it entered through, plus id -> stmt for
    the compounds.  The field matters: a statement in a ``try`` body
    and one in that try's handler share the compound but not the path.
    """
    anc: Dict[int, Set[_Loc]] = {}
    stmt_by_id: Dict[int, ast.stmt] = {}

    def build(stmts: List[ast.stmt], stack: Set[_Loc]) -> None:
        for s in stmts:
            anc[id(s)] = set(stack)
            stmt_by_id[id(s)] = s
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for tag in ("body", "orelse", "finalbody"):
                group = getattr(s, tag, None)
                if group:
                    build(list(group), stack | {(id(s), tag)})
            for handler in getattr(s, "handlers", []) or []:
                build(list(handler.body), stack | {(id(s), "handler")})
            for case in getattr(s, "cases", []) or []:
                build(list(case.body), stack | {(id(s), "case")})

    build(body, set())
    return anc, stmt_by_id


def _unconditional(parent: ast.stmt, tag: str) -> bool:
    """Does entering ``parent`` guarantee this field runs on the normal
    (no-exception) path?  try/with bodies and finally blocks: yes.
    if/loop/handler/orelse/case: no."""
    if isinstance(parent, (ast.With, ast.AsyncWith)):
        return tag == "body"
    if isinstance(parent, ast.Try):
        return tag in ("body", "finalbody")
    return False


class _Tracked:
    """One scope-local acquisition being balanced."""

    __slots__ = ("name", "stmt", "idx", "recv", "proto_ref", "method")

    def __init__(self, name: str, stmt: ast.stmt, idx: int, recv: str,
                 proto_ref: ProtoRef, method: str):
        self.name = name
        self.stmt = stmt
        self.idx = idx
        self.recv = recv
        self.proto_ref = proto_ref
        self.method = method


def _contains_load(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               and isinstance(n.ctx, ast.Load)
               for n in ast.walk(node))


def _aliases(value: ast.AST, name: str) -> bool:
    """Is ``value`` the bare name or a container literal holding it —
    the forms that create a second owner we can't track?"""
    if isinstance(value, ast.Name) and value.id == name:
        return True
    if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
        return any(_aliases(e, name) for e in value.elts)
    if isinstance(value, ast.Dict):
        return any(v is not None and _aliases(v, name)
                   for v in list(value.keys) + list(value.values))
    return False


@register
class PageRefcountBalanceRule(Rule):
    name = "page-refcount-balance"
    severity = "error"
    family = "cross-module"
    requires_link = True
    description = ("pages acquired from a refcounted allocator "
                   "(per its class export summary) are not released "
                   "on every exit path — normal AND exception exits "
                   "must free or transfer ownership")

    def check(self, tree: ast.Module, posix_path: str
              ) -> Iterable[Finding]:
        return ()               # linking-only rule

    # -- receiver typing ------------------------------------------------

    def _name_protocols(self, tree: ast.Module, ctx
                        ) -> Dict[str, ProtoRef]:
        """Local bare name -> protocol class it refers to: classes
        DEFINED here (own module's summary) plus imported ones."""
        out: Dict[str, ProtoRef] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                proto = ctx.class_protocol(ctx.module, node.name)
                if proto:
                    out[node.name] = (ctx.module, node.name, proto)
        for local, (mod, attr) in ctx.bindings(tree).items():
            if attr is None:
                continue
            proto = ctx.class_protocol(mod, attr)
            if proto:
                out[local] = (mod, attr, proto)
        return out

    def _expr_protocol(self, expr: ast.AST, names: Dict[str, ProtoRef],
                       bindings, ctx) -> Optional[ProtoRef]:
        """Protocol ref for a class-naming expression: a bare local
        name, or a module attribute (``decode.PageAllocator``)."""
        dotted = astutil.dotted_name(expr)
        if dotted is None:
            return None
        if dotted in names:
            return names[dotted]
        ref = summary_mod.resolve_imported_callee(expr, bindings)
        if ref is not None:
            proto = ctx.class_protocol(*ref)
            if proto:
                return (ref[0], ref[1], proto)
        return None

    def _value_protocol(self, value: Optional[ast.AST],
                        names: Dict[str, ProtoRef], bindings, ctx
                        ) -> Optional[ProtoRef]:
        if isinstance(value, ast.Call):
            return self._expr_protocol(value.func, names, bindings, ctx)
        return None

    def _scope_receivers(self, scope: ast.AST,
                         names: Dict[str, ProtoRef], bindings, ctx
                         ) -> Dict[str, ProtoRef]:
        """dotted receiver -> protocol, from ctor assignments and
        annotations visible in ``scope`` (params included)."""
        typed: Dict[str, ProtoRef] = {}
        args = getattr(scope, "args", None)
        if args is not None:
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)):
                if a.annotation is not None:
                    ref = self._expr_protocol(a.annotation, names,
                                              bindings, ctx)
                    if ref:
                        typed[a.arg] = ref
        for stmt, _depth in _scope_statements(scope):
            target: Optional[ast.AST] = None
            ref: Optional[ProtoRef] = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                ref = self._value_protocol(stmt.value, names, bindings,
                                           ctx)
            elif isinstance(stmt, ast.AnnAssign):
                target = stmt.target
                ref = self._expr_protocol(stmt.annotation, names,
                                          bindings, ctx) \
                    or self._value_protocol(stmt.value, names, bindings,
                                            ctx)
            if target is None or ref is None:
                continue
            dotted = astutil.dotted_name(target)
            if dotted is not None:
                typed[dotted] = ref
        return typed

    def _class_attr_receivers(self, cls: ast.ClassDef,
                              names: Dict[str, ProtoRef], bindings, ctx
                              ) -> Dict[str, ProtoRef]:
        """``self.x`` receivers typed anywhere in the class: assigned
        from a protocol constructor, or from a parameter annotated as
        a protocol class."""
        typed: Dict[str, ProtoRef] = {}
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            params = self._scope_receivers(method, names, bindings, ctx)
            for stmt in ast.walk(method):
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1):
                    continue
                dotted = astutil.dotted_name(stmt.targets[0])
                if dotted is None or not dotted.startswith("self."):
                    continue
                ref = self._value_protocol(stmt.value, names, bindings,
                                           ctx)
                if ref is None and isinstance(stmt.value, ast.Name):
                    ref = params.get(stmt.value.id)
                if ref is not None:
                    typed[dotted] = ref
        return typed

    # -- the check ------------------------------------------------------

    def check_linked(self, tree: ast.Module, posix_path: str,
                     ctx) -> Iterable[Finding]:
        names = self._name_protocols(tree, ctx)
        if not names:
            return
        bindings = ctx.bindings(tree)
        module_typed = self._scope_receivers(tree, names, bindings, ctx)
        class_of: Dict[int, ast.ClassDef] = {}
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                for m in cls.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        class_of[id(m)] = cls
        attr_typed_by_class: Dict[int, Dict[str, ProtoRef]] = {}

        scopes: List[ast.AST] = [tree]
        scopes.extend(n for n in ast.walk(tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)))
        for scope in scopes:
            typed = dict(module_typed)
            cls = class_of.get(id(scope))
            if cls is not None:
                if id(cls) not in attr_typed_by_class:
                    attr_typed_by_class[id(cls)] = \
                        self._class_attr_receivers(cls, names, bindings,
                                                   ctx)
                typed.update(attr_typed_by_class[id(cls)])
            if scope is not tree:
                typed.update(self._scope_receivers(scope, names,
                                                   bindings, ctx))
            if typed:
                yield from self._check_scope(scope, typed, posix_path)

    def _protocol_call(self, node: ast.AST,
                       typed: Dict[str, ProtoRef], kinds: Tuple[str, ...]
                       ) -> Optional[Tuple[str, ProtoRef, str, ast.Call]]:
        """Match ``<typed receiver>.<protocol method>(...)`` where the
        method belongs to one of the given protocol kinds; returns
        (receiver dotted, proto ref, method, call)."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            return None
        recv = astutil.dotted_name(node.func.value)
        if recv is None or recv not in typed:
            return None
        ref = typed[recv]
        proto = ref[2]
        for kind in kinds:
            if node.func.attr in proto.get(kind, []):
                return recv, ref, node.func.attr, node
        return None

    def _check_scope(self, scope: ast.AST, typed: Dict[str, ProtoRef],
                     posix_path: str) -> Iterator[Finding]:
        stmts = list(_scope_statements(scope))
        top = [s for s, d in stmts if d == 0]
        anc, compound = _located_ancestors(top)

        tracked: List[_Tracked] = []
        for i, (stmt, _depth) in enumerate(stmts):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                hit = self._protocol_call(stmt.value, typed,
                                          ("acquire",))
                if hit is not None:
                    recv, ref, meth, _call = hit
                    tracked.append(_Tracked(stmt.targets[0].id, stmt, i,
                                            recv, ref, meth))
                    continue
            if isinstance(stmt, ast.Expr):
                hit = self._protocol_call(stmt.value, typed,
                                          ("acquire",))
                if hit is not None:
                    recv, ref, meth, _call = hit
                    mod, cls, _proto = ref
                    yield self.finding(
                        posix_path, stmt,
                        f"{cls}.{meth}() result discarded — the "
                        "acquired pages are unreachable and can never "
                        f"be released (class summary of {mod})")
                    continue
            # share: an extra reference on an existing name, whether
            # the call's result is bound or not
            value = stmt.value if isinstance(stmt,
                                             (ast.Expr, ast.Assign)) \
                else None
            if value is not None:
                hit = self._protocol_call(value, typed, ("share",))
                if hit is not None and hit[3].args \
                        and isinstance(hit[3].args[0], ast.Name):
                    recv, ref, meth, call = hit
                    tracked.append(_Tracked(call.args[0].id, stmt, i,
                                            recv, ref, meth))

        for t in tracked:
            yield from self._balance(t, stmts, top, anc, compound,
                                     posix_path)

    def _balance(self, t: _Tracked,
                 stmts: List[Tuple[ast.stmt, int]],
                 top: List[ast.stmt],
                 anc: Dict[int, Set[_Loc]],
                 compound: Dict[int, ast.stmt],
                 posix_path: str) -> Iterator[Finding]:
        mod, cls, proto = t.proto_ref
        release = set(proto.get("release", []))
        exclusive = _exclusive_with(top, t.stmt)
        a_loc = anc.get(id(t.stmt), set())
        # trys whose BODY holds the acquisition: their handlers may run
        # with the acquisition never having executed (the alloc itself
        # raised), so exits there cannot be proven to leak — abstain
        a_try_bodies = {cid for cid, tag in a_loc
                        if tag == "body"
                        and isinstance(compound.get(cid), ast.Try)}

        def frees_name(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in release
                    and astutil.dotted_name(node.func.value) == t.recv
                    and any(isinstance(a, ast.Name) and a.id == t.name
                            for a in node.args))

        def compatible(f_loc: Set[_Loc], e_loc: Set[_Loc]) -> bool:
            """Did a free at f_loc run on the path reaching e_loc
            (given both are past the acquisition)?"""
            e_ids = {cid for cid, _tag in e_loc}
            for cid, tag in f_loc:
                if (cid, tag) in e_loc or (cid, tag) in a_loc:
                    continue
                parent = compound.get(cid)
                if parent is not None and _unconditional(parent, tag) \
                        and cid not in e_ids:
                    continue
                return False
            return True

        def finally_covers(e_loc: Set[_Loc]) -> bool:
            """A finally block of a try enclosing this point frees the
            name — runs on return/raise propagation too."""
            for cid, _tag in e_loc | a_loc:
                parent = compound.get(cid)
                if isinstance(parent, ast.Try):
                    for s in parent.finalbody:
                        if any(frees_name(n) for n in ast.walk(s)):
                            return True
            return False

        free_locs: List[Tuple[int, Set[_Loc]]] = []
        for i in range(t.idx + 1, len(stmts)):
            stmt, _depth = stmts[i]
            if id(stmt) in exclusive:
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            s_loc = anc.get(id(stmt), set())

            # _immediate_walk: a free nested in a child statement of a
            # compound belongs to THAT statement's entry (with its own
            # located ancestors), not to the compound's header
            if any(frees_name(n) for n in _immediate_walk(stmt)):
                free_locs.append((i, s_loc))
                continue

            # ownership transfers / aliasing end the tracking
            if isinstance(stmt, (ast.Return, ast.Expr)) \
                    and stmt.value is not None:
                inner = stmt.value
                if isinstance(inner, (ast.Yield, ast.YieldFrom)):
                    if inner.value is not None \
                            and _contains_load(inner.value, t.name):
                        return
                elif isinstance(stmt, ast.Return) \
                        and _contains_load(inner, t.name):
                    return
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                stores_out = any(
                    isinstance(n, (ast.Attribute, ast.Subscript))
                    for tgt in targets for n in ast.walk(tgt))
                value = stmt.value
                if value is not None:
                    if stores_out and _contains_load(value, t.name):
                        return
                    if _aliases(value, t.name):
                        return
                if any(isinstance(n, ast.Name) and n.id == t.name
                       and isinstance(n.ctx, (ast.Store, ast.Del))
                       for tgt in targets for n in ast.walk(tgt)):
                    return      # rebound; the old binding is gone
            if isinstance(stmt, ast.Delete) \
                    and any(isinstance(n, ast.Name) and n.id == t.name
                            for tgt in stmt.targets
                            for n in ast.walk(tgt)):
                return
            for node in _immediate_walk(stmt):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SINK_METHODS \
                        and any(_contains_load(a, t.name)
                                for a in node.args):
                    return      # stored into a container

            # exits: must run after a free on their own path
            if isinstance(stmt, (ast.Return, ast.Raise)):
                if any(cid in a_try_bodies for cid, tag in s_loc
                       if tag == "handler"):
                    continue    # the acquisition may never have run
                if finally_covers(s_loc):
                    continue
                if any(fi < i and compatible(f_loc, s_loc)
                       for fi, f_loc in free_locs):
                    continue
                kind = "return" if isinstance(stmt, ast.Return) \
                    else "raise"
                yield self.finding(
                    posix_path, stmt,
                    f"this {kind} exits without releasing {t.name!r} "
                    f"(acquired via {cls}.{t.method}() at line "
                    f"{t.stmt.lineno}) — pages leak on this path; "
                    "free them first or move the release into a "
                    f"finally (class summary of {mod})")
                return

        # normal fall-off: some free must cover the acquisition's own
        # continuation (or a finally does)
        if finally_covers(a_loc):
            return
        if any(compatible(f_loc, a_loc) for _fi, f_loc in free_locs):
            return
        if free_locs:
            yield self.finding(
                posix_path, t.stmt,
                f"{t.name!r} (acquired via {cls}.{t.method}() here) is "
                "released only on some branches — the normal exit "
                "path leaks the pages; release on the acquisition's "
                f"own continuation or in a finally (class summary of "
                f"{mod})")
        else:
            yield self.finding(
                posix_path, t.stmt,
                f"{t.name!r} (acquired via {cls}.{t.method}() here) is "
                "never released in this scope and never transferred — "
                f"the pages leak (class summary of {mod})")
