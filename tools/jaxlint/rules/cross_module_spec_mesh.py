"""cross-module-spec-mesh: importing a spec factory whose axes the
local mesh never declares.

``spec-axis-outside-mesh`` (v3) checks a module's OWN PartitionSpec
literals against its OWN mesh builder.  But the repo's layering puts
the two on opposite sides of an import: ``models/gpt.shard_specs()``
emits ``P("model", None)`` trees, and a driver builds
``Mesh(devs, ("data",))`` and feeds the imported specs straight into
``NamedSharding`` — the KeyError fires on the pod at consumption time.

Pass 1 records, per exported function, the union of axis names its
PartitionSpec entries resolve to (``spec_axes``); ``None`` means the
factory had at least one opaque entry and the summary abstains.  This
rule runs in the CONSUMER: if the consuming module pins its mesh with
a literal axis tuple (same builder recognition and same opacity
bail-outs as the v3 rule), every call to an imported spec factory must
only need axes that mesh declares.  The finding sits at the call site
and names the factory's module — the v3 rule still owns the
factory-side literal.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from tools.jaxlint import astutil, summary as summary_mod
from tools.jaxlint.core import Finding, Rule, register
from tools.jaxlint.rules.mesh_axes import _axis_tuple_expr


def _declared_axes(tree: ast.Module) -> Optional[Set[str]]:
    """The axis set this module's mesh builders pin, or None when the
    module declares no mesh / any builder or element is opaque (the
    same abstention contract as spec-axis-outside-mesh)."""
    chain = astutil.enclosing_chain(tree)
    declared: Set[str] = set()
    builders: List[ast.Call] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        axes_expr = _axis_tuple_expr(node)
        if axes_expr is None:
            continue
        builders.append(node)
        if not isinstance(axes_expr, (ast.Tuple, ast.List)):
            return None
        for elt in axes_expr.elts:
            values = astutil.resolve_axis_entry(
                elt, tree, chain.get(id(elt), []))
            if not values:
                return None
            declared |= values
    if not builders:
        return None
    return declared


@register
class CrossModuleSpecMeshRule(Rule):
    name = "cross-module-spec-mesh"
    severity = "error"
    family = "cross-module"
    requires_link = True
    description = ("call to an imported spec factory whose export "
                   "summary emits PartitionSpec axes the local mesh "
                   "builder never declares")

    def check(self, tree: ast.Module, posix_path: str
              ) -> Iterable[Finding]:
        return ()               # linking-only rule

    def check_linked(self, tree: ast.Module, posix_path: str,
                     ctx) -> Iterable[Finding]:
        declared = _declared_axes(tree)
        if declared is None:
            return
        bindings = ctx.bindings(tree)
        if not bindings:
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            ref = summary_mod.resolve_imported_callee(node.func, bindings)
            if ref is None:
                continue
            mod, fname = ref
            entry = ctx.function_summary(mod, fname)
            if entry is None:
                continue
            axes = entry.get("spec_axes")
            if not axes:        # [] = emits no specs; None = opaque
                continue
            loose = sorted(a for a in axes if a not in declared)
            if loose:
                yield self.finding(
                    posix_path, node,
                    f"{fname}() ({mod}) emits PartitionSpec axis "
                    f"{loose[0]!r} per its export summary, but this "
                    "module's mesh builder only declares "
                    f"({', '.join(sorted(declared))}) — the sharding "
                    "fails when the imported specs are consumed on "
                    "this mesh")
