"""unstable-imported-cache-key: a compile-cache key built by calling
an imported helper whose export summary says it is impure.

``unstable-cache-key`` (v3) walks the key expression lexically, so
``cached_jit(f, key=f"{time.time()}")`` is caught — but the moment the
instability hides behind a def the walker goes blind::

    # runtime/keys.py
    def run_tag():
        return f"run-{time.time()}"     # impure, per pass 1

    # elsewhere
    from runtime.keys import run_tag
    eng = cached_jit(step, key=run_tag())    # fresh compile per call

Pass 1 runs the same ``key_impurities`` walker over every function
body and records the verdict plus the reason; the linker closes it
over intra-repo call chains (``run_tag`` calling an impure helper two
modules away is still impure, with the provenance chain threaded into
the reason).  This rule re-checks the v3 call sites —
``cached_jit``/``get_or_build`` key and label expressions — for CALLS
to imported helpers and flags the ones whose linked summary says
``key_pure: false``.  Helpers without a summary (stdlib, jax, opaque)
are skipped: the rule only speaks when the summary gives it grounds.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.jaxlint import astutil, summary as summary_mod
from tools.jaxlint.core import Finding, Rule, register
from tools.jaxlint.rules.unstable_cache_key import _ENGINE_CALLS


@register
class UnstableImportedCacheKeyRule(Rule):
    name = "unstable-imported-cache-key"
    severity = "error"
    family = "cross-module"
    requires_link = True
    description = ("compile-cache key/label calls an imported helper "
                   "whose export summary is impure — the instability "
                   "is hidden behind the module boundary, but the "
                   "steady-state recompile is the same")

    def check(self, tree: ast.Module, posix_path: str
              ) -> Iterable[Finding]:
        return ()               # linking-only rule

    def check_linked(self, tree: ast.Module, posix_path: str,
                     ctx) -> Iterable[Finding]:
        bindings = ctx.bindings(tree)
        if not bindings:
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.dotted_name(node.func)
            if name is None:
                continue
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in _ENGINE_CALLS:
                continue
            key_exprs: List[ast.AST] = []
            if leaf == "get_or_build" and node.args:
                key_exprs.append(node.args[0])
            for kw in node.keywords:
                if kw.arg in ("key", "label"):
                    key_exprs.append(kw.value)
            for expr in key_exprs:
                for call in ast.walk(expr):
                    if not isinstance(call, ast.Call):
                        continue
                    ref = summary_mod.resolve_imported_callee(
                        call.func, bindings)
                    if ref is None:
                        continue
                    mod, fname = ref
                    entry = ctx.function_summary(mod, fname)
                    if entry is None or entry.get("key_pure", True):
                        continue
                    why = entry.get("key_impure_reason") \
                        or "impure per its export summary"
                    yield self.finding(
                        posix_path, call,
                        f"compile-cache key for {leaf}() calls "
                        f"{fname}() ({mod}), which is impure per its "
                        f"export summary — {why}; the key never "
                        "matches an existing entry, so steady state "
                        "recompiles per call")
