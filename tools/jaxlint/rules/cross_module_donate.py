"""cross-module-use-after-donate: reusing a tree after handing it to a
function whose EXPORT SUMMARY donates that position.

``use-after-donate`` catches the scope-local shape — a name read after
being passed into a literal ``donate_argnums`` slot of a jit call the
same module built.  But the repo's training entry points hide the
donation behind a module boundary::

    # parallel/sharded_fit.py
    def fit_step(params, ustate, batch):        # donates 0 and 1
        step = cached_jit(body, donate_argnums=(0, 1))
        return step(params, ustate, batch)

    # somewhere else
    from parallel.sharded_fit import fit_step
    out = fit_step(params, ustate, batch)
    debug_norm(params)          # <-- deleted buffer; invisible to v3

Pass 1 records, per exported function, which positional params flow
into donated slots (closed over forwarding chains by the linker, so a
re-export wrapper donates too); this rule replays the PROVEN v3
read-after-donate dataflow — same-statement ordering, mutually
exclusive branches, conditional-rebind taint — against call sites of
those imports.  The finding message carries the summary provenance
(callee module and position) so a baseline entry or CI annotation
points at the contract, not just the line.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from tools.jaxlint.core import Finding, Rule, register
from tools.jaxlint.rules.use_after_donate import (DonationTable,
                                                  UseAfterDonateRule)


class _LinkedChecker(UseAfterDonateRule):
    """Throwaway per-call checker: inherits the v3 dataflow, swaps the
    donation tables for summary-derived ones and the message for one
    that names the exporting module.  Never registered — the public
    rule below instantiates one per ``check_linked`` call, so the
    registered instance stays stateless across threads."""

    direct_form = False

    def __init__(self, provenance: Dict[str, Tuple[str, str, List[int]]]):
        self._prov = provenance

    def _message(self, name: str, label: str, line: int) -> str:
        mod, fname, donated = self._prov.get(label, ("?", label, []))
        pos = ",".join(str(i) for i in donated)
        return (f"{name!r} read after being passed to {label}() at line "
                f"{line} — the export summary of {mod} says {fname}() "
                f"donates positional arg(s) {pos}; the buffer is deleted "
                "on return; copy before the call or rebind from the "
                "result")


@register
class CrossModuleUseAfterDonateRule(Rule):
    name = "cross-module-use-after-donate"
    severity = "error"
    family = "cross-module"
    requires_link = True
    description = ("variable read after being passed to an imported "
                   "function whose export summary donates that "
                   "positional argument — the buffer is deleted across "
                   "the module boundary")

    def check(self, tree: ast.Module, posix_path: str
              ) -> Iterable[Finding]:
        return ()               # linking-only rule

    def check_linked(self, tree: ast.Module, posix_path: str,
                     ctx) -> Iterable[Finding]:
        bindings = ctx.bindings(tree)
        # local alias -> donated positions + provenance, for imports of
        # functions whose LINKED summary donates something
        table: DonationTable = {}
        provenance: Dict[str, Tuple[str, str, List[int]]] = {}
        binder_by_name: Dict[str, ast.stmt] = {}
        for stmt in ast.walk(tree):
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                for a in stmt.names:
                    binder_by_name[(a.asname or a.name).split(".")[0]] \
                        = stmt
        for local, (mod, attr) in bindings.items():
            if attr is None:
                continue        # module object; attribute calls are
                                # rarer and summaries stay name-keyed
            entry = ctx.function_summary(mod, attr)
            if entry is None:
                continue
            donated = list(entry.get("donates_linked",
                                     entry.get("donates", [])))
            if not donated:
                continue
            binder = binder_by_name.get(local)
            if binder is None:
                continue
            table[local] = (set(donated), binder)
            provenance[local] = (mod, attr, donated)
        if not table:
            return
        checker = _LinkedChecker(provenance)
        checker.name = self.name
        checker.severity = self.severity
        scopes: List[ast.AST] = [tree]
        scopes.extend(n for n in ast.walk(tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)))
        for scope in scopes:
            if scope is tree:
                # module scope: the import stmt is the binding, so the
                # "last binding wins" check applies via the local table
                yield from checker._check_scope(scope, table, {},
                                                posix_path)
            else:
                yield from checker._check_scope(scope, {}, table,
                                                posix_path)
