"""unbound-axis: a collective's axis name must actually be bound.

``lax.psum(x, "dta")`` traces and compiles fine inside a ``shard_map``
over ``("data",)`` on some jax versions — and on others is an eager-mode
no-op or a late NameError at dispatch time, after the job has been
queued on a pod.  The repo fixes its axis vocabulary package-wide in
``parallel/mesh.py`` (``data``/``model``/``pipe``/``seq``/``expert``)
precisely so that a collective can be validated against it statically.

A collective call (``psum``/``pmean``/``all_gather``/...) is flagged
when its axis-name argument RESOLVES to a string literal (at the call
site, through a parameter default, or through an unambiguous local/
module constant) that is neither in the mesh vocabulary nor bound by an
explicit ``axis_name=``/``axis_names=`` literal on a pmap/vmap/xmap/
shard_map/Mesh call in the same module.  Unresolvable axis expressions
(a parameter without a default, an imported constant) are the caller's
contract and stay silent — this rule exists to catch the typo'd or
ad-hoc axis nobody binds, not to demand whole-program inference.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register


@register
class UnboundAxisRule(Rule):
    name = "unbound-axis"
    severity = "error"
    family = "collective"
    description = ("collective axis name neither in the parallel/mesh "
                   "vocabulary nor bound by an enclosing "
                   "shard_map/pmap axis_name")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        bound = None        # computed lazily: most files have no collectives
        chain = None
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) \
                    or not astutil.is_collective_call(node):
                continue
            axis_expr = astutil.collective_axis_expr(node)
            if axis_expr is None:
                continue
            if chain is None:
                bound = astutil.bound_axis_names(tree)
                chain = astutil.enclosing_chain(tree)
            values = astutil.resolve_axis_literal(
                axis_expr, tree, chain.get(id(axis_expr), []))
            if values is None:
                continue
            loose = sorted(v for v in values if v not in bound)
            if loose:
                leaf = (astutil.dotted_name(node.func) or "collective"
                        ).rsplit(".", 1)[-1]
                yield self.finding(
                    posix_path, node,
                    f"{leaf}() over axis {loose[0]!r}, which no enclosing "
                    "shard_map/pmap binds and the parallel/mesh vocabulary "
                    "does not contain — this collective is a silent no-op "
                    "(or late NameError) outside a matching mesh")
