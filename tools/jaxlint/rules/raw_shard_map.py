"""raw-shard-map: ``shard_map`` is only reached via ``compat.py``.

The repo supports both jax 0.4.x (``jax.experimental.shard_map`` with
``check_rep``) and current jax (``jax.shard_map`` with ``check_vma``)
through one shim — ``deeplearning4j_tpu/compat.py`` — which translates
the replication-check kwarg.  A direct import anywhere else either
crashes on one jax generation or silently skips the replication check
on the other.  ``compat.py`` itself carries a file-wide
``# jaxlint: disable-file=raw-shard-map`` (it IS the shim) rather than
a path exemption baked in here.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.jaxlint.core import Finding, Rule, register

_MSG = ("direct shard_map import bypasses deeplearning4j_tpu/compat.py "
        "(the check_rep/check_vma shim); use "
        "'from deeplearning4j_tpu.compat import shard_map'")


@register
class RawShardMapRule(Rule):
    name = "raw-shard-map"
    severity = "error"
    description = ("shard_map imported from jax instead of the "
                   "compat.py shim")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "jax.experimental.shard_map":
                    yield self.finding(posix_path, node, _MSG)
                elif mod in ("jax", "jax.experimental") and any(
                        a.name == "shard_map" for a in node.names):
                    yield self.finding(posix_path, node, _MSG)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("jax.experimental.shard_map"):
                        yield self.finding(posix_path, node, _MSG)
            elif isinstance(node, ast.Attribute) \
                    and node.attr == "shard_map":
                # expression use: jax.shard_map / jax.experimental.shard_map
                base = node.value
                if (isinstance(base, ast.Name) and base.id == "jax") or (
                        isinstance(base, ast.Attribute)
                        and base.attr == "experimental"
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "jax"):
                    yield self.finding(posix_path, node, _MSG)
