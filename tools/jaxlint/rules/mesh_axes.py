"""spec-axis-outside-mesh: PartitionSpec axes must come from the
module's own declared mesh axes.

``unknown-axis-in-partition-spec`` checks specs against the
package-wide vocabulary (``parallel/mesh.ALL_AXES``) — a typo net.
This rule is stricter where the module itself pins the mesh shape: a
module that constructs its mesh with an explicit LITERAL axis tuple
(``Mesh(devs, ("data", "model"))`` or ``make_mesh(spec,
axis_order=(DATA_AXIS, MODEL_AXIS))``) has declared, in source, which
axes exist at run time.  A ``P(..., "pipe")`` in that module names an
axis the mesh will never carry — NamedSharding construction raises
``KeyError``/``ValueError`` only when the spec is consumed, on the
pod, far from the literal that caused it (the 4D-parallelism PR made
this a real hazard: five package axes, but any given mesh binds only
the ones its builder listed).

Mechanics: collect every mesh-builder call in the module whose axis
tuple is a resolvable literal (string constants, the exported axis
constants, local aliases).  If any builder's tuple is opaque — a
parameter, a computed value — the module's run-time axis set is
unknowable and the rule stays silent (``parallel/mesh.py`` itself,
whose ``axis_order`` is a parameter, is the canonical example; that is
why the baseline is empty).  Otherwise every resolvable PartitionSpec
entry must name a declared axis.  The runtime twin of this check is
``sharded_fit.validate_specs_against_mesh``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

#: callables that bind a mesh's axis-name tuple, and where the tuple
#: lives in each: ``Mesh(devs, axis_names)`` — positional slot 1 or
#: ``axis_names=``; repo ``make_mesh(spec, devices, axis_order)`` —
#: ``axis_order=`` (positional use would be slot 2, but the repo
#: spells it as a keyword; an unrecognised spelling is simply not a
#: declaration, never a false positive).
_MESH_BUILDERS = {"Mesh": (1, ("axis_names",)),
                  "make_mesh": (None, ("axis_order", "axis_names"))}


def _axis_tuple_expr(call: ast.Call) -> Optional[ast.AST]:
    leaf = (astutil.dotted_name(call.func) or "").rsplit(".", 1)[-1]
    slot_kws = _MESH_BUILDERS.get(leaf)
    if slot_kws is None:
        return None
    slot, kws = slot_kws
    for kw in call.keywords:
        if kw.arg in kws:
            return kw.value
    if slot is not None and len(call.args) > slot:
        return call.args[slot]
    return None


@register
class SpecAxisOutsideMeshRule(Rule):
    name = "spec-axis-outside-mesh"
    severity = "error"
    family = "sharding-layout"
    description = ("PartitionSpec names an axis the module's own mesh "
                   "builder never declares — the NamedSharding fails "
                   "when consumed on the pod, not at build time")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        calls = astutil.partition_spec_calls(tree)
        if not calls:
            return
        chain = astutil.enclosing_chain(tree)

        declared: Set[str] = set()
        builders: List[ast.Call] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            axes_expr = _axis_tuple_expr(node)
            if axes_expr is None:
                continue
            builders.append(node)
            if not isinstance(axes_expr, (ast.Tuple, ast.List)):
                return          # opaque tuple: run-time axes unknowable
            for elt in axes_expr.elts:
                values = astutil.resolve_axis_entry(
                    elt, tree, chain.get(id(elt), []))
                if not values:
                    return      # opaque element: same story
                declared |= values
        if not builders:
            return              # module declares no mesh — out of scope

        for call in calls:
            for entry in astutil.partition_spec_entries(call):
                values = astutil.resolve_axis_entry(
                    entry, tree, chain.get(id(entry), []))
                if values is None:
                    continue
                loose = sorted(v for v in values if v not in declared)
                if loose:
                    yield self.finding(
                        posix_path, call,
                        f"PartitionSpec names axis {loose[0]!r}, but this "
                        "module's mesh builder only declares "
                        f"({', '.join(sorted(declared))}) — the sharding "
                        "fails when the spec is consumed on the target "
                        "mesh, not here")
