"""Rule modules register themselves on import (see core.register).

Seven families:

- tracing   (PR 4): stray-jit, use-after-donate, host-sync-in-hot-path,
              raw-shard-map, impure-jit
- collective (PR 10): unbound-axis, collective-in-divergent-branch,
              donation-across-collective — the SPMD discipline the PR 5
              sharded fit hand-enforced
- concurrency (PR 10): unlocked-shared-mutation, blocking-under-lock,
              impure-signal-handler — the thread/drain/handler contracts
              of the PR 7 batcher and PR 8 async checkpointer — and
              blocking-in-health-monitor (PR 17): the serving watchdog
              must never block unboundedly or sync device values
- distributed-protocol (PR 15): cluster-sync-in-divergent-branch,
              uncommitted-coordinator-write — the PR 13 cluster
              barrier/commit protocols
- sharding-layout (PR 15): unknown-axis-in-partition-spec,
              spec-without-divisibility-guard — the PR 12 GSPMD weight
              layout contracts — and spec-axis-outside-mesh (PR 18):
              specs must draw axes from the module's own declared mesh
- compile-stability (PR 15): unstable-cache-key,
              host-sync-on-serving-worker — the zero-steady-state-
              compile and never-stall-the-decode-worker invariants of
              PRs 7/11/14
- cross-module (PR 19): cross-module-use-after-donate,
              cross-module-spec-mesh, page-refcount-balance,
              unstable-imported-cache-key — the linked rules; they run
              only when the two-pass driver hands each file a
              LinkContext built from its dependencies' export
              summaries (``requires_link = True``), and are silently
              skipped by single-module API calls and ``--no-link``
"""

from tools.jaxlint.rules import (  # noqa: F401
    blocking_under_lock,
    cluster_divergent,
    coordinator_write,
    cross_module_donate,
    cross_module_spec_mesh,
    divergent_collective,
    divisibility_guard,
    donation_across_collective,
    health_monitor_blocking,
    host_sync,
    impure_jit,
    impure_signal_handler,
    imported_cache_key,
    mesh_axes,
    page_refcount,
    partition_spec,
    raw_shard_map,
    serving_worker_sync,
    stray_jit,
    unbound_axis,
    unlocked_shared_mutation,
    unstable_cache_key,
    use_after_donate,
)
