"""Rule modules register themselves on import (see core.register).

Three families:

- tracing   (PR 4): stray-jit, use-after-donate, host-sync-in-hot-path,
              raw-shard-map, impure-jit
- collective (PR 10): unbound-axis, collective-in-divergent-branch,
              donation-across-collective — the SPMD discipline the PR 5
              sharded fit hand-enforced
- concurrency (PR 10): unlocked-shared-mutation, blocking-under-lock,
              impure-signal-handler — the thread/drain/handler contracts
              of the PR 7 batcher and PR 8 async checkpointer
"""

from tools.jaxlint.rules import (  # noqa: F401
    blocking_under_lock,
    divergent_collective,
    donation_across_collective,
    host_sync,
    impure_jit,
    impure_signal_handler,
    raw_shard_map,
    stray_jit,
    unbound_axis,
    unlocked_shared_mutation,
    use_after_donate,
)
