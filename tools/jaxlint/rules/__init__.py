"""Rule modules register themselves on import (see core.register)."""

from tools.jaxlint.rules import (  # noqa: F401
    host_sync,
    impure_jit,
    raw_shard_map,
    stray_jit,
    use_after_donate,
)
