"""spec-without-divisibility-guard: a spec factory naming ``model``
must validate divisibility.

Sharding a weight axis over ``model`` only works when the axis length
divides the mesh's model degree — otherwise jax raises deep inside
``NamedSharding`` consumption with a shape error that names neither
the config knob nor the factory that chose the layout.  PR 12's
convention is that the ``shard_specs`` factories validate up front and
raise with the REAL constraint (``"n_heads=12 not divisible by model
degree 8 — attention heads shard over `model`"``,
``transformer.shard_specs``); this rule keeps every future family
honest.

A module-level (or method) factory whose name ends in ``specs`` and
whose body names the ``model`` axis in a ``P(...)`` literal must
either

- contain a divisibility check (any ``%`` — the ``if cfg.n_heads %
  model_degree: raise`` idiom, or a ``vocab_ok = ... % ... == 0``
  predicate), or
- delegate to another ``*specs`` factory (``gpt.shard_specs`` is
  ``transformer.shard_specs`` re-exported — the delegatee carries the
  guard), or
- carry an inline suppression explaining where the validation lives
  (``gpt.slot_specs``: the DecodeEngine validates at construction).
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_SCOPE_HINTS = ("models/", "parallel/sharded_fit.py")
_own_body = astutil.walk_own_body


@register
class SpecWithoutDivisibilityGuardRule(Rule):
    name = "spec-without-divisibility-guard"
    severity = "error"
    family = "sharding-layout"
    description = ("a *specs factory names the `model` axis without a "
                   "divisibility check or delegation to a guarded "
                   "factory — bad (conf, mesh) pairings fail inside XLA "
                   "partitioning instead of at build time")

    def applies_to(self, posix_path: str) -> bool:
        return any(h in posix_path for h in _SCOPE_HINTS)

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        chain = astutil.enclosing_chain(tree)
        aliases = astutil.partition_spec_aliases(tree)
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or not fn.name.endswith("specs"):
                continue
            names_model = False
            has_mod = False
            delegates = False
            for node in _own_body(fn):
                if isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.Mod):
                    has_mod = True
                elif isinstance(node, ast.Call):
                    name = astutil.dotted_name(node.func)
                    if name is not None and name != fn.name \
                            and name.rsplit(".", 1)[-1].endswith("specs"):
                        delegates = True
            for node in _own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = astutil.dotted_name(node.func)
                if name is None:
                    continue
                leaf = name.rsplit(".", 1)[-1]
                if leaf != "PartitionSpec" and name not in aliases:
                    continue
                for entry in astutil.partition_spec_entries(node):
                    values = astutil.resolve_axis_entry(
                        entry, tree, chain.get(id(entry), []))
                    if values and "model" in values:
                        names_model = True
            if names_model and not has_mod and not delegates:
                yield self.finding(
                    posix_path, fn,
                    f"{fn.name}() shards over the `model` axis but "
                    "neither checks divisibility (no `%` in the body) "
                    "nor delegates to a *specs factory that does — a "
                    "model degree that does not divide the sharded axis "
                    "fails deep inside XLA partitioning; validate up "
                    "front with the real constraint, or suppress with "
                    "a pointer to where the validation lives")
