"""impure-jit: jitted function bodies must be pure.

A traced function runs its Python body ONCE per (shapes, dtypes)
signature; everything that isn't a jax op is frozen into the program or
silently skipped on cached dispatches.  ``time.time()`` bakes the trace
timestamp in forever, ``np.random.*`` bakes one fixed draw, ``print``
fires only while tracing (then never again), and mutating a closed-over
container leaks trace-time state that replays differently per compile —
all four are the classic "works in eager, wrong under jit" bugs.

Flagged inside hot functions (see ``astutil.hot_functions``):
- ``time.time/perf_counter/monotonic/process_time/sleep`` calls,
- any ``np.random.*`` use,
- ``print(...)`` (use ``jax.debug.print`` for traced values),
- ``global``/``nonlocal`` declarations,
- mutation of names NOT bound in the function itself (``.append()`` &
  co., or subscript/augmented assignment to a closed-over name).
  Mutating a function-local container at trace time (building a layer
  list, say) is fine and not flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_TIME_FNS = {"time", "perf_counter", "monotonic", "process_time", "sleep"}
#: container methods that mutate in place.  ``update`` is deliberately
#: absent: in jax code ``x.update(...)`` is overwhelmingly optax's PURE
#: ``GradientTransformation.update`` (every step function here calls
#: it), and the dict.update spelling of this bug is caught anyway when
#: the result is stored back into the closed-over container.
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "add", "discard", "setdefault", "sort", "reverse", "popitem"}
_NP_NAMES = {"np", "numpy", "onp"}


@register
class ImpureJitRule(Rule):
    name = "impure-jit"
    severity = "error"
    description = ("side effect inside a jitted function (time.*, "
                   "np.random.*, print, global, closed-over mutation)")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        hot = astutil.hot_functions(tree)
        if not hot:
            return
        owner = astutil.enclosing_function_params(tree)
        locals_of: Dict[ast.AST, Set[str]] = {
            fn: astutil.local_bindings(fn) for fn in hot}

        for root, _ in astutil.hot_roots(hot):
            for node in ast.walk(root):
                yield from self._check_node(node, posix_path, owner,
                                            locals_of)

    def _check_node(self, node, posix_path, owner, locals_of
                    ) -> Iterable[Finding]:
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _TIME_FNS \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "time":
                yield self.finding(
                    posix_path, node,
                    f"time.{fn.attr}() runs at TRACE time only — its "
                    "value is baked into the compiled program")
            elif isinstance(fn, ast.Name) and fn.id == "print":
                yield self.finding(
                    posix_path, node,
                    "print() fires only while tracing, never on cached "
                    "dispatches (use jax.debug.print)")
            elif isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS \
                    and isinstance(fn.value, ast.Name):
                name = fn.value.id
                enclosing = owner.get(node)
                if enclosing is not None \
                        and enclosing in locals_of \
                        and name not in locals_of[enclosing]:
                    yield self.finding(
                        posix_path, node,
                        f"mutating closed-over {name!r} leaks trace-time "
                        "state (runs once per compile, not per step)")
        elif isinstance(node, ast.Attribute):
            # any np.random.<member> — including np.random.random()
            # itself (the inner np.random node has a Name base, so the
            # walk never double-reports)
            base = node.value
            if isinstance(base, ast.Attribute) and base.attr == "random" \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id in _NP_NAMES:
                yield self.finding(
                    posix_path, node,
                    f"np.random.{node.attr} draws ONE value at trace "
                    "time — use jax.random with a threaded key")
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            kw = "global" if isinstance(node, ast.Global) else "nonlocal"
            yield self.finding(
                posix_path, node,
                f"`{kw}` rebinding inside a traced function is a "
                "trace-time side effect")
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Name):
                    name = tgt.value.id
                    enclosing = owner.get(node)
                    if enclosing is not None \
                            and enclosing in locals_of \
                            and name not in locals_of[enclosing]:
                        yield self.finding(
                            posix_path, node,
                            f"item assignment into closed-over {name!r} "
                            "leaks trace-time state")
