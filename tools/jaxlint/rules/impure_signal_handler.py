"""impure-signal-handler: a signal handler may ONLY set flags.

A Python signal handler runs on the main thread at an arbitrary
bytecode boundary — including while the interrupted thread holds the
metrics registry lock, the tracer lock, or logging's module lock.  Any
re-acquisition from the handler deadlocks the process inside its
preemption grace window; this is the exact bug PR 8 fixed by hand in
``PreemptionGuard``: the handler body is ``Event.set()`` and nothing
else, with metric/telemetry/log booking deferred to the first
``requested()`` observation on a regular thread (see
runtime/resilience.py's ``request`` docstring).

This rule machine-checks that contract.  Handlers are found by
CALLABLE RESOLUTION, not naming: any function registered via
``signal.signal(sig, fn)`` — a module function by name or a bound
``self._handler`` method — and any ``_handler``/``request`` override on
a ``PreemptionGuard`` subclass (the guard installs them itself).  The
handler and every same-class/same-module callee reachable from it may
not:

- enter a ``with`` block or call ``.acquire()`` (lock/context
  acquisition — even "just" a metrics lock),
- log (``log``/``logger``/``logging``/``warnings``) or ``print``,
- book metrics (``*_metrics`` receivers, ``.note*`` methods) or
  telemetry (``telemetry.event``/``span``),
- touch numpy/jax (``np``/``jnp``/``jax`` — allocation and dispatch
  are not async-signal-safe).

``Event.set``, dict reads, ``signal.*`` re-registration and
``raise_signal``/``os.kill`` (the second-delivery escape hatch) stay
legal, as do calls the analyzer cannot resolve — the contract is
enforced where it can be seen.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)

_LOGGING_ROOTS = {"log", "logger", "logging", "warnings"}
_NUMERIC_ROOTS = {"np", "numpy", "onp", "jnp", "jax"}
_GUARD_HOOKS = {"_handler", "request"}


def _walk_own_body(fn) -> Iterator[ast.AST]:
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, _SCOPES):
                stack.append(child)


def _registered_handlers(tree: ast.Module
                         ) -> List[Tuple[astutil.FunctionNode, str,
                                         Optional[ast.ClassDef]]]:
    """(handler def, how it was registered, owning class) triples."""
    owner_cls = astutil.enclosing_class(tree)
    out = []
    seen: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and len(node.args) >= 2:
            name = astutil.dotted_name(node.func) or ""
            parts = name.split(".")
            # `signal.signal(...)` or bare `signal(...)` via
            # `from signal import signal` — not some_obj.signal(...)
            if parts[-1] != "signal" \
                    or (len(parts) > 1 and parts[-2] != "signal"):
                continue
            cls = owner_cls.get(id(node))
            fn = astutil.resolve_callable(node.args[1], tree, cls)
            if fn is not None and id(fn) not in seen:
                seen.add(id(fn))
                out.append((fn, f"signal.signal at line {node.lineno}",
                            owner_cls.get(id(fn))))
    # PreemptionGuard subclasses: the guard installs _handler/request
    # itself, so overrides are handlers even with no visible signal call
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        if not any((astutil.dotted_name(b) or "").rsplit(".", 1)[-1]
                   == "PreemptionGuard" for b in cls.bases):
            continue
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name in _GUARD_HOOKS \
                    and id(stmt) not in seen:
                seen.add(id(stmt))
                out.append((stmt, f"PreemptionGuard override "
                                  f"{cls.name}.{stmt.name}", cls))
    return out


@register
class ImpureSignalHandlerRule(Rule):
    name = "impure-signal-handler"
    severity = "error"
    family = "concurrency"
    description = ("signal handler does more than set a flag (locks, "
                   "logging, metrics, allocation deadlock the grace "
                   "window)")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        handlers = _registered_handlers(tree)
        if not handlers:
            return
        for fn, origin, cls in handlers:
            visited: Set[int] = set()
            yield from self._check_handler(fn, origin, cls, tree,
                                           posix_path, visited)

    def _check_handler(self, fn, origin: str, cls, tree: ast.Module,
                       posix_path: str, visited: Set[int]
                       ) -> Iterator[Finding]:
        if id(fn) in visited:
            return
        visited.add(id(fn))
        label = fn.name
        for node in _walk_own_body(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                yield self.finding(
                    posix_path, node,
                    f"`with` inside signal handler {label!r} ({origin}) "
                    "— acquiring a lock/context from handler context "
                    "deadlocks if the interrupted thread holds it; set "
                    "a flag and do the work at the next safe point")
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            dotted = astutil.dotted_name(func) or ""
            root = dotted.split(".", 1)[0]
            attr = func.attr if isinstance(func, ast.Attribute) else ""
            if attr == "acquire":
                yield self.finding(
                    posix_path, node,
                    f".acquire() inside signal handler {label!r} "
                    f"({origin}) — handler-side lock acquisition is the "
                    "deadlock PR 8 removed; set a flag instead")
            elif isinstance(func, ast.Name) and func.id == "print":
                yield self.finding(
                    posix_path, node,
                    f"print() inside signal handler {label!r} ({origin}) "
                    "— stdio is locked and not async-signal-safe")
            elif root in _LOGGING_ROOTS:
                yield self.finding(
                    posix_path, node,
                    f"logging call inside signal handler {label!r} "
                    f"({origin}) — the logging module lock may be held "
                    "by the interrupted thread; defer to the first "
                    "flag observation")
            elif "metrics" in root or attr.startswith("note"):
                yield self.finding(
                    posix_path, node,
                    f"metric booking inside signal handler {label!r} "
                    f"({origin}) — the registry takes a non-reentrant "
                    "lock; defer booking to the flag's first reader")
            elif root == "telemetry" or (attr in ("event", "span")
                                         and root in ("telemetry", "tr")):
                yield self.finding(
                    posix_path, node,
                    f"telemetry call inside signal handler {label!r} "
                    f"({origin}) — the tracer locks its ring buffer; "
                    "defer to the flag's first reader")
            elif root in _NUMERIC_ROOTS:
                yield self.finding(
                    posix_path, node,
                    f"{root}.* call inside signal handler {label!r} "
                    f"({origin}) — allocation/dispatch is not "
                    "async-signal-safe")
            else:
                callee = astutil.resolve_callable(func, tree, cls)
                if callee is not None:
                    yield from self._check_handler(
                        callee, origin, cls, tree, posix_path, visited)
