"""stray-jit: hot-path packages must compile through the runtime engine.

A raw ``jax.jit`` in ``nn/``, ``optimize/``, ``runtime/``, ``serving/``
or ``eval/`` bypasses ``runtime/compile_cache.cached_jit`` — the
cross-network compile cache and the compile-count/cache-hit/compile-ms
counters — silently re-charging every worker replica a full XLA compile
and hiding the compile from the ``compile_delta == 0`` acceptance
assertions.  This is the AST port of the original
``tools/check_no_stray_jit.py`` (which now shims into this rule).

The one legitimate ``jax.jit`` site — the engine implementation itself —
carries an inline ``# jaxlint: disable=stray-jit`` annotation instead of
a hardcoded exemption list.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from tools.jaxlint.core import Finding, Rule, register

#: package dirs whose every .py is a hot path routed through the engine
#: (matched as path substrings so fixture trees exercise the rule too)
SCOPES = (
    "deeplearning4j_tpu/nn/",
    "deeplearning4j_tpu/optimize/",
    "deeplearning4j_tpu/runtime/",
    "deeplearning4j_tpu/serving/",
    "deeplearning4j_tpu/eval/",
)

#: jax callables that compile programs and must go through the engine
_COMPILERS = {"jit", "pjit"}


@register
class StrayJitRule(Rule):
    name = "stray-jit"
    severity = "error"
    description = ("raw jax.jit/pjit in an engine-scoped package "
                   "bypasses runtime/compile_cache.cached_jit")

    def applies_to(self, posix_path: str) -> bool:
        # resolve relative spellings against the cwd so `cd
        # deeplearning4j_tpu && jaxlint nn/` still matches the scope —
        # a raw substring test on the as-given path would silently skip
        # the rule (a false clean from the enforcement gate)
        p = Path(posix_path)
        resolved = (p if p.is_absolute() else Path.cwd() / p)
        full = resolved.resolve().as_posix()
        return any(scope in full for scope in SCOPES)

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in _COMPILERS
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "jax"):
                yield self.finding(
                    posix_path, node,
                    f"jax.{node.attr} bypasses "
                    "runtime/compile_cache.cached_jit")
            elif isinstance(node, ast.ImportFrom) and node.module == "jax":
                for alias in node.names:
                    if alias.name in _COMPILERS:
                        yield self.finding(
                            posix_path, node,
                            f"'from jax import {alias.name}' hides "
                            "compiles from the engine")
