"""cluster-sync-in-divergent-branch: hosts must reach cluster
rendezvous together.

The PR 13 control plane (``parallel/multihost.Cluster``) makes the
HOST program SPMD too: every member must make the SAME sequence of
``barrier``/``any_flag``/``gather``/``agree_lost_ids`` calls (the
class docstring's protocol discipline), and ``shrink`` must happen on
every survivor or the generations fork — which namespaces the
divergent member away from every later rendezvous, the same deadlock
one hop later.  The dangerous shapes are exactly the per-replica ones
lifted one level up:

- a rendezvous under a branch on PER-HOST state — ``is_coordinator``,
  a ``process_id``/``process_index``/``member_rank`` compare, a
  heartbeat finding (``stale_members``/``lost_device_ids``: each
  host's own filesystem view of its peers), or a value tainted by one
  of those;
- a rendezvous lexically AFTER a divergent branch that can exit early
  (``if not cl.is_coordinator: return`` then ``cl.barrier()`` — the
  divergent coordinator-only path the PR 14 review caught by hand);
- a rendezvous inside a LOCAL ``except`` handler — exceptions are
  per-host events, so only the host that raised enters the handler.

The sanctioned coordinator-commit shape
(``runtime/checkpoint.py::_save_cluster``) passes by construction: the
coordinator-only branch holds WRITES, and the barriers sit outside it
with no early exit.  Values that flowed THROUGH a cluster primitive
(``lost = set(cl.agree_lost_ids(...))``) are cluster-agreed and
launder the taint, mirroring the post-psum rule of the per-replica
family.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Set

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_SCOPES = astutil.SCOPE_NODES


def _contains_sync(expr: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and astutil.is_cluster_sync_call(n)
               for n in astutil.walk_no_scopes(expr))


def _target_names(target: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(target)
            if isinstance(n, ast.Name)
            and isinstance(n.ctx, (ast.Store, ast.Del))}


@register
class ClusterSyncInDivergentBranchRule(Rule):
    name = "cluster-sync-in-divergent-branch"
    severity = "error"
    family = "distributed-protocol"
    description = ("Cluster barrier/any_flag/gather/agree_lost_ids/shrink "
                   "reachable only under per-host-divergent state "
                   "(is_coordinator, process-id compares, local except "
                   "handlers, heartbeat findings) — a cross-host deadlock")

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # cheap pre-filter: most functions have no cluster ops
                if any(isinstance(n, ast.Call)
                       and astutil.is_cluster_sync_call(n)
                       for n in ast.walk(node)):
                    seen: Set[int] = set()
                    yield from self._scan(node.body, set(), posix_path,
                                          seen, context=None)

    # ``context`` carries the divergence label when the statements being
    # scanned are only reachable by a subset of hosts (inside a
    # divergent branch, after a divergent early exit, inside an except
    # handler); None means all hosts reach them.
    def _scan(self, stmts: List[ast.stmt], taint: Set[str], path: str,
              seen: Set[int], context: Optional[str]) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, _SCOPES):
                continue
            if context is not None:
                yield from self._flag(stmt, context, path, seen)
            if isinstance(stmt, (ast.If, ast.While)):
                hit = astutil.host_divergent_read(stmt.test, taint)
                branch_ctx = hit if hit is not None else context
                # each branch gets a COPY of the taint state; afterwards
                # a name tainted on EITHER path stays tainted — a kill
                # inside one conditional branch must not clear the taint
                # for hosts that took the other path
                branch_taints = []
                for group in (stmt.body, stmt.orelse):
                    t = set(taint)
                    yield from self._scan(group, t, path, seen,
                                          branch_ctx)
                    branch_taints.append(t)
                taint |= branch_taints[0] | branch_taints[1]
                if hit is not None and context is None and (
                        astutil.can_exit_suite(stmt.body)
                        or astutil.can_exit_suite(stmt.orelse)):
                    # the remainder of THIS suite is host-divergent too
                    context = (f"{hit} (a branch on it above can exit "
                               "early)")
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                names: Set[str] = set()
                for t in targets:
                    names |= _target_names(t)
                value = stmt.value
                if value is not None and _contains_sync(value):
                    # flowed through a cluster primitive: agreed again
                    taint -= names
                elif value is not None and astutil.host_divergent_read(
                        value, taint) is not None:
                    taint |= names
                elif not isinstance(stmt, ast.AugAssign):
                    taint -= names
            elif isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call) \
                    and isinstance(stmt.value.func, ast.Attribute) \
                    and isinstance(stmt.value.func.value, ast.Name):
                # receiver mutation: ``lost.update(hb.lost_device_ids())``
                # taints the receiver when any argument is divergent
                call = stmt.value
                if any(astutil.host_divergent_read(a, taint) is not None
                       for a in list(call.args)
                       + [k.value for k in call.keywords]):
                    taint.add(call.func.value.id)
            elif isinstance(stmt, ast.For):
                if astutil.host_divergent_read(stmt.iter, taint) \
                        is not None:
                    taint |= _target_names(stmt.target)
                for group in (stmt.body, stmt.orelse):
                    t = set(taint)
                    yield from self._scan(group, t, path, seen, context)
                    taint |= t
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._scan(stmt.body, taint, path, seen,
                                      context)
            elif isinstance(stmt, ast.Try):
                for group in (stmt.body, stmt.orelse, stmt.finalbody):
                    t = set(taint)
                    yield from self._scan(group, t, path, seen, context)
                    taint |= t
                for handler in stmt.handlers:
                    # only the host whose try body raised gets here
                    t = set(taint)
                    yield from self._scan(
                        handler.body, t, path, seen,
                        context or "a local except handler")
                    taint |= t
            elif isinstance(stmt, ast.Match):
                hit = astutil.host_divergent_read(stmt.subject, taint)
                for case in stmt.cases:
                    t = set(taint)
                    yield from self._scan(case.body, t, path, seen,
                                          hit if hit is not None
                                          else context)
                    taint |= t

    def _flag(self, stmt: ast.stmt, label: str, path: str,
              seen: Set[int]) -> Iterator[Finding]:
        for node in astutil.walk_no_scopes(stmt):
            if isinstance(node, ast.Call) \
                    and astutil.is_cluster_sync_call(node) \
                    and id(node) not in seen:
                seen.add(id(node))
                op = node.func.attr  # type: ignore[union-attr]
                yield self.finding(
                    path, node,
                    f"{op}() reachable only under per-host-divergent "
                    f"state ({label}) — members that skip it never join "
                    "the rendezvous and the cluster deadlocks; make the "
                    "call unconditional (gate only the WRITES, like the "
                    "checkpoint commit protocol) or agree the verdict "
                    "first via any_flag/agree_lost_ids")
