"""host-sync-on-serving-worker: the serving workers must not fetch.

The continuous-batching decode worker advances EVERY in-flight
request's next token per iteration — any device→host fetch on that
thread stalls every user's inter-token latency, not just one
request's.  This is exactly the PR 14 harvest-stall bug: the prefix
harvester's full-bucket ``np.asarray`` ran on the decode worker and
was moved to a dedicated harvest thread during review.  Six hardening
passes later, the bug class is a rule.

Worker attribution is the PR 10 thread-target resolver grown two
hops (``astutil.worker_attributed_functions``): worker methods of
thread-owning classes, methods of module classes those workers drive
through a typed attribute (``ContinuousBatcher._advance_all`` →
``self.engine.advance`` with ``engine: DecodeEngine``), and local
function defs spawned by bare name (``Thread(target=loop)``).  Inside
an attributed body the rule flags

- ``.item()``,
- single-argument ``np.asarray(x)`` — the device-fetch form (the
  two-argument ``np.asarray(x, dtype)`` host-normalization idiom this
  repo uses on request inputs stays clean), as a call or passed as a
  bare callable (``jax.tree.map(np.asarray, out)``),
- ``jax.device_get`` (call or reference),
- ``block_until_ready`` (method or ``jax.block_until_ready``).

Deliberate syncs — the decode stream's one per-step token fetch, the
harvest worker whose whole job is absorbing the fetch, the
DynamicBatcher's host-numpy result contract — carry inline
suppressions with their reasons; everything else is a stall bug.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.jaxlint import astutil
from tools.jaxlint.core import Finding, Rule, register

_NP_NAMES = {"np", "numpy", "onp"}

_own_body = astutil.walk_own_body


def _is_np_asarray(node: ast.AST) -> bool:
    name = astutil.dotted_name(node)
    return name is not None and "." in name \
        and name.split(".", 1)[0] in _NP_NAMES \
        and name.rsplit(".", 1)[-1] == "asarray"


def _is_device_get(node: ast.AST) -> bool:
    name = astutil.dotted_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "device_get"


@register
class HostSyncOnServingWorkerRule(Rule):
    name = "host-sync-on-serving-worker"
    severity = "error"
    family = "compile-stability"
    description = ("device→host fetch (.item(), single-arg np.asarray, "
                   "jax.device_get, block_until_ready) on a serving "
                   "worker thread — stalls every in-flight request's "
                   "latency (the PR 14 harvest-stall bug)")

    def applies_to(self, posix_path: str) -> bool:
        return "serving/" in posix_path

    def check(self, tree: ast.Module, posix_path: str) -> Iterable[Finding]:
        for fn, why in astutil.worker_attributed_functions(tree):
            for node in _own_body(fn):
                if isinstance(node, ast.Call):
                    func = node.func
                    if isinstance(func, ast.Attribute) \
                            and func.attr == "item" \
                            and not node.args and not node.keywords:
                        yield self.finding(
                            posix_path, node,
                            f".item() on {why} — a per-call device→host "
                            "sync stalls every in-flight request")
                    elif _is_np_asarray(func) and len(node.args) == 1 \
                            and not node.keywords:
                        yield self.finding(
                            posix_path, node,
                            f"single-arg np.asarray() on {why} — fetches "
                            "a device value to host on the worker; move "
                            "the fetch off-thread (the PR 14 harvest "
                            "worker pattern) or keep it on device")
                    elif _is_device_get(func):
                        yield self.finding(
                            posix_path, node,
                            f"jax.device_get() on {why} — blocks the "
                            "worker on the transfer")
                    elif (isinstance(func, ast.Attribute)
                          and func.attr == "block_until_ready"):
                        yield self.finding(
                            posix_path, node,
                            f"block_until_ready on {why} — the worker "
                            "waits out the whole dispatch; let the next "
                            "dispatch's data dependency do the waiting")
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load) \
                        and (_is_np_asarray(node) or _is_device_get(node)) \
                        and not self._is_call_func(node, fn):
                    yield self.finding(
                        posix_path, node,
                        f"{astutil.dotted_name(node)} passed as a "
                        f"callable on {why} — applied leaf-wise it "
                        "fetches every device leaf to host on the worker")

    @staticmethod
    def _is_call_func(attr: ast.Attribute, fn) -> bool:
        """Is this attribute the FUNC of a call (already handled above)
        rather than a bare reference passed along?"""
        for node in _own_body(fn):
            if isinstance(node, ast.Call) and node.func is attr:
                return True
        return False
