"""donation-across-collective: builder-made sharded steps donate too.

The PR 4 ``use-after-donate`` dataflow sees donation declared AT the
assignment (``step = cached_jit(f, donate_argnums=(0,))``).  The PR 5
sharded-fit stack moved that declaration into FACTORIES: a caller gets
its compiled step from ``build_sharded_step``/``build_scanned_epochs``
(parallel/sharded_fit.py), which wrap the per-shard body in
``shard_map`` and compile it with ``donate_argnums=(0, 1)`` — params
and updater state are donated on every dispatch, but nothing at the
CALL SITE says so.  Reading ``params`` after

    fn = build_scanned_epochs(step, mesh, label=...)
    new_params, new_ustate, scores, skips = fn(params, ustate, ...)
    loss(params)        # <-- donated on EVERY replica of the mesh

touches a buffer XLA reused on every device of the mesh at once — the
failure is per-replica garbage or a crash, and it only reproduces on
sharded runs.

This rule extends the same read-after-donate tracking to the
wrapped-callable form, two resolutions deep:

- the known sharded-fit builders (``build_sharded_step``,
  ``build_scanned_epochs``) donate positions (0, 1) unless called with
  a literal ``donate=False``;
- any SAME-MODULE factory whose body both wraps a callable in
  ``shard_map`` and compiles with a literal ``donate_argnums`` (the
  ``(0, 1) if donate else ()`` conditional counts as donating) is
  resolved structurally — new builders get checked without touching
  this rule.

The plain assignment and direct-call forms stay with use-after-donate;
this rule never double-reports them.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

from tools.jaxlint import astutil
from tools.jaxlint.core import register
from tools.jaxlint.rules.use_after_donate import (
    DonationTable,
    ScopeNode,
    UseAfterDonateRule,
    _scope_statements,
)

#: cross-module builders this repo compiles sharded steps through
#: (parallel/sharded_fit.py) — position (0, 1) = (params, ustate)
KNOWN_FACTORIES: Dict[str, Set[int]] = {
    "build_sharded_step": {0, 1},
    "build_scanned_epochs": {0, 1},
}


def _donate_literal(call: ast.Call) -> Set[int]:
    """Literal ``donate_argnums`` positions, resolving the conditional
    ``(0, 1) if donate else ()`` builder idiom to the donating arm."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        value = kw.value
        if isinstance(value, ast.IfExp):
            out = set()
            for arm in (value.body, value.orelse):
                out |= astutil.donated_argnums(
                    ast.Call(func=call.func, args=[], keywords=[
                        ast.keyword(arg="donate_argnums", value=arm)]))
            return out
    return astutil.donated_argnums(call)


def _local_factories(tree: ast.Module) -> Dict[str, Set[int]]:
    """Same-module factory defs that build a donated shard_map'd
    executable: the subtree contains both a ``shard_map(...)`` call and
    a jit-family compile with a literal ``donate_argnums``."""
    out: Dict[str, Set[int]] = {}
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_shard_map = False
        donated: Set[int] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            leaf = (astutil.dotted_name(node.func) or "").rsplit(".", 1)[-1]
            if leaf == "shard_map":
                has_shard_map = True
            if astutil.is_jit_reference(node.func):
                donated |= _donate_literal(node)
        if has_shard_map and donated:
            out[fn.name] = donated
    return out


def _factory_positions(call: ast.Call, factories: Dict[str, Set[int]]
                       ) -> Optional[Set[int]]:
    """Donated positions for a builder call, or None if it isn't one
    (or was called with a literal ``donate=False``)."""
    name = astutil.dotted_name(call.func)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    donated = factories.get(leaf, KNOWN_FACTORIES.get(leaf))
    if donated is None:
        return None
    for kw in call.keywords:
        if kw.arg == "donate" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return None
    return donated


@register
class DonationAcrossCollectiveRule(UseAfterDonateRule):
    name = "donation-across-collective"
    severity = "error"
    family = "collective"
    description = ("variable read after being donated into a "
                   "shard_map'd builder step (freed on every replica)")
    direct_form = False

    def _build_tables(self, tree: ast.Module) -> Dict[ScopeNode,
                                                      DonationTable]:
        factories = _local_factories(tree)
        tbls: Dict[ScopeNode, DonationTable] = {}

        def scan(scope: ScopeNode) -> None:
            table = tbls.setdefault(scope, {})
            for stmt, _depth in _scope_statements(scope):
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.Call):
                    # a factory is NOT itself donating when assigned
                    # through cached_jit (that's use-after-donate's form)
                    if astutil.is_jit_reference(stmt.value.func):
                        continue
                    donated = _factory_positions(stmt.value, factories)
                    if donated:
                        table[stmt.targets[0].id] = (donated, stmt)
                elif isinstance(stmt, (ast.ClassDef, ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    scan(stmt)

        scan(tree)
        return tbls

    def _message(self, name: str, label: str, line: int) -> str:
        return (f"{name!r} read after being donated into the shard_map'd "
                f"step from {label}() (line {line}) — the buffer was "
                "reused on every replica of the mesh; rebind from the "
                "step's result or build with donate=False")
