"""CI chaos drill for serving fleet fault tolerance (PR 17).

Machine-checks the failure contract of the tier-3 serving fleet: under
injected faults — a poisoned dispatch, a killed decode worker, a stalled
replica, an exhausted KV page pool — every submitted request must still
complete with tokens BIT-IDENTICAL to an undisturbed run, replacement
replicas must compile ZERO new programs (shared compile cache, the
autoscaling invariant), and the page allocator must account for every
page after the drill (no leaks from any recovery path).

Why bit-exactness is even possible: sampling keys fold (seed, POSITION),
so a request journaled as (prompt, seed, temperature, tokens-emitted)
replays on any identically-configured replica and continues exactly
where it died — replica death loses no request and changes no token.

Drill phases (deterministic; each fault armed via
``parallel.chaos.ServingChaos`` and fired at a step boundary on the
victim's own worker thread):

1. POISON — one dispatch raises ``InjectedFault``: the batcher frees the
   affected slots, reclaims their pages, and replays the requests
   in-place (no replacement — the error streak stays under the bound);
2. KILL — the worker thread dies mid-traffic (``WorkerKilled``): the
   health monitor sees ``worker_alive() == False``, spawns a factory
   replacement, and re-dispatches every journaled request onto it;
3. STALL — a dispatch sleeps past ``stall_after_s``: the monitor's
   progress-age detector replaces the replica while the zombie worker
   is still asleep; mid-decode requests replay from their last token;
4. EXHAUST — the free page pool is held hostage: admissions stall (no
   deadlock, no shed — the prompts fit the pool), a deadline probe
   queued behind the exhaustion expires with the typed
   ``DeadlineExceeded``, and releasing the pages lets the wave finish.

Run by ``tools/ci.sh`` after the telemetry gate; exits non-zero on any
violation.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_REQUESTS = 12


def _prompts():
    import numpy as np

    r = np.random.RandomState(17)
    return [r.randint(1, 48, size=r.randint(2, 12)).astype(np.int32)
            for _ in range(N_REQUESTS)]


def _make_factory(cfg, params):
    from deeplearning4j_tpu.serving.decode import (ContinuousBatcher,
                                                   DecodeEngine)

    def factory():
        eng = DecodeEngine(cfg, params, n_slots=3, buckets=(16, 32),
                           prefill_chunk=8, paged=True,
                           label="chaos-gate")
        eng.warmup()
        return ContinuousBatcher(eng, default_max_tokens=5)
    return factory


def _submit(target, prompt, i):
    # per-request (seed, temperature) pairs make bit-exactness a claim
    # about SAMPLED decode, not just greedy argmax
    return target.submit(prompt, max_tokens=5, temperature=0.7,
                         seed=100 + i)


def main() -> int:
    import numpy as np

    from deeplearning4j_tpu.models import gpt
    from deeplearning4j_tpu.parallel.chaos import ServingChaos
    from deeplearning4j_tpu.runtime import telemetry
    from deeplearning4j_tpu.runtime.metrics import decode_metrics
    from deeplearning4j_tpu.serving.decode import DeadlineExceeded
    from deeplearning4j_tpu.serving.router import (AutoscalePolicy,
                                                   AutoscalingRouter,
                                                   ReplicaHealth)
    import jax

    registry = telemetry.registry
    cfg = gpt.gpt_tiny(vocab_size=48, max_len=32)
    params = gpt.init_params(jax.random.key(0), cfg)
    factory = _make_factory(cfg, params)
    prompts = _prompts()

    # -- 1) undisturbed baseline: the bit-exact reference -------------------
    base = factory()
    try:
        handles = [_submit(base, p, i) for i, p in enumerate(prompts)]
        expect = [h.result(120) for h in handles]
    finally:
        base.close()

    # -- 2) the chaos fleet --------------------------------------------------
    decode_metrics.reset()
    router = AutoscalingRouter(
        factory, AutoscalePolicy(min_replicas=2, max_replicas=3),
        max_queue_depth=64,
        health=ReplicaHealth(poll_interval_s=0.02, max_error_streak=3,
                             stall_after_s=0.6))
    got: dict = {}
    probe = None
    try:
        # every program is warmed (baseline + factory warmups); from
        # here on — including the replacement spawns the faults will
        # force — the fleet must not compile ONE new program
        registry.mark()

        # phase 1: POISON one dispatch — in-place replay, no replacement
        b0 = router.batchers[0]
        ServingChaos(b0).poison_dispatch(1)
        wave = [(i, _submit(b0, prompts[i], i)) for i in range(0, 4)]
        for i, h in wave:
            got[i] = h.result(120)

        # phase 2: KILL a worker — monitor replaces, requests replay
        victim = router.batchers[1]
        ServingChaos(victim).kill_worker()
        wave = [(i, _submit(victim, prompts[i], i)) for i in range(4, 8)]
        for i, h in wave:
            got[i] = h.result(120)
        if victim in router.batchers:
            print("[serving-chaos-gate] FAIL: killed replica was never "
                  "replaced — the health monitor missed a dead worker")
            return 1

        # phase 3: STALL a replica mid-decode — progress-age detector
        # replaces it; the requests replay from their last token
        stalled = router.batchers[0]
        ServingChaos(stalled).stall_dispatch(1.5)
        wave = [(i, _submit(stalled, prompts[i], i)) for i in range(8, 10)]
        for i, h in wave:
            got[i] = h.result(120)
        if stalled in router.batchers:
            print("[serving-chaos-gate] FAIL: stalled replica was never "
                  "replaced — the progress-age detector missed it")
            return 1

        # phase 4: EXHAUST the page pool — admissions stall (never
        # deadlock/shed), a deadline probe behind the exhaustion
        # expires typed, releasing the pages completes the wave
        host = router.batchers[0]
        chaos = ServingChaos(host)
        chaos.exhaust_pages()
        wave = [(i, _submit(host, prompts[i], i)) for i in range(10, 12)]
        probe = host.submit(prompts[0], max_tokens=5, temperature=0.7,
                            seed=100, deadline_ms=80)
        time.sleep(0.3)                  # let the probe expire queued
        chaos.release_pages()
        for i, h in wave:
            got[i] = h.result(120)

        live_engines = [b.engine for b in router.batchers]
    finally:
        router.close()

    # -- 3) verdicts ---------------------------------------------------------
    bad = [i for i in range(N_REQUESTS)
           if not np.array_equal(got[i], expect[i])]
    if bad:
        print(f"[serving-chaos-gate] FAIL: request(s) {bad} completed "
              "with tokens differing from the undisturbed run — replay "
              "is not bit-exact")
        return 1

    delta = registry.compile_delta_since_mark()
    if delta != 0:
        print(f"[serving-chaos-gate] FAIL: the drill compiled {delta} "
              "new program(s) — replica replacement must reuse the "
              "shared compile cache")
        return 1

    try:
        probe.result(1)
        print("[serving-chaos-gate] FAIL: the deadline probe completed "
              "instead of expiring behind the exhausted pool")
        return 1
    except DeadlineExceeded:
        pass

    for eng in live_engines:
        # pool-resident prefix pages are a CACHE (registry-held refs),
        # not occupancy — evict them (workers are joined; the engine is
        # quiescent) so in_use() == 0 is the honest leak audit
        eng.drop_residents()
        if eng._alloc.in_use() != 0 or eng.pages_unaccounted() != 0:
            print(f"[serving-chaos-gate] FAIL: pages leaked after "
                  f"drain: in_use={eng._alloc.in_use()} "
                  f"unaccounted={eng.pages_unaccounted()}")
            return 1

    snap = decode_metrics.snapshot()
    for key, floor in (("replicas_replaced", 2),
                       ("requests_replayed", 1),
                       ("deadline_expirations", 1)):
        if snap[key] < floor:
            print(f"[serving-chaos-gate] FAIL: {key}={snap[key]} "
                  f"(expected >= {floor}) — the drill did not exercise "
                  "its fault path")
            return 1

    print(f"[serving-chaos-gate] ok: {N_REQUESTS} requests bit-exact "
          f"under poison/kill/stall/exhaust, compile_delta={delta}, "
          f"replaced={snap['replicas_replaced']}, "
          f"replayed={snap['requests_replayed']}, pages_leaked=0")
    return 0


if __name__ == "__main__":
    sys.exit(main())
