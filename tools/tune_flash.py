"""Flash-attention block-size tuner — run on real TPU hardware.

The kernels default to (block_q, block_k) = (128, 128); the best tiling
depends on the chip generation (VMEM size / MXU shape) and sequence
length.  This sweeps the grid at the bench shapes and prints one JSON
line per (T, bq, bk) plus the winner per T, so the defaults (and
bench_longctx) can be retuned from data rather than guesswork.

Usage:  python tools/tune_flash.py [T ...]     (default: 8192 16384 32768)
"""
import itertools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BLOCKS = (128, 256, 512, 1024)
HEADS, HEAD_DIM, BATCH = 12, 64, 1
STEPS, WARMUP = 8, 2


def time_config(T: int, bq: int, bk: int) -> float | None:
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import pallas_attention as pa

    ks = jax.random.split(jax.random.key(0), 3)
    shape = (BATCH, T, HEADS, HEAD_DIM)
    q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)

    def fwd_bwd(q, k, v):
        def loss(q, k, v):
            return jnp.sum(pa.flash_attention(
                q, k, v, None, True, block_q=bq, block_k=bk,
                interpret=False).astype(jnp.float32))
        l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, grads

    try:
        f = jax.jit(fwd_bwd)
        (l, _) = f(q, k, v)
        float(l)                                  # compile + warm
        for _ in range(WARMUP):
            l, _ = f(q, k, v)
        float(l)
        t0 = time.perf_counter()
        for _ in range(STEPS):
            l, g = f(q, k, v)
        float(l)
        float(jnp.ravel(g[0])[0])                 # true device sync
        return (time.perf_counter() - t0) / STEPS
    except Exception as e:                        # Mosaic reject / OOM
        print(json.dumps({"T": T, "bq": bq, "bk": bk,
                          "error": repr(e)[:160]}))
        return None


def main() -> None:
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # a sitecustomize pins the hardware plugin AND may have already
        # initialized it; a config update alone is ineffective then —
        # drop backends first (same pattern as bench.py _force_cpu)
        from jax.extend import backend as jexb
        jexb.clear_backends()
        jax.config.update("jax_platforms", "cpu")
    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"error": "tuner is tpu-only (run via the "
                                   "tunnel when healthy)"}))
        return
    seqs = [int(a) for a in sys.argv[1:]] or [8192, 16384, 32768]
    for T in seqs:
        best = None
        for bq, bk in itertools.product(BLOCKS, BLOCKS):
            # flash_attention clamps to the largest divisor of T
            # (_pick_block); only run configs whose tiling is what the
            # label says, or the winner records a tiling never executed
            if T % bq != 0 or T % bk != 0:
                continue
            dt = time_config(T, bq, bk)
            if dt is None:
                continue
            toks = BATCH * T / dt
            print(json.dumps({"T": T, "bq": bq, "bk": bk,
                              "step_ms": round(dt * 1e3, 2),
                              "tokens_per_sec": round(toks, 0)}))
            if best is None or dt < best[0]:
                best = (dt, bq, bk)
        if best:
            print(json.dumps({"T": T, "best_bq": best[1],
                              "best_bk": best[2],
                              "best_step_ms": round(best[0] * 1e3, 2)}))


if __name__ == "__main__":
    main()
