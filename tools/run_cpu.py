"""Run a script/module on N virtual CPU devices regardless of any
pre-imported hardware platform (the conftest.py dance, as a launcher).

Usage: python tools/run_cpu.py [N] script.py [args...]
"""

import os
import runpy
import sys

n = "8"
args = sys.argv[1:]
if args and args[0].isdigit():
    n, args = args[0], args[1:]

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

if not args:
    sys.exit("usage: run_cpu.py [N] script.py [args...]")
sys.argv = args
sys.path.insert(0, os.path.dirname(os.path.abspath(args[0])))
runpy.run_path(args[0], run_name="__main__")
