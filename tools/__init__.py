# Makes `tools` importable so `python -m tools.jaxlint` and
# `from tools.jaxlint import ...` resolve from the repo root without
# relying on namespace-package semantics.
