"""Build a local plain-text corpus (``data/text8``) from the Python
standard library's docstrings — real English prose available on any host
with zero egress.  The committed ``data/text8`` was produced by this
script; re-run to regenerate (deterministic module order).

The word2vec quality tier (tests/test_nlp.py real-corpus tier) wants
text8-style input: lowercase words, single spaces, vocabulary in the
thousands with a natural Zipf head ("the", "of", "and", ...).
"""
import io
import pydoc
import re
import sys

#: modules whose import has user-visible side effects (antigravity opens
#: a browser, ``this`` prints) — never import these
_SKIP = {"antigravity", "this", "idlelib", "turtledemo", "tkinter"}


def harvest(limit_bytes: int = 2_000_000) -> str:
    out = io.StringIO()
    seen = set()
    # STDLIB ONLY, sorted: the same module list (and so the same corpus)
    # on every host with this Python version — site-packages would make
    # the output host-dependent and can be minutes-slow to import
    names = sorted(n for n in sys.stdlib_module_names
                   if not n.startswith("_") and n not in _SKIP)
    for name in names:
        if out.tell() >= limit_bytes:
            break
        try:
            mod = __import__(name)
        except Exception:
            continue
        for obj in [mod] + [getattr(mod, a, None) for a in dir(mod)
                            if not a.startswith("_")]:
            doc = pydoc.getdoc(obj) if obj is not None else ""
            if not doc or id(obj) in seen:
                continue
            seen.add(id(obj))
            words = re.findall(r"[a-z]+", doc.lower())
            if len(words) >= 8:
                out.write(" ".join(words) + " ")
            if out.tell() >= limit_bytes:
                break
    return out.getvalue()


if __name__ == "__main__":
    dest = sys.argv[1] if len(sys.argv) > 1 else "data/text8"
    text = harvest()
    with open(dest, "w") as f:
        f.write(text)
    words = text.split()
    print(f"wrote {dest}: {len(text)} bytes, {len(words)} words, "
          f"{len(set(words))} distinct")
