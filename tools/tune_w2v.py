"""Word2Vec device-engine tuning sweep — run in a healthy TPU window.

Sweeps the two free knobs of the ``pair_mode="device"`` engine (chunk
batch size and kernel selection) on the bench corpus shape and prints
one JSON line per point (cold-fit words/sec, kernel actually used).
If a point clearly beats bench.py's defaults (batch_size=16384,
kernel=auto), set those defaults and re-run
``python tools/measure_tpu.py word2vec_device`` to re-bank.

Usage:  python tools/tune_w2v.py [--quick]
Exit 1 if the backend is not a TPU (the numbers would be meaningless).
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(REPO, ".jax_cache"))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.nlp.word2vec import Word2Vec, Word2VecConfig  # noqa


def corpus(n_sentences: int, sent_len: int = 30, vocab: int = 2000):
    rng = np.random.RandomState(0)
    p = 1.0 / np.arange(1, vocab + 1) ** 1.05
    p /= p.sum()
    ids = rng.choice(vocab, p=p, size=(n_sentences, sent_len))
    return [" ".join(f"w{i}" for i in row) for row in ids]


def main() -> None:
    platform = jax.devices()[0].platform
    if platform == "cpu":
        print(json.dumps({"abort": "cpu backend — tuning needs the TPU"}))
        sys.exit(1)
    quick = "--quick" in sys.argv
    n_sent, epochs = (4000, 1) if quick else (16000, 2)
    sents = corpus(n_sent)
    total = n_sent * 30 * epochs
    cache = None
    best = None
    for batch_size in (8192, 16384, 32768, 65536):
        for kernel in ("auto", "xla"):
            cfg = Word2VecConfig(vector_size=100, window=5, epochs=epochs,
                                 negative=5, use_hs=True,
                                 batch_size=batch_size,
                                 pair_mode="device", kernel=kernel)
            warm = Word2Vec(sents, cfg, cache=cache)
            warm.fit()                       # compile + vocab
            float(np.asarray(warm.syn0).ravel()[0])
            cache = warm.cache
            cold = Word2Vec(sents, cfg, cache=cache)
            t0 = time.perf_counter()
            cold.fit()
            float(np.asarray(cold.syn0).ravel()[0])
            wps = total / (time.perf_counter() - t0)
            row = {"batch_size": batch_size, "kernel": kernel,
                   "kernel_used": cold.kernel_used,
                   "words_per_sec": round(wps, 1)}
            print(json.dumps(row), flush=True)
            if best is None or wps > best["words_per_sec"]:
                best = row
    print(json.dumps({"best": best}), flush=True)


if __name__ == "__main__":
    main()
