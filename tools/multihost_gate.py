"""CI gate for the multi-host training runtime (parallel/multihost.py).

Four phases machine-checking the ISSUE-13/18/20 acceptance contracts:

**Phase A — virtual 2-host drill (always runs, single process).**  The
8 forced CPU devices partitioned as 2 virtual hosts x 4:

1. one warmed sharded ``ResilientFit`` fit over the full data mesh must
   show ``compile_delta == 0`` (the multi-host plumbing adds no trace);
2. its snapshots must be committed (manifest verifies);
3. an injected loss of host 1 (``parallel.chaos.HostLossChaos`` — ALL
   four of its devices at once) must trigger the coordinated
   ``elastic_remesh`` to the surviving host with ``grad_accum`` x2 and
   a restore from the last committed snapshot, and the resumed run
   must be BIT-exact vs the uninterrupted equal-effective-batch run.

**Phase B — real 2-process cluster drill (skip-aware).**  Two fresh
interpreters join a real ``jax.distributed`` cluster through
``multihost.initialize``; the drills ride the coordination-service KV
store (control plane), so they run even on CPU backends that cannot
form cross-process device computations:

4. join + control-plane smoke (barrier, cluster-wide flag OR, gather);
5. each process runs one warmed fit with ``compile_delta == 0``, with
   CLUSTER-committed snapshots (coordinator writes the manifest only
   after the all-members barrier) verified from outside;
6. host loss for real: process 1 is SIGKILLed mid-fit; process 0's
   control-plane sync times out, the shared-fs heartbeat names the
   dead member, the cluster shrinks to the survivor, the last
   cluster-committed snapshot restores, and the finished run is
   bit-exact vs an uninterrupted single-process run.

**Phase C — two-shape 4D drill (ISSUE 18, always runs).**  The same
``CausalLM`` trained at two 3D mesh shapes differing only in pipe
degree must be byte-identical, with ``compile_delta == 0`` on the
warmed steady-state fit and no copy-on-donate warnings.

**Phase D — distributed data service, real 2-process drill (ISSUE 20,
skip-aware).**  A fresh 2-process cluster whose 16 forced CPU devices
DO form a real cross-process mesh for staging:

7. per-host shard readers on the spanning mesh: each process's staged
   bytes must be <= 0.6x the global-staging path at equal global
   batch, and the staged global arrays must be bit-identical
   shard-by-shard to ``multihost.stage_global_batch``;
8. a data-service fit and a legacy whole-batch fit on the SAME cluster
   must produce bit-identical params (the old path stays exact);
9. SIGKILL one host mid-fit: the survivor shrinks, resumes from the
   manifest's committed reader cursor, and the CONSUME beacon stream
   must show exactly one rewind — to that cursor — then run gapless to
   the end (zero replayed, zero skipped sample ids), finishing
   bit-exact vs an uninterrupted data-service run.

Exits 0 with a SKIP note for phases B/D when 2-process bring-up is
unavailable or times out; any contract violation exits non-zero.
"""

from __future__ import annotations

import json
import os
import signal  # noqa: F401 — SIGKILL drill uses Popen.kill()
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fixture():
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)

    conf = (NeuralNetConfiguration.builder()
            .n_in(4).lr(0.1).momentum(0.5).use_adagrad(False)
            .num_iterations(1).activation("tanh")
            .list(3).hidden_layer_sizes(8, 6)
            .override(2, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True).build())
    rng = np.random.RandomState(0)
    batches = [DataSet(rng.randn(16, 4).astype(np.float32),
                       np.eye(3, dtype=np.float32)[
                           rng.randint(0, 3, 16)])
               for _ in range(4)]
    return conf, batches


def phase_a(tmp: str) -> None:
    import numpy as np

    import jax
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.chaos import HostLossChaos
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
    from deeplearning4j_tpu.runtime.checkpoint import CheckpointManager
    from deeplearning4j_tpu.runtime.telemetry import registry
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)

    assert len(jax.devices()) >= 8, \
        f"gate needs 8 virtual devices, got {len(jax.devices())}"
    conf, batches = _fixture()

    def run(sub, fault=None):
        net = MultiLayerNetwork(conf).init(seed=9)
        drv = ResilientFit(net, ResilienceConfig(
            checkpoint_dir=os.path.join(tmp, sub), checkpoint_every=3),
            mesh=make_mesh(MeshSpec(data=8)), fault_hook=fault)
        drv.fit(batches, num_epochs=3, seed=7)
        return net, drv

    run("warm")                               # compiles banked
    registry.mark()
    net_ref, drv_ref = run("ref")
    delta = registry.compile_delta_since_mark()
    if delta != 0:
        print(f"[multihost-gate] FAIL: warmed sharded ResilientFit "
              f"compiled {delta} new program(s)")
        sys.exit(1)
    latest = drv_ref.manager.latest_step()
    drv_ref.manager.verify(latest)            # committed, not just present

    net_el, drv = run("elastic",
                      fault=HostLossChaos(at_step=7, host_index=1,
                                          n_hosts=2))
    ok = (drv.remeshes == 1 and drv.mesh.shape["data"] == 4
          and drv.elastic_accum == 2
          and np.array_equal(np.asarray(net_ref.params_flat()),
                             np.asarray(net_el.params_flat())))
    if not ok:
        print(f"[multihost-gate] FAIL: virtual host-loss drill "
              f"(remeshes={drv.remeshes}, mesh={drv.mesh and dict(drv.mesh.shape)}, "
              f"accum={drv.elastic_accum}, bit-exact="
              f"{np.array_equal(np.asarray(net_ref.params_flat()), np.asarray(net_el.params_flat()))})")
        sys.exit(1)
    print(f"[multihost-gate] phase A ok: warmed sharded fit "
          f"compile_delta=0, committed step {latest} verified, host-1 "
          f"loss re-meshed 8->4 (accum x2) bit-exact")


_WORKER = textwrap.dedent("""
    import hashlib, os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    import jax.numpy as jnp
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import multihost
    from deeplearning4j_tpu.runtime.telemetry import registry
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)
    cluster = multihost.initialize(
        multihost.ClusterConfig({coord!r}, 2, {pid}),
        attempts=2, timeout_s=120)
    cluster.barrier("gate_join")
    assert cluster.any_flag({pid} == 0) is True
    conf = (NeuralNetConfiguration.builder()
            .n_in(4).lr(0.1).momentum(0.5).use_adagrad(False)
            .num_iterations(1).activation("tanh")
            .list(3).hidden_layer_sizes(8, 6)
            .override(2, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True).build())
    rng = np.random.RandomState(0)
    batches = [DataSet(jnp.asarray(rng.randn(16, 4).astype(np.float32)),
                       jnp.asarray(np.eye(3, dtype=np.float32)[
                           rng.randint(0, 3, 16)]))
               for _ in range(4)]

    # warmed fit with CLUSTER-committed snapshots; second fit must be
    # compile-free on THIS process
    net = MultiLayerNetwork(conf).init(seed=9)
    ResilientFit(net, ResilienceConfig(
        checkpoint_dir={warm!r}, checkpoint_every=3,
        cluster_timeout_s=90, hb_interval_s=0.2, hb_timeout_s=10.0),
        cluster=cluster).fit(batches, num_epochs=2, seed=7)
    registry.mark()
    net = MultiLayerNetwork(conf).init(seed=9)
    ResilientFit(net, ResilienceConfig(
        checkpoint_dir={timed!r}, checkpoint_every=3,
        cluster_timeout_s=90, hb_interval_s=0.2, hb_timeout_s=10.0),
        cluster=cluster).fit(batches, num_epochs=2, seed=7)
    assert registry.compile_delta_since_mark() == 0, \\
        registry.compile_delta_since_mark()
    print("WARMED_OK", flush=True)

    # host-loss drill: process 1 is killed by the gate mid-fit; the
    # survivor detects, shrinks, restores, finishes
    net = MultiLayerNetwork(conf).init(seed=9)
    drv = ResilientFit(net, ResilienceConfig(
        checkpoint_dir={loss!r}, checkpoint_every=3,
        cluster_timeout_s=5, hb_interval_s=0.2, hb_timeout_s=1.5),
        cluster=cluster, fault_hook=lambda step: time.sleep(0.2))
    class Beacon:
        def iteration_done(self, model, it, score):
            print("STEP", it, flush=True)
    net.set_listeners([Beacon()])
    drv.fit(batches, num_epochs=4, seed=7)
    digest = hashlib.md5(np.asarray(
        net.params_flat()).tobytes()).hexdigest()
    print("DONE remeshes=%s members=%s hash=%s" % (
        drv.remeshes, drv.cluster.members, digest), flush=True)
    sys.stdout.flush()
    os._exit(0)   # peer is dead: skip the doomed distributed shutdown
""")


def phase_b(tmp: str) -> bool:
    """Returns True when the drill RAN (passed or exited the gate),
    False for a clean environment skip."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    dirs = {k: os.path.join(tmp, "b_" + k)
            for k in ("warm", "timed", "loss")}
    # stderr to FILES: the gate tails worker 1's stdout line-by-line,
    # and an undrained stderr PIPE would fill with jax chatter and
    # deadlock the child (the preemption_drill.py lesson)
    err_paths = [os.path.join(tmp, f"worker{pid}.stderr")
                 for pid in (0, 1)]
    procs = []
    for pid in (0, 1):
        with open(err_paths[pid], "w") as err_f:
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _WORKER.format(repo=REPO, coord=coord, pid=pid,
                                warm=dirs["warm"], timed=dirs["timed"],
                                loss=dirs["loss"])],
                stdout=subprocess.PIPE, stderr=err_f, text=True))

    # wait until worker 1 is mid-fit in the LOSS drill, then kill it
    deadline = time.time() + 240
    seen = False
    while time.time() < deadline and not seen:
        line = procs[1].stdout.readline()
        if not line and procs[1].poll() is not None:
            break
        if line.startswith("STEP"):
            seen = int(line.split()[1]) >= 2
    if not seen:
        for p in procs:
            p.kill()
        procs[1].communicate(timeout=30)
        err = open(err_paths[1]).read().strip()
        tail = err.splitlines()[-1][:160] if err else "no steps produced"
        print("[multihost-gate] SKIP phase B: 2-process bring-up "
              f"unavailable here ({tail})")
        return False
    procs[1].kill()
    try:
        out, _ = procs[0].communicate(timeout=300)
        err = open(err_paths[0]).read()
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("[multihost-gate] FAIL: survivor hung after host kill")
        sys.exit(1)
    if procs[0].returncode != 0:
        print(f"[multihost-gate] FAIL: survivor exited "
              f"{procs[0].returncode}:\n{err[-1500:]}")
        sys.exit(1)
    if "WARMED_OK" not in out:
        print(f"[multihost-gate] FAIL: warmed cluster fit did not "
              f"report compile_delta==0:\n{out[-500:]}\n{err[-500:]}")
        sys.exit(1)
    done = [ln for ln in out.splitlines() if ln.startswith("DONE")]
    if not done or "remeshes=1" not in done[0] \
            or "members=(0,)" not in done[0]:
        print(f"[multihost-gate] FAIL: survivor recovery wrong: {done}")
        sys.exit(1)

    # the warm run's snapshots are CLUSTER-committed: manifest names the
    # cluster and verifies from a fresh manager (what a relaunch sees)
    from deeplearning4j_tpu.runtime.checkpoint import CheckpointManager
    mgr = CheckpointManager(dirs["warm"])
    latest = mgr.latest_step()
    assert latest is not None, "no cluster-committed snapshot found"
    mgr.verify(latest)
    man = json.load(open(os.path.join(
        dirs["warm"], f"ckpt_{latest}.npz.manifest.json")))
    assert man["cluster"]["members"] == [0, 1], man

    # survivor's final params == uninterrupted single-process run
    import hashlib

    import numpy as np
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)
    conf, batches = _fixture()
    net = MultiLayerNetwork(conf).init(seed=9)
    ResilientFit(net, ResilienceConfig(
        checkpoint_dir=os.path.join(tmp, "ref2"),
        checkpoint_every=3)).fit(batches, num_epochs=4, seed=7)
    ref = hashlib.md5(np.asarray(
        net.params_flat()).tobytes()).hexdigest()
    if f"hash={ref}" not in done[0]:
        print(f"[multihost-gate] FAIL: survivor not bit-exact "
              f"({done[0]} vs ref {ref})")
        sys.exit(1)
    print(f"[multihost-gate] phase B ok: 2-process join + control "
          f"plane, warmed cluster fits compile_delta=0 per process, "
          f"cluster-committed step {latest} verified, SIGKILLed host "
          f"-> survivor re-mesh resume bit-exact")
    return True


_WORKER_D = textwrap.dedent("""
    import hashlib, os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.data_service import DataService
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import multihost
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
    from deeplearning4j_tpu.runtime.metrics import ingest_metrics
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)
    cluster = multihost.initialize(
        multihost.ClusterConfig({coord!r}, 2, {pid}),
        attempts=2, timeout_s=120)
    cluster.barrier("gate_join_d")
    conf = (NeuralNetConfiguration.builder()
            .n_in(4).lr(0.1).momentum(0.5).use_adagrad(False)
            .num_iterations(1).activation("tanh")
            .list(3).hidden_layer_sizes(8, 6)
            .override(2, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True).build())
    rng = np.random.RandomState(0)
    batches = [DataSet(rng.randn(16, 4).astype(np.float32),
                       np.eye(3, dtype=np.float32)[
                           rng.randint(0, 3, 16)])
               for _ in range(4)]

    # D1: per-host shard reads on the REAL spanning mesh (16 devices
    # across both processes).  The service must stage <= 0.6x the
    # global-staging bytes, and land arrays bit-identical shard-by-
    # shard to multihost.stage_global_batch (so the training math
    # cannot differ from the old path).
    mesh = make_mesh(MeshSpec(data=16))
    assert len(set(d.process_index
                   for d in mesh.devices.flat)) == 2, mesh
    svc = DataService.from_batches(batches, cluster=cluster, seed=7)
    svc.configure(mesh=mesh, cluster=cluster, pad_chunk=16,
                  dp_mode=True, spans=True)
    order = list(range(len(batches)))
    base = ingest_metrics.snapshot()["bytes_staged"]
    staged = [svc.staged(0, p, order) for p in order]
    per_host = ingest_metrics.snapshot()["bytes_staged"] - base
    svc.close()
    glob, equal = 0, True
    for ds, sg in zip(batches, staged):
        x, y = np.asarray(ds.features), np.asarray(ds.labels)
        glob += x.nbytes + y.nbytes
        xg, yg = multihost.stage_global_batch(x, y, mesh,
                                              cluster=cluster)
        for a, b in ((xg, sg.features), (yg, sg.labels)):
            sa = sorted(a.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
            sb = sorted(b.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
            equal = equal and len(sa) == len(sb) and all(
                np.array_equal(np.asarray(p.data), np.asarray(q.data))
                for p, q in zip(sa, sb))
    print("BYTES per_host=%d global=%d staged_equal=%d"
          % (per_host, glob, int(equal)), flush=True)

    # D2: data-service fit vs legacy whole-batch fit on the SAME live
    # cluster — the trajectories must be bit-identical
    def run(data, ckdir, **cfg_kw):
        net = MultiLayerNetwork(conf).init(seed=9)
        ResilientFit(net, ResilienceConfig(
            checkpoint_dir=ckdir, checkpoint_every=3,
            cluster_timeout_s=90, hb_interval_s=0.2,
            hb_timeout_s=10.0, **cfg_kw),
            cluster=cluster).fit(data, num_epochs=2, seed=7)
        return net, hashlib.md5(np.asarray(
            net.params_flat()).tobytes()).hexdigest()
    _, h_old = run(batches, {old!r}, data_service=False)
    _, h_new = run(DataService.from_batches(batches, cluster=cluster,
                                            seed=7), {new!r})
    print("PATHS match=%d hash=%s" % (int(h_old == h_new), h_new),
          flush=True)

    # D3: SIGKILL drill through the service.  Every staged position
    # emits a CONSUME beacon; the gate audits the stream across the
    # shrink/resume for zero replayed / zero skipped sample ids.
    svc3 = DataService.from_batches(batches, cluster=cluster, seed=7)
    _staged = svc3.staged
    def _audit(epoch, pos, order):
        print("CONSUME %d %d %d" % (epoch, pos, int(order[int(pos)])),
              flush=True)
        return _staged(epoch, pos, order)
    svc3.staged = _audit
    net = MultiLayerNetwork(conf).init(seed=9)
    drv = ResilientFit(net, ResilienceConfig(
        checkpoint_dir={loss!r}, checkpoint_every=3,
        cluster_timeout_s=5, hb_interval_s=0.2, hb_timeout_s=1.5),
        cluster=cluster, fault_hook=lambda step: time.sleep(0.2))
    class Beacon:
        def iteration_done(self, model, it, score):
            print("STEP", it, flush=True)
    net.set_listeners([Beacon()])
    drv.fit(svc3, num_epochs=4, seed=7)
    rs = getattr(drv, "_last_restore_meta", None)
    rs = rs.get("data_service") if rs else None
    assert rs is not None, "survivor resumed without reader state"
    print("RESTORED %d %d" % (rs["epoch"], rs["cursor"]), flush=True)
    ing = drv.manager.ingest_state()
    latest = drv.manager.latest_step()
    assert ing is not None and (ing["epoch"], ing["cursor"]) == \\
        divmod(latest, len(batches)), (ing, latest)
    digest = hashlib.md5(np.asarray(
        net.params_flat()).tobytes()).hexdigest()
    print("DONE remeshes=%s members=%s hash=%s ingest=1" % (
        drv.remeshes, drv.cluster.members, digest), flush=True)
    sys.stdout.flush()
    os._exit(0)   # peer is dead: skip the doomed distributed shutdown
""")


def phase_d(tmp: str) -> bool:
    """ISSUE-20 acceptance drill (module docstring items 7-9).
    Returns True when the drill RAN, False for a clean environment
    skip (no 2-process bring-up)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    dirs = {k: os.path.join(tmp, "d_" + k)
            for k in ("old", "new", "loss")}
    err_paths = [os.path.join(tmp, f"worker{pid}.d.stderr")
                 for pid in (0, 1)]
    procs = []
    for pid in (0, 1):
        with open(err_paths[pid], "w") as err_f:
            procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 _WORKER_D.format(repo=REPO, coord=coord, pid=pid,
                                  old=dirs["old"], new=dirs["new"],
                                  loss=dirs["loss"])],
                stdout=subprocess.PIPE, stderr=err_f, text=True))

    # wait until worker 1 is mid-fit in the kill drill, then kill it.
    # A BYTES beacon means bring-up SUCCEEDED — dying after that is a
    # real failure, not an environment skip.
    deadline = time.time() + 300
    seen = False
    brought_up = False
    while time.time() < deadline and not seen:
        line = procs[1].stdout.readline()
        if not line and procs[1].poll() is not None:
            break
        if line.startswith("BYTES"):
            brought_up = True
        if line.startswith("STEP"):
            seen = int(line.split()[1]) >= 2
    if not seen:
        for p in procs:
            p.kill()
        procs[1].communicate(timeout=30)
        err = open(err_paths[1]).read().strip()
        tail = err.splitlines()[-1][:160] if err else "no steps produced"
        if brought_up:
            print(f"[multihost-gate] FAIL: data-service drill died "
                  f"after cluster bring-up ({tail})")
            sys.exit(1)
        print("[multihost-gate] SKIP phase D: 2-process bring-up "
              f"unavailable here ({tail})")
        return False
    procs[1].kill()
    try:
        out, _ = procs[0].communicate(timeout=300)
        err = open(err_paths[0]).read()
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("[multihost-gate] FAIL: data-service survivor hung "
              "after host kill")
        sys.exit(1)
    if procs[0].returncode != 0:
        print(f"[multihost-gate] FAIL: data-service survivor exited "
              f"{procs[0].returncode}:\n{err[-1500:]}")
        sys.exit(1)
    lines = out.splitlines()

    def beacon(prefix):
        hit = [ln for ln in lines if ln.startswith(prefix)]
        if not hit:
            print(f"[multihost-gate] FAIL: no {prefix} beacon from the "
                  f"data-service survivor:\n{out[-500:]}")
            sys.exit(1)
        return hit[0]

    kv = dict(f.split("=") for f in beacon("BYTES").split()[1:])
    ratio = int(kv["per_host"]) / int(kv["global"])
    if ratio > 0.6 or kv["staged_equal"] != "1":
        print(f"[multihost-gate] FAIL: per-host staging contract "
              f"(per_host/global={ratio:.3f}, "
              f"staged_equal={kv['staged_equal']})")
        sys.exit(1)
    if "match=1" not in beacon("PATHS"):
        print(f"[multihost-gate] FAIL: data-service fit diverged from "
              f"the legacy staging path ({beacon('PATHS')})")
        sys.exit(1)
    done = beacon("DONE")
    if "remeshes=1" not in done or "members=(0,)" not in done \
            or "ingest=1" not in done:
        print(f"[multihost-gate] FAIL: survivor recovery wrong: {done}")
        sys.exit(1)

    # uninterrupted data-service reference (single process): final
    # params hash + the step -> batch schedule the CONSUME stream must
    # reproduce
    import hashlib

    import numpy as np
    from deeplearning4j_tpu.datasets.data_service import DataService
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)
    conf, batches = _fixture()
    n = len(batches)
    svc = DataService.from_batches(batches, seed=7)
    ref_cons = []
    orig = svc.staged
    svc.staged = lambda e, p, o: (
        ref_cons.append((int(e) * n + int(p), int(o[int(p)]))),
        orig(e, p, o))[1]
    net = MultiLayerNetwork(conf).init(seed=9)
    with svc:
        ResilientFit(net, ResilienceConfig(
            checkpoint_dir=os.path.join(tmp, "d_ref"),
            checkpoint_every=3)).fit(svc, num_epochs=4, seed=7)
    ref = hashlib.md5(np.asarray(
        net.params_flat()).tobytes()).hexdigest()
    if f"hash={ref}" not in done:
        print(f"[multihost-gate] FAIL: data-service survivor not "
              f"bit-exact ({done} vs ref {ref})")
        sys.exit(1)

    # zero replay / zero skip: exactly ONE rewind, to the manifest's
    # committed cursor, then gapless to the end; every consumed batch
    # matches the uninterrupted schedule
    cons = [tuple(int(v) for v in ln.split()[1:4])
            for ln in lines if ln.startswith("CONSUME")]
    steps = [e * n + p for e, p, _ in cons]
    re_, rc_ = (int(v) for v in beacon("RESTORED").split()[1:3])
    rewinds = [i for i in range(1, len(steps))
               if steps[i] <= steps[i - 1]]
    refmap = dict(ref_cons)
    ok = (len(rewinds) == 1
          and steps[rewinds[0]] == re_ * n + rc_
          and steps[:rewinds[0]] == list(range(rewinds[0]))
          and steps[rewinds[0]:] == list(range(re_ * n + rc_, 4 * n))
          and all(refmap[e * n + p] == b for e, p, b in cons))
    if not ok:
        print(f"[multihost-gate] FAIL: sample stream audit "
              f"(restored=({re_},{rc_}), rewinds="
              f"{[steps[i] for i in rewinds]}, steps={steps})")
        sys.exit(1)
    print(f"[multihost-gate] phase D ok: per-host staged bytes "
          f"{ratio:.2f}x global (<=0.6) bit-identical shards, service "
          f"vs legacy fit bit-exact, SIGKILLed host -> shrink resumed "
          f"at committed cursor ({re_},{rc_}) zero replay/skip, "
          f"bit-exact")
    return True


def phase_c() -> None:
    """Two-shape 4D drill (ISSUE 18 tentpole proof): the same CausalLM
    trained at two 3D mesh shapes differing ONLY in pipe degree —
    (2,2,2) on 8 chips vs (2,2,1) on 4 — must produce byte-identical
    final params (pipe moves the stacked-layer LAYOUT, never the
    reduction order), with the warmed steady-state fit showing
    ``compile_delta == 0`` and zero copy-on-donate warnings (donation
    survives the 4D layouts)."""
    import dataclasses
    import warnings

    import numpy as np
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import gpt
    from deeplearning4j_tpu.models.lm_fit import CausalLM
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
    from deeplearning4j_tpu.runtime.telemetry import registry

    cfg = dataclasses.replace(gpt.gpt_tiny(vocab_size=64, max_len=16),
                              hidden=32, n_layers=4, n_heads=4,
                              ffn_dim=64, compute_dtype="float32")
    rng = np.random.RandomState(0)
    batches = [DataSet(jnp.asarray(rng.randint(0, 64, (8, 16)), jnp.int32),
                       jnp.asarray(rng.randint(0, 64, (8, 16)), jnp.int32))
               for _ in range(2)]

    def fit(mesh):
        net = CausalLM(cfg, lr=0.05, momentum=0.9,
                       pipe_microbatches=2).init(0)
        net.fit_backprop(batches, num_epochs=2, mesh=mesh)
        return net

    mesh_a = make_mesh(MeshSpec(data=2, model=2, pipe=2),
                       devices=jax.devices()[:8])
    mesh_b = make_mesh(MeshSpec(data=2, model=2, pipe=1),
                       devices=jax.devices()[:4])
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        fit(mesh_a)                           # compiles banked
        registry.mark()
        net_a = fit(mesh_a)                   # warmed steady state
        delta = registry.compile_delta_since_mark()
        net_b = fit(mesh_b)
    donate = [w for w in caught if "donat" in str(w.message).lower()]
    if donate:
        print(f"[multihost-gate] FAIL: {len(donate)} copy-on-donate "
              f"warning(s) on the 4D fit: {donate[0].message}")
        sys.exit(1)
    if delta != 0:
        print(f"[multihost-gate] FAIL: warmed (2,2,2) fit compiled "
              f"{delta} new program(s)")
        sys.exit(1)
    pa = np.asarray(net_a.params_flat())
    pb = np.asarray(net_b.params_flat())
    if not (np.isfinite(pa).all() and np.array_equal(pa, pb)):
        print(f"[multihost-gate] FAIL: two-shape drill not bit-exact "
              f"(finite={np.isfinite(pa).all()}, "
              f"max|a-b|={np.abs(pa - pb).max()})")
        sys.exit(1)
    print("[multihost-gate] phase C ok: (2,2,2) vs (2,2,1) training "
          "bit-exact, warmed steady-state compile_delta=0, donation "
          "clean")


def main() -> int:
    with tempfile.TemporaryDirectory() as tmp:
        phase_a(tmp)
        if phase_b(tmp):
            phase_d(tmp)
        else:
            print("[multihost-gate] SKIP phase D: follows phase B skip")
    phase_c()
    print("[multihost-gate] ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
