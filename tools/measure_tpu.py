"""One-shot TPU measurement sweep — run when the axon tunnel is healthy.

Runs the headline benches in sequence in separate processes (the tunnel
serializes device access) and prints one JSON line per config plus a
word2vec depth-bucket A/B. Usage:  python tools/measure_tpu.py
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

AB_SNIPPET = r'''
import time, numpy as np, sys
sys.path.insert(0, "%s")
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, Word2VecConfig
rng = np.random.RandomState(0)
words = [f"w{i}" for i in range(2000)]
p = 1.0 / np.arange(1, 2001) ** 1.05; p /= p.sum()
sents = [" ".join(rng.choice(words, p=p, size=30)) for _ in range(1600)]
for tag, kw in (("db1", dict(depth_buckets=1)),
                ("db2", dict(depth_buckets=2)),
                ("db3", dict(depth_buckets=3)),
                ("exact", dict(pair_mode="exact")),
                ("exact_db2", dict(pair_mode="exact", depth_buckets=2))):
    cfg = Word2VecConfig(vector_size=100, window=5, epochs=2, negative=5,
                         use_hs=True, batch_size=16384, **kw)
    w = Word2Vec(sents, cfg); w.fit()
    float(np.asarray(w.syn0).ravel()[0])
    cold = Word2Vec(sents, cfg, cache=w.cache)
    t0 = time.perf_counter(); cold.fit()
    float(np.asarray(cold.syn0).ravel()[0])
    dt = time.perf_counter() - t0
    print(f'{{"metric": "w2v_ab_{tag}", '
          f'"words_per_sec": {96000 / dt:.0f}}}')
''' % REPO


def main() -> None:
    for cfg in ("probe", "bert", "resnet", "word2vec", "glove", "longctx",
                "longctx32k", "lenet"):
        r = subprocess.run(
            [sys.executable, f"{REPO}/bench.py", cfg],
            capture_output=True, text=True, timeout=1800)
        line = [l for l in r.stdout.splitlines() if l.startswith("{")]
        print(line[-1] if line else json.dumps(
            {"config": cfg, "error": r.stderr[-200:]}))
    r = subprocess.run([sys.executable, "-c", AB_SNIPPET],
                       capture_output=True, text=True, timeout=1800)
    print(r.stdout.strip() or json.dumps({"ab": "failed",
                                          "err": r.stderr[-200:]}))


if __name__ == "__main__":
    main()
