"""Incremental TPU measurement sweep — survives a flaky tunnel.

The axon tunnel flaps (up for minutes, down for hours).  A monolithic
sweep loses everything after the first drop, so this version:

  * keeps per-config state in ``TPU_SWEEP_STATE.json`` — a config is done
    once a result with ``platform == "tpu"`` is recorded; re-runs skip it;
  * probes the tunnel with a cheap matmul before every config and exits
    rc=1 the moment the link is dead (the watcher resumes polling instead
    of burning a 25-minute timeout on a hung subprocess);
  * runs each bench via ``bench.py --inner`` directly (no CPU fallback —
    a CPU row is worthless here and wastes the healthy window);
  * benefits from bench.py's persistent compilation cache: a config that
    timed out mid-compile restarts warm on the next window.

Exit codes: 0 = every config captured on TPU; 1 = tunnel down / partial.
Usage:  python tools/measure_tpu.py [config ...]   (default: all missing)
"""
import fcntl
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATE_PATH = os.path.join(REPO, "TPU_SWEEP_STATE.json")
STATE_LOCK = STATE_PATH + ".lock"
SWEEP_LOCK = os.path.join(REPO, "tools", "tpu_sweep.lock")

# (name, inner-timeout seconds).  Round-5 order = VERDICT r4 priority:
# 1. word2vec_device — the r4 engine (device pair mode + dense-scores
#    kernel) has never touched the TPU; cheapest, banked first;
# 2. lenet (r5 ingestion-inclusive engine) + glove (cheap);
# 3. the BERT MFU batch sweep (VERDICT #2: settle MFU >= 0.40);
# 4. the full 3-mode word2vec (the masked/exact comparison + per-mode
#    profile — big, so it must not starve the rows above);
# 5. the rest, cheapest-first.  bert/longctx are banked (skipped by
#    the no-arg watcher sweep) and sit last as explicit-re-run targets.
CONFIGS = [
    ("word2vec_device", 700),
    ("lenet", 600),
    ("glove", 900),
    ("bert_b64", 1200),
    ("bert_b128", 1200),
    ("bert_b256", 1200),
    ("bert_T512b32", 1500),
    ("word2vec", 1500),     # 3 pair modes x (warm+cold) since r4
    ("longctx32k", 1500),
    ("resnet", 1800),
    # space-to-depth stem variant (TPU stem trick)
    ("resnet_s2d", 1800),
    ("bert", 1200),
    ("longctx", 1200),
]

#: headline slot <- best of its sweep variants (same metric family).
#: word2vec_device is deliberately NOT promoted into the "word2vec"
#: slot: slot==config-key here, so promotion would mark the full
#: 3-mode config as captured and the watcher would never measure the
#: masked/exact modes (bench.py's family-suffix promotion handles the
#: artifact headline instead).
PROMOTIONS = {
    "bert": ("bert", "bert_b64", "bert_b128", "bert_b256"),
    "resnet": ("resnet", "resnet_s2d"),
}

# word2vec depth-bucket / exact-pair A/B (VERDICT r2 next-step #2): each
# variant is its own subprocess so a tunnel drop keeps earlier variants.
AB_VARIANTS = [
    ("ab_db1", "dict(depth_buckets=1)"),
    ("ab_db2", "dict(depth_buckets=2)"),
    ("ab_db3", "dict(depth_buckets=3)"),
    ("ab_exact", 'dict(pair_mode="exact")'),
    ("ab_exact_db2", 'dict(pair_mode="exact", depth_buckets=2)'),
    ("ab_device", 'dict(pair_mode="device")'),
]

AB_SNIPPET = r'''
import time, numpy as np, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_compilation_cache_dir", %(cache)r)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, Word2VecConfig
rng = np.random.RandomState(0)
# ~1M trained words so the tunnel's fixed per-call overhead (observed up
# to ~700 ms) stays below ~10%% of the cold-fit window — at the old
# 96k-word shape a 20%% variant difference drowned in link latency
N_SENT, SENT_LEN, EPOCHS = 16000, 30, 2
p = 1.0 / np.arange(1, 2001) ** 1.05; p /= p.sum()
ids = rng.choice(2000, p=p, size=(N_SENT, SENT_LEN))
sents = [" ".join(f"w{i}" for i in row) for row in ids]
cfg = Word2VecConfig(vector_size=100, window=5, epochs=EPOCHS, negative=5,
                     use_hs=True, batch_size=16384, **%(kw)s)
w = Word2Vec(sents, cfg); w.fit()
float(np.asarray(w.syn0).ravel()[0])
cold = Word2Vec(sents, cfg, cache=w.cache)
t0 = time.perf_counter(); cold.fit()
float(np.asarray(cold.syn0).ravel()[0])
dt = time.perf_counter() - t0
print('{"metric": "w2v_%(tag)s", "platform": "%%s", "words_per_sec": %%d}'
      %% (jax.devices()[0].platform, round(N_SENT * SENT_LEN * EPOCHS / dt)))
'''


def load_state() -> dict:
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def bank_row(name: str, obj: dict) -> dict:
    """Crash-proof banking: locked read-merge-write-verify of ONE row.

    Round-3 postmortem (VERDICT r3 weak #3): each sweep held its startup
    snapshot of the state dict and ``save_state`` wrote the WHOLE dict,
    so a stale concurrent sweep overwrote — and silently dropped — the
    word2vec row another sweep had just banked.  Now every bank takes an
    exclusive flock, re-reads the file, merges exactly one row, replaces
    atomically, and re-reads to verify the row landed.  Returns the
    merged state.  Raises if verification fails (caller must NOT print
    the row as banked)."""
    with open(STATE_LOCK, "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        state = load_state()
        state[name] = obj
        tmp = STATE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, STATE_PATH)
        check = load_state()
        if check.get(name) != obj:
            raise RuntimeError(f"bank verify failed for {name!r}")
        return check


def tunnel_up() -> bool:
    """Cheap end-to-end probe: backend init + matmul + value fetch."""
    code = ("import jax, jax.numpy as jnp\n"
            "assert jax.devices()[0].platform != 'cpu'\n"
            "x = jnp.ones((256, 256), jnp.bfloat16)\n"
            "print(float(jnp.ravel(x @ x)[0]))\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=150,
                           capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def last_json(stdout: str):
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            obj = json.loads(line)
            if isinstance(obj, dict):
                return obj
        except json.JSONDecodeError:
            continue
    return None


def _run_json(argv: list, timeout: int):
    """Run a subprocess expected to print a JSON result line; returns
    (obj, error) with exactly one of the two set."""
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    if r.returncode != 0:
        return None, f"rc={r.returncode}: " + \
            (r.stderr or r.stdout or "")[-300:]
    obj = last_json(r.stdout)
    if obj is None:
        return None, "no JSON: " + (r.stderr or r.stdout or "")[-300:]
    return obj, None


def run_bench(name: str, timeout: int):
    return _run_json([sys.executable, f"{REPO}/bench.py", "--inner", name],
                     timeout)


def run_ab(tag: str, kw: str):
    snippet = AB_SNIPPET % {"repo": REPO, "kw": kw, "tag": tag,
                            "cache": os.path.join(REPO, ".jax_cache")}
    return _run_json([sys.executable, "-c", snippet], 1200)


def main() -> None:
    if sys.argv[1:2] == ["--probe"]:
        sys.exit(0 if tunnel_up() else 1)
    # One sweep at a time, ever.  The watcher's flock only covered the
    # watcher loop; a manually-launched sweep could still race it (the
    # round-3 row-loss).  Held for the whole process lifetime.
    sweep_lk = open(SWEEP_LOCK, "w")
    try:
        fcntl.flock(sweep_lk, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except BlockingIOError:
        print(json.dumps({"abort": "another sweep is running"}), flush=True)
        sys.exit(1)
    only = set(sys.argv[1:])
    state = load_state()
    work = [(n, t, None) for n, t in CONFIGS] + \
           [(n, 0, kw) for n, kw in AB_VARIANTS]
    known = {w[0] for w in work}
    if only - known:
        print(json.dumps({"error": f"unknown configs: {sorted(only - known)}",
                          "known": sorted(known)}))
        sys.exit(2)
    if only:
        # explicitly named configs are ALWAYS re-measured (the path for
        # re-benching a config after an optimization lands); the no-arg
        # watcher sweep still skips banked rows
        work = [w for w in work if w[0] in only]
        pending = work
    else:
        pending = [w for w in work
                   if (state.get(w[0]) or {}).get("platform") != "tpu"]
    print(json.dumps({"done": len(work) - len(pending),
                      "pending": [w[0] for w in pending]}), flush=True)
    for name, timeout, kw in pending:
        if not tunnel_up():
            print(json.dumps({"abort": "tunnel down", "at": name}),
                  flush=True)
            sys.exit(1)
        obj, err = (run_ab(name, kw) if kw is not None
                    else run_bench(name, timeout))
        if obj is not None and obj.get("platform") == "tpu":
            state = bank_row(name, obj)  # verify-then-print, never reverse
            print(json.dumps(obj), flush=True)
        else:
            detail = err if obj is None else \
                f"platform={obj.get('platform')}"
            print(json.dumps({"config": name, "error": detail or "empty"}),
                  flush=True)
    state = load_state()
    # promote each headline slot to the best of its captured sweep
    # variants (value is per-chip throughput within one metric family)
    for slot, group in PROMOTIONS.items():
        cands = [state[k] for k in group
                 if (state.get(k) or {}).get("platform") == "tpu"]
        if not cands:
            continue
        best = max(cands, key=lambda r: r.get("value") or 0)
        if best.get("value") != (state.get(slot) or {}).get("value"):
            state = bank_row(slot, best)
            print(json.dumps({f"promoted_{slot}":
                              best.get("config_sig")}), flush=True)
    still = [w[0] for w in work
             if (state.get(w[0]) or {}).get("platform") != "tpu"]
    sys.exit(1 if still else 0)


if __name__ == "__main__":
    main()
