#!/bin/bash
# Test gate (reference role: .travis.yml:1-5 + pom.xml's qa profile).
#
#   tools/ci.sh          fast tier only (--fast: slow files skipped) ~<3 min
#   tools/ci.sh --slow   full suite (same as plain `pytest tests/`)  ~14 min
#
# The full suite was ~14 min serial by round 4 and silently stopped being
# run (VERDICT r4 weak #4); the split keeps the default loop fast and the
# full gate cheap enough to run before every snapshot commit.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

# Serial on purpose: this host has 1 CPU core, so pytest-xdist workers
# only add IPC + duplicate-jax-init overhead (measured: -n 4 was ~40%
# slower than serial for the fast tier).  A PLAIN pytest run (the
# driver/judge command) executes the whole suite; only ci.sh's default
# fast tier skips the slow files.
# Static analysis first: jaxlint machine-checks the JAX invariants
# (engine-routed jits, donation discipline, compat-only shard_map, pure
# host-sync-free steps, SPMD collective discipline, thread/lock/signal
# contracts, and since v4 the cross-module linking family: donation/
# spec/purity contracts checked at call sites against callee export
# summaries, plus the PR 17 page-refcount balance) — no point booting
# jax for the test tier if the tree already violates them.  Non-zero on
# any finding not in tools/jaxlint/baseline.json.  --format json emits
# file/line/rule/severity records plus summary_ms/link_ms pass timings;
# the exit code contract is identical to text mode.  The cache file
# makes repeat CI runs warm (summaries + per-file results persist).
echo "[ci] jaxlint (two-pass linked analysis)"
python -m tools.jaxlint deeplearning4j_tpu bench.py tools \
  --format json --jobs 4 --cache-file .jaxlint_ci_cache.json || exit 1

# Linked-analysis wall-clock budget: the v4 two-pass pipeline earns its
# keep only if linking stays cheap once warm — a WARM two-pass run must
# cost <= 1.5x a warm v3 single-pass run (small absolute grace for
# timer noise on this 1-core host), and must re-extract ZERO summaries.
# A broken summary/result cache shows up here as an 18 s cold re-link
# and fails the stage, not as a silent CI slowdown.
echo "[ci] jaxlint linked-analysis budget"
python - <<'EOF' || exit 1
import time
from pathlib import Path
from tools.jaxlint import rules  # noqa: F401 — registers the rule set
from tools.jaxlint.core import run_paths

paths = [Path("deeplearning4j_tpu"), Path("bench.py"), Path("tools")]
nolink = Path(".jaxlint_ci_nolink.json")
linked = Path(".jaxlint_ci_cache.json")   # warmed by the stage above
run_paths(paths, cache_path=nolink, link=False)          # warm v3 cache
t0 = time.perf_counter()
run_paths(paths, cache_path=nolink, link=False)
single = time.perf_counter() - t0
stats = {}
t0 = time.perf_counter()
run_paths(paths, cache_path=linked, stats=stats)
two_pass = time.perf_counter() - t0
budget = 1.5 * single + 0.25
print(f"[ci] warm single-pass {single * 1000:.0f} ms, "
      f"warm two-pass {two_pass * 1000:.0f} ms "
      f"(budget {budget * 1000:.0f} ms, "
      f"re-extracted {stats['summaries_extracted']} summaries)")
if stats["summaries_extracted"] != 0:
    raise SystemExit("[ci] warm run re-extracted summaries — "
                     "the summary cache is broken")
if two_pass > budget:
    raise SystemExit(f"[ci] linked analysis over budget: "
                     f"{two_pass * 1000:.0f} ms > {budget * 1000:.0f} ms")
EOF

# The analyzer's own type soundness: the linter that gates CI should
# not itself be type-unsound.  Zero-error config committed at
# tools/jaxlint/mypy.ini; gated on availability because the container
# image does not bake mypy in (no ad-hoc installs in CI — the tier-1
# test test_jaxlint_package_typechecks_under_mypy skips the same way).
echo "[ci] jaxlint type-check"
if python -c "import mypy" 2>/dev/null; then
  python -m mypy --config-file tools/jaxlint/mypy.ini tools/jaxlint \
    || exit 1
else
  echo "[ci] mypy not installed — skipping analyzer type-check"
fi

# Telemetry overhead gate: a tracer-off AND a tracer-on fit must show
# compile_delta_since_mark == 0 (the span tracer is host-side only and
# must never change a jitted program), and the journal's Perfetto
# conversion must stay valid.  Seconds on CPU; catches instrumentation
# accidentally landing inside a traced region.
echo "[ci] telemetry overhead gate"
JAX_PLATFORMS=cpu python -m tools.telemetry_gate || exit 1

# Serving chaos drill: under injected faults (poisoned dispatch, killed
# decode worker, stalled replica, exhausted KV page pool) every request
# must complete BIT-identical to an undisturbed run, replacement
# replicas must compile zero new programs, and the page allocator must
# end the drill with zero occupancy — the serving fault-tolerance
# contract.  ~15 s on CPU.
echo "[ci] serving chaos drill"
JAX_PLATFORMS=cpu python -m tools.serving_chaos_gate || exit 1

# Autotune smoke gate: a tiny kernel sweep must complete, persist a
# well-formed winner record, and a cold (memo-dropped) consult must hit
# the on-disk cache with zero re-sweeps and zero steady-state compiles —
# the MFU-campaign persistence contract.  Seconds on CPU.
echo "[ci] autotune smoke gate"
JAX_PLATFORMS=cpu python -m tools.autotune_gate || exit 1

# Preemption drill: SIGTERM against a live ResilientFit subprocess must
# produce a committed (manifest-verified) final snapshot, a clean exit
# 0, and a resumable checkpoint dir — the fault-tolerance contract
# ROADMAP item 4 exists for — plus the 2-process cluster drill (one
# member's SIGTERM drains BOTH at the same boundary; skip-aware).
# Seconds on CPU.
echo "[ci] preemption drill"
JAX_PLATFORMS=cpu python -m tools.preemption_drill || exit 1

# Multi-host gate: virtual 2-host drill (warmed sharded ResilientFit
# compile_delta==0, committed snapshot verify, injected host loss ->
# re-mesh resume bit-exact) + a REAL 2-process jax.distributed drill
# (join, control plane, cluster-committed snapshots, SIGKILLed host ->
# survivor restore) — skipping the 2-process half cleanly where
# bring-up is unavailable.  The ROADMAP item 2 contract.  Phase C is
# the ISSUE 18 two-shape 4D drill: training at two mesh shapes that
# differ only in pipe degree must be bit-exact, donation intact,
# compile_delta==0 when warmed.
echo "[ci] multihost gate (incl. two-shape 4D drill)"
JAX_PLATFORMS=cpu python -m tools.multihost_gate || exit 1

if [ "${1:-}" = "--slow" ]; then
  python -m pytest tests/ -q
else
  python -m pytest tests/ -q -x --fast
fi
