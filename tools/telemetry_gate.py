"""CI overhead gate for the run-telemetry layer (runtime/telemetry.py).

Machine-checks the tentpole's overhead contract on a real (tiny) fit:

1. warm the engine with one fit, ``registry.mark()``;
2. a second, tracer-OFF fit must show ``compile_delta_since_mark == 0``
   (telemetry plumbing at rest adds no trace);
3. a tracer-ON fit must ALSO show ``compile_delta_since_mark == 0``
   (enabling spans changes no jitted program — the tracer is host-side
   by construction) and must produce a journal whose chrome-trace
   conversion is valid Perfetto JSON with the fit span present;
4. the same off/on zero-compile contract for a warmed ``ResilientFit``
   with BACKGROUND snapshots (runtime/checkpoint.py
   ``AsyncCheckpointer``, the PR 8 default): staging copies, writer
   commits, and drains must never trace a new program;
5. the same off/on zero-compile contract for a warmed MIXED-PRECISION
   fit (``MultiLayerConfiguration.mixed_precision="bf16"``): the
   dynamic loss scale is a traced value threading the scanned epochs,
   so its transitions must never retrace;
6. the same off/on zero-compile contract for the continuous-batching
   decode loop (serving/decode.py): after ``DecodeEngine.warmup()``, a
   concurrent request mix — joins, EOS recycling, varied prompt
   lengths — must dispatch only cached programs with the tracer off AND
   on (the decode path's prefill/dispatch spans and join/complete
   events are host-side only);
6b. the same off/on zero-compile contract for the SERVING TIER 2
   decode loop: a warmed int8-weight + int8-KV engine with a prefix
   store must serve a mix of prefix MISSES (which read + store pages)
   and prefix HITS (which write cached pages into a slot) without a
   single new program — the dequant-fused executables, the page
   read/write pair, and every hit length are covered by ``warmup()``;
6c. the same off/on zero-compile contract for the SERVING TIER 3
   loop: a warmed PAGED + SPECULATIVE replica fleet behind the
   autoscaling router serving mixed traffic — page allocation/release,
   draft propose/verify rounds, prefix mounts — with a zero-downtime
   ``swap_weights`` in the MIDDLE of each pass: the swap drains,
   rebinds, and requantizes without tracing one new program;
6d. the same off/on zero-compile contract for a warmed DATA-SERVICE
   fit (``datasets/data_service.py``, the ISSUE 20 ingest layer) on an
   8-way data mesh with a RAGGED final batch: the per-host shard
   reads, prefetch staging, pad-to-chunk shapes, and reader-state
   checkpointing must dispatch only cached programs — tracer off AND
   on;
7. the same off/on zero-compile contract for a warmed DATA×MODEL fit
   (``models/lm_fit.CausalLM`` on a 2×4 mesh through the sharded_fit
   GSPMD builders): the model-sharded scanned dispatch, its staging
   device_puts, and the loss-scale/guard state threading must never
   retrace — the gate process forces 8 virtual CPU devices so the
   real sharded program runs.

Run by ``tools/ci.sh`` before the test tiers; exits non-zero on any
violation.  (jaxlint runs separately in ci.sh and must also stay clean —
the instrumentation sites live in linted packages.)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the data×model gate needs a real multi-device mesh; force the virtual
# 8-device CPU platform BEFORE any backend initializes (same pattern as
# tests/conftest.py)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def _net_and_data():
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .n_in(4).lr(0.1).num_iterations(1).activation("tanh")
            .list(2).hidden_layer_sizes(8)
            .override(1, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True).build())
    rng = np.random.RandomState(0)
    batches = [DataSet(rng.randn(16, 4).astype(np.float32),
                       np.eye(3, dtype=np.float32)[
                           rng.randint(0, 3, 16)])
               for _ in range(3)]
    return MultiLayerNetwork(conf).init(seed=1), batches


def _decode_requests(cb, np, n: int, seed: int) -> None:
    rng = np.random.RandomState(seed)
    handles = [cb.submit(rng.randint(1, 48, size=rng.randint(2, 12)),
                         max_tokens=4 + i % 4)
               for i in range(n)]
    for h in handles:
        h.result(120)


def _checkpoint_gate(registry, telemetry, net, batches) -> int:
    """Async-checkpoint loop gate: a WARMED ResilientFit with background
    snapshots (the PR 8 default) must dispatch only cached programs —
    the AsyncCheckpointer's device-side staging copies and its writer
    thread are outside every jitted region — with the tracer off AND
    on."""
    import tempfile

    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)

    def one_fit(seed):
        with tempfile.TemporaryDirectory() as ckdir:
            ResilientFit(net, ResilienceConfig(
                checkpoint_dir=ckdir, checkpoint_every=2,
                patience=10 ** 6)).fit(batches, num_epochs=2, seed=seed)

    one_fit(0)              # warm (same engine step as the fit gate,
    registry.mark()         # but snapshots + drain now ride along)

    assert not telemetry.enabled()
    one_fit(1)
    delta_off = registry.compile_delta_since_mark()
    if delta_off != 0:
        print(f"[telemetry-gate] FAIL: tracer-off async-checkpoint fit "
              f"compiled {delta_off} new program(s)")
        return 1

    telemetry.enable("telemetry-gate-ckpt")
    registry.mark()
    one_fit(2)
    delta_on = registry.compile_delta_since_mark()
    telemetry.disable()
    if delta_on != 0:
        print(f"[telemetry-gate] FAIL: tracer-on async-checkpoint fit "
              f"compiled {delta_on} new program(s) — checkpoint "
              "instrumentation leaked into a jitted region")
        return 1
    print(f"[telemetry-gate] ok: async-checkpoint loop compile_delta "
          f"off={delta_off} on={delta_on}")
    return 0


def _data_service_gate(registry, telemetry) -> int:
    """Data-service loop gate (ISSUE 20): a WARMED ResilientFit fed by
    the distributed data service on an 8-way data mesh — per-host shard
    reads, depth-k prefetch staging, a ragged final batch padding to
    the dispatch chunk, reader-state riding every snapshot — must
    dispatch only cached programs with the tracer off AND on.  The
    staged shapes must equal the legacy pad path's exactly; one extra
    shape here IS the regression this gate exists to catch."""
    import tempfile

    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
    from deeplearning4j_tpu.runtime.metrics import ingest_metrics
    from deeplearning4j_tpu.runtime.resilience import (ResilienceConfig,
                                                       ResilientFit)

    conf = (NeuralNetConfiguration.builder()
            .n_in(4).lr(0.1).num_iterations(1).activation("tanh")
            .list(2).hidden_layer_sizes(8)
            .override(1, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True).build())
    rng = np.random.RandomState(3)

    def batch(n):
        return DataSet(rng.randn(n, 4).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)])

    batches = [batch(16) for _ in range(3)] + [batch(12)]   # ragged tail
    net = MultiLayerNetwork(conf).init(seed=4)
    mesh = make_mesh(MeshSpec(data=8))

    def one_fit(seed):
        with tempfile.TemporaryDirectory() as ckdir:
            ResilientFit(net, ResilienceConfig(
                checkpoint_dir=ckdir, checkpoint_every=3,
                patience=10 ** 6, data_service=True),
                mesh=mesh).fit(batches, num_epochs=2, seed=seed)

    one_fit(0)              # warm (full + ragged staged shapes)
    registry.mark()

    assert not telemetry.enabled()
    one_fit(1)
    delta_off = registry.compile_delta_since_mark()
    if delta_off != 0:
        print(f"[telemetry-gate] FAIL: tracer-off data-service fit "
              f"compiled {delta_off} new program(s)")
        return 1

    telemetry.enable("telemetry-gate-ingest")
    registry.mark()
    one_fit(2)
    delta_on = registry.compile_delta_since_mark()
    telemetry.disable()
    if delta_on != 0:
        print(f"[telemetry-gate] FAIL: tracer-on data-service fit "
              f"compiled {delta_on} new program(s) — ingest "
              "instrumentation leaked into a jitted region")
        return 1
    snap = ingest_metrics.snapshot()
    if snap["batches_staged"] == 0 or snap["seed_agreements"] == 0:
        print("[telemetry-gate] FAIL: data-service fit booked no ingest "
              f"counters ({snap}) — the service was not in the loop")
        return 1
    print(f"[telemetry-gate] ok: data-service loop compile_delta "
          f"off={delta_off} on={delta_on}, "
          f"{snap['batches_staged']} batch(es) staged, depth_hw="
          f"{snap['depth_hw']}")
    return 0


def _mixed_precision_gate(registry, telemetry) -> int:
    """Mixed-precision loop gate: a WARMED bf16 fit (dynamic loss scale
    threading through the scanned epochs) must dispatch only cached
    programs with the tracer off AND on — the scale is a traced value in
    the updater-state slot, so its per-step transitions must never cost
    a retrace."""
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (LayerKind,
                                            NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .n_in(4).lr(0.1).num_iterations(1).activation("tanh")
            .list(2).hidden_layer_sizes(8)
            .override(1, kind=LayerKind.OUTPUT, n_out=3,
                      activation="softmax", loss_function="mcxent")
            .pretrain(False).backward(True)
            .mixed_precision("bf16").build())
    rng = np.random.RandomState(1)
    batches = [DataSet(rng.randn(16, 4).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
               for _ in range(3)]
    net = MultiLayerNetwork(conf).init(seed=2)

    net.fit_backprop(batches, num_epochs=1)      # warm the mp engine step
    registry.mark()

    assert not telemetry.enabled()
    net.fit_backprop(batches, num_epochs=1)
    delta_off = registry.compile_delta_since_mark()
    if delta_off != 0:
        print(f"[telemetry-gate] FAIL: tracer-off mixed-precision fit "
              f"compiled {delta_off} new program(s)")
        return 1

    telemetry.enable("telemetry-gate-mp")
    registry.mark()
    net.fit_backprop(batches, num_epochs=1)
    delta_on = registry.compile_delta_since_mark()
    telemetry.disable()
    if delta_on != 0:
        print(f"[telemetry-gate] FAIL: tracer-on mixed-precision fit "
              f"compiled {delta_on} new program(s) — loss-scale state "
              "leaked a retrace")
        return 1
    print(f"[telemetry-gate] ok: mixed-precision loop compile_delta "
          f"off={delta_off} on={delta_on}")
    return 0


def _model_parallel_gate(registry, telemetry) -> int:
    """data×model loop gate: a WARMED 2×4 GSPMD fit (CausalLM through
    the sharded_fit builders — model-sharded params, donated scanned
    dispatch, guard + loss-scale state threading) must dispatch only
    cached programs with the tracer off AND on."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import gpt
    from deeplearning4j_tpu.models.lm_fit import CausalLM
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 8:
        print("[telemetry-gate] skip: model-parallel loop needs 8 "
              f"devices, have {len(jax.devices())}")
        return 0
    cfg = dataclasses.replace(gpt.gpt_tiny(vocab_size=64, max_len=16),
                              hidden=32, n_layers=2, n_heads=4,
                              ffn_dim=64, compute_dtype="float32")
    rng = np.random.RandomState(0)
    batches = [DataSet(jnp.asarray(rng.randint(0, 64, (8, 16)),
                                   jnp.int32),
                       jnp.asarray(rng.randint(0, 64, (8, 16)),
                                   jnp.int32))
               for _ in range(3)]
    mesh = make_mesh(MeshSpec(data=2, model=4),
                     devices=jax.devices()[:8])
    lm = CausalLM(cfg, lr=0.05)

    def one_fit(seed):
        lm.init(seed=1)
        lm.fit_backprop(batches, num_epochs=1, seed=seed, mesh=mesh)

    one_fit(0)              # warm the data×model engine entry
    registry.mark()

    assert not telemetry.enabled()
    one_fit(1)
    delta_off = registry.compile_delta_since_mark()
    if delta_off != 0:
        print(f"[telemetry-gate] FAIL: tracer-off data×model fit "
              f"compiled {delta_off} new program(s)")
        return 1

    telemetry.enable("telemetry-gate-mp-mesh")
    registry.mark()
    one_fit(2)
    delta_on = registry.compile_delta_since_mark()
    telemetry.disable()
    if delta_on != 0:
        print(f"[telemetry-gate] FAIL: tracer-on data×model fit "
              f"compiled {delta_on} new program(s) — model-parallel "
              "instrumentation leaked into a jitted region")
        return 1
    print(f"[telemetry-gate] ok: data×model loop compile_delta "
          f"off={delta_off} on={delta_on}")
    return 0


def _decode_gate(registry, telemetry) -> int:
    import numpy as np

    from deeplearning4j_tpu.models import gpt
    from deeplearning4j_tpu.serving.decode import (ContinuousBatcher,
                                                   DecodeEngine)

    cfg = gpt.gpt_tiny(vocab_size=48, max_len=32)
    params = gpt.init_params(__import__("jax").random.key(0), cfg)
    eng = DecodeEngine(cfg, params, n_slots=3, buckets=(16, 32),
                       prefill_chunk=8)
    eng.warmup()
    with ContinuousBatcher(eng, default_max_tokens=4) as cb:
        registry.mark()

        # tracer OFF
        assert not telemetry.enabled()
        _decode_requests(cb, np, 6, seed=0)
        delta_off = registry.compile_delta_since_mark()
        if delta_off != 0:
            print(f"[telemetry-gate] FAIL: tracer-off decode loop "
                  f"compiled {delta_off} new program(s)")
            return 1

        # tracer ON
        telemetry.enable("telemetry-gate-decode")
        registry.mark()
        _decode_requests(cb, np, 6, seed=1)
        delta_on = registry.compile_delta_since_mark()
        telemetry.disable()
        if delta_on != 0:
            print(f"[telemetry-gate] FAIL: tracer-on decode loop "
                  f"compiled {delta_on} new program(s) — decode "
                  "instrumentation leaked into a jitted region")
            return 1
    print(f"[telemetry-gate] ok: decode loop compile_delta "
          f"off={delta_off} on={delta_on}")
    return 0


def _tier2_decode_gate(registry, telemetry) -> int:
    """Serving-tier-2 loop gate: a warmed int8-quantized + int8-KV +
    prefix-cached engine must serve misses (page harvest) and hits
    (page copy) compile-free with the tracer off AND on."""
    import numpy as np

    from deeplearning4j_tpu.models import gpt
    from deeplearning4j_tpu.serving.decode import (ContinuousBatcher,
                                                   DecodeEngine)

    cfg = gpt.gpt_tiny(vocab_size=48, max_len=32)
    params = gpt.init_params(__import__("jax").random.key(0), cfg)
    eng = DecodeEngine(cfg, params, n_slots=3, buckets=(16, 32),
                       prefill_chunk=8, quantize="int8",
                       kv_dtype="int8", prefix_cache=True,
                       label="gate-tier2")
    eng.warmup()
    rng = np.random.RandomState(3)
    shared = rng.randint(1, 48, size=16).astype(np.int32)

    def mixed_requests(cb, seed):
        r = np.random.RandomState(seed)
        handles = []
        for i in range(6):
            if i % 2:                     # prefix-sharing requests
                tail = r.randint(1, 48, size=r.randint(1, 6))
                prompt = np.concatenate([shared, tail.astype(np.int32)])
            else:                         # fresh prompts (misses)
                prompt = r.randint(1, 48, size=r.randint(2, 12))
            handles.append(cb.submit(prompt, max_tokens=3 + i % 3))
        for h in handles:
            h.result(120)

    with ContinuousBatcher(eng, default_max_tokens=4) as cb:
        mixed_requests(cb, seed=7)        # seed the store
        eng.flush_harvests()              # async harvests land first
        registry.mark()

        assert not telemetry.enabled()
        mixed_requests(cb, seed=8)
        delta_off = registry.compile_delta_since_mark()
        if delta_off != 0:
            print(f"[telemetry-gate] FAIL: tracer-off tier-2 decode "
                  f"loop compiled {delta_off} new program(s)")
            return 1

        telemetry.enable("telemetry-gate-tier2")
        registry.mark()
        mixed_requests(cb, seed=9)
        delta_on = registry.compile_delta_since_mark()
        telemetry.disable()
        if delta_on != 0:
            print(f"[telemetry-gate] FAIL: tracer-on tier-2 decode "
                  f"loop compiled {delta_on} new program(s) — "
                  "quantized/prefix instrumentation leaked into a "
                  "jitted region")
            return 1
    from deeplearning4j_tpu.runtime.metrics import decode_metrics
    hits = decode_metrics.snapshot()["prefix_hits"]
    if hits < 2:
        print(f"[telemetry-gate] FAIL: tier-2 loop recorded only "
              f"{hits} prefix hit(s) — the gate did not exercise the "
              "hit path")
        return 1
    print(f"[telemetry-gate] ok: tier-2 decode loop compile_delta "
          f"off={delta_off} on={delta_on}, {hits} prefix hit(s)")
    return 0


def _tier3_decode_gate(registry, telemetry) -> int:
    """Serving-tier-3 loop gate: a warmed PAGED + SPECULATIVE fleet
    behind the autoscaling router — prefix misses and hits, draft
    propose/verify rounds, and a mid-loop zero-downtime weight swap —
    must dispatch only cached programs with the tracer off AND on.
    The swap itself is part of the contract: same shapes, same
    executables, zero new programs."""
    import dataclasses

    import jax
    import numpy as np

    from deeplearning4j_tpu.models import gpt
    from deeplearning4j_tpu.runtime.metrics import decode_metrics
    from deeplearning4j_tpu.serving.decode import (ContinuousBatcher,
                                                   DecodeEngine,
                                                   PrefixCache)
    from deeplearning4j_tpu.serving.router import (AutoscalePolicy,
                                                   AutoscalingRouter)

    cfg = gpt.gpt_tiny(vocab_size=48, max_len=32)
    dcfg = dataclasses.replace(cfg, hidden=16, n_layers=1, n_heads=2,
                               ffn_dim=32)
    params = gpt.init_params(jax.random.key(0), cfg)
    dp = gpt.init_params(jax.random.key(1), dcfg)
    p_new = gpt.init_params(jax.random.key(5), cfg)
    store = PrefixCache()

    def factory():
        eng = DecodeEngine(cfg, params, n_slots=3, buckets=(16, 32),
                           prefill_chunk=8, paged=True,
                           draft=(dcfg, dp), draft_k=3,
                           prefix_cache=store, label="gate-tier3")
        eng.warmup()
        return ContinuousBatcher(eng, default_max_tokens=4)

    shared = np.random.RandomState(3).randint(1, 48, size=16) \
        .astype(np.int32)

    def mixed_requests(router, seed):
        r = np.random.RandomState(seed)
        handles = []
        for i in range(6):
            if i % 2:                     # prefix-sharing requests
                tail = r.randint(1, 48, size=r.randint(1, 6))
                prompt = np.concatenate([shared, tail.astype(np.int32)])
            else:                         # fresh prompts (misses)
                prompt = r.randint(1, 48, size=r.randint(2, 12))
            handles.append(router.submit(prompt, max_tokens=3 + i % 3))
        for h in handles:
            h.result(120)

    router = AutoscalingRouter(
        factory, AutoscalePolicy(min_replicas=2, max_replicas=2))
    try:
        mixed_requests(router, seed=7)    # warm joins + seed the store
        for b in router.batchers:
            b.engine.flush_harvests()
        registry.mark()

        assert not telemetry.enabled()
        mixed_requests(router, seed=8)
        router.swap_weights(p_new)        # mid-loop hot swap
        mixed_requests(router, seed=9)
        delta_off = registry.compile_delta_since_mark()
        if delta_off != 0:
            print(f"[telemetry-gate] FAIL: tracer-off tier-3 decode "
                  f"loop compiled {delta_off} new program(s)")
            return 1

        telemetry.enable("telemetry-gate-tier3")
        registry.mark()
        mixed_requests(router, seed=10)
        router.swap_weights(params)       # and back, tracer on
        mixed_requests(router, seed=11)
        delta_on = registry.compile_delta_since_mark()
        telemetry.disable()
        if delta_on != 0:
            print(f"[telemetry-gate] FAIL: tracer-on tier-3 decode "
                  f"loop compiled {delta_on} new program(s) — paged/"
                  "speculative/swap instrumentation leaked into a "
                  "jitted region")
            return 1
    finally:
        router.close()
    snap = decode_metrics.snapshot()
    if snap["draft_proposed"] < 1:
        print("[telemetry-gate] FAIL: tier-3 loop proposed no draft "
              "tokens — the speculative path did not run")
        return 1
    if snap["swaps_completed"] < 2:
        print(f"[telemetry-gate] FAIL: tier-3 loop completed only "
              f"{snap['swaps_completed']} swap(s), expected 2")
        return 1
    print(f"[telemetry-gate] ok: tier-3 decode loop compile_delta "
          f"off={delta_off} on={delta_on}, accept_rate="
          f"{snap['draft_accept_rate']}, {snap['swaps_completed']} "
          "swap(s)")
    return 0


def main() -> int:
    from deeplearning4j_tpu.runtime import telemetry

    registry = telemetry.registry
    net, batches = _net_and_data()

    # 1) warm every program this gate will dispatch
    net.fit_backprop(batches, num_epochs=1)
    registry.mark()

    # 2) tracer OFF: zero compile delta
    assert not telemetry.enabled()
    net.fit_backprop(batches, num_epochs=1)
    delta_off = registry.compile_delta_since_mark()
    if delta_off != 0:
        print(f"[telemetry-gate] FAIL: tracer-off fit compiled "
              f"{delta_off} new program(s)")
        return 1

    # 3) tracer ON: still zero compile delta, and a valid trace export
    tracer = telemetry.enable("telemetry-gate")
    registry.mark()
    net.fit_backprop(batches, num_epochs=1)
    delta_on = registry.compile_delta_since_mark()
    if delta_on != 0:
        print(f"[telemetry-gate] FAIL: tracer-on fit compiled "
              f"{delta_on} new program(s) — instrumentation leaked into "
              "a jitted region")
        return 1

    with tempfile.TemporaryDirectory() as d:
        journal = tracer.export_journal(
            os.path.join(d, "gate.jsonl"), snapshot=registry.snapshot())
        records = telemetry.read_journal(journal)
        payload = telemetry.chrome_trace(records, run_id=tracer.run_id)
        # valid Perfetto input: a traceEvents list that survives a JSON
        # round-trip, with the fit span among the complete slices
        payload = json.loads(json.dumps(payload))
        slices = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
        if not any(e["name"] == "multilayer.fit" for e in slices):
            print("[telemetry-gate] FAIL: no multilayer.fit span in the "
                  "exported trace")
            return 1
    telemetry.disable()
    print(f"[telemetry-gate] ok: compile_delta off={delta_off} "
          f"on={delta_on}, {len(records)} journal record(s)")
    rc = _checkpoint_gate(registry, telemetry, net, batches)
    if rc:
        return rc
    rc = _data_service_gate(registry, telemetry)
    if rc:
        return rc
    rc = _mixed_precision_gate(registry, telemetry)
    if rc:
        return rc
    rc = _model_parallel_gate(registry, telemetry)
    if rc:
        return rc
    rc = _decode_gate(registry, telemetry)
    if rc:
        return rc
    rc = _tier2_decode_gate(registry, telemetry)
    if rc:
        return rc
    return _tier3_decode_gate(registry, telemetry)


if __name__ == "__main__":
    sys.exit(main())
