"""Distributed training over device meshes.

This package replaces ALL FOUR of the reference's distributed runtimes
(SURVEY.md §5.8 — Akka/Hazelcast param server, Spark parameter averaging,
YARN IterativeReduce BSP, ZooKeeper config) with the TPU-native design:

- data plane: XLA collectives (psum/pmean/all_gather/reduce_scatter/
  ppermute/all_to_all) compiled over ICI within a slice and DCN across
  slices, expressed via ``jax.sharding.Mesh`` + ``shard_map``/``pjit``;
- control plane: a thin in-process/host coordinator (``StateTracker``
  parity) for job routing, heartbeats, and async (Hogwild) updates — the
  data plane no longer needs a parameter server.

Axes convention (mesh.py): ``data`` (DP), ``model`` (TP), ``pipe`` (PP),
``seq`` (SP/ring attention), ``expert`` (EP).
"""

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec, make_mesh, auto_data_mesh, mesh_signature, local_batch_size,
    pad_global_batch, DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
    EXPERT_AXIS,
)
from deeplearning4j_tpu.parallel.data_parallel import (  # noqa: F401
    DataParallelTrainer, ParameterAveragingTrainer,
)
from deeplearning4j_tpu.parallel.coordinator import (  # noqa: F401
    Job, StateTracker, WorkerRecord,
)
