"""Shared sharded-fit step builder: ONE XLA dispatch per fit, mesh-wide.

This is the junction the scanned-epoch engine (nn/multilayer.py, PR 1)
and the data-parallel trainers (parallel/data_parallel.py) meet at.
Before it, ``DataParallelTrainer.fit`` dispatched one program per batch —
re-paying the host->device round trip the scanned engine was built to
eliminate — and ``MultiLayerNetwork.fit`` was single-device only.  Both
now hand a PER-SHARD step function to the builders here and get back a
compiled program that:

- shards the batch axis over the mesh's ``data`` axis (``shard_map``
  via the compat shim) with params/updater state replicated;
- scans the step over stacked batches and again over epochs, so a whole
  fit is ONE device dispatch (``build_scanned_epochs``) — or keeps the
  per-batch dispatch shape for streaming ingestion
  (``build_sharded_step``);
- routes through ``runtime/compile_cache.cached_jit`` with params +
  updater state donated, exactly like the single-device engine steps.

The step function owns its collectives (psum/pmean over ``data``) and
its guard semantics: a skip decision must be computed from COLLECTIVE
values (post-psum grads/score) so every replica skips identically and
replicated params never diverge.

data×model×pipe(×expert) meshes (the 4D-parallelism tentpole): passing
``param_specs`` (a pytree of ``PartitionSpec`` over the params, e.g.
``models/transformer.shard_specs`` — attention heads and MLP hidden
over ``model``, embeddings over vocab, the stacked layer axis split
into contiguous GPipe stages over ``pipe``) switches both builders to
GSPMD mode: the step is a GLOBAL-view function (no hand-written psums
— XLA inserts the collectives from the shardings), params and updater
state are laid out with ``NamedSharding`` from the specs instead of
replicated, the batch stays sharded over ``data``, and donation
aliases each weight shard in place on its own device.  The step MAY
nest explicit ``shard_map`` regions for the manual-collective kernels
— ring attention over ``seq`` (ops/pallas_attention.make_attn_fn picks
it at trace time), the MoE all_to_all dispatch over ``expert``
(parallel/expert.make_gspmd_moe_ffn) — GSPMD and the manual regions
compose inside one jitted program.  Because every value in a GSPMD
program is logically GLOBAL, the PR 2 guard-skip verdict and the PR 11
loss-scale transition are replica-consistent across ALL axes by
construction — there is one verdict, not one per shard.  A mesh-shape
change that only moves the ``pipe`` degree is a pure LAYOUT change
(per-layer math and reduction order are untouched), so training the
same schedule at different pipe degrees is bit-exact — the property
the two-shape multihost drill gates.

Engine keys: callers that want cross-instance sharing pass
``engine_key`` including ``mesh.mesh_signature(mesh)`` — mesh shape AND
device ids — so two meshes never silently share a compiled executable
(a 2×4 data×model mesh and an 8×1 data mesh over the same devices are
different signatures, hence different entries).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Hashable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.compat import shard_map
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS
from deeplearning4j_tpu.runtime import compile_cache, telemetry

PyTree = Any
#: shard_step(params, ustate, batch, key, it) -> (params, ustate, score,
#: skipped) — written against LOCAL shards, collectives over DATA_AXIS
ShardStep = Callable[..., Tuple[PyTree, PyTree, jax.Array, jax.Array]]

#: scanned-path budget: stacking a whole batch list on device is only a
#: win while it comfortably fits in HBM; above this the callers stream
#: per-batch instead (same number MultiLayerNetwork.SCAN_MAX_DATASET_BYTES
#: has used since PR 1)
SCAN_MAX_DATASET_BYTES = 256 * 1024 * 1024


# ---------------------------------------------------------------------------
# mixed precision: bf16 compute / fp32 master + dynamic loss scaling
# ---------------------------------------------------------------------------
# The policy half of ``MultiLayerConfiguration.mixed_precision``: the
# step builders (nn/multilayer._build_dp_machinery) cast params to the
# compute dtype INSIDE the objective — grads flow back through the cast
# as fp32, so master params and every updater accumulator stay fp32 —
# and thread the scale state below through the scanned epochs alongside
# the updater state.  The skip-on-overflow decision rides the PR 2 guard
# (``resilience.guard_update``) on the POST-psum grads, so under a mesh
# every replica halves (or grows) the scale identically and replicated
# state never diverges; all transitions are ``jnp.where`` selects, never
# traced branches (jaxlint's divergent-branch rule stays clean).

#: initial dynamic loss scale (2^15 — the classic mixed-precision seed;
#: bf16's fp32-sized exponent makes overflow rare, so the scale mostly
#: idles at its cap, but a genuine overflow still halves it and skips)
LOSS_SCALE_INIT = 2.0 ** 15
#: floor/cap the dynamic scale walks between
LOSS_SCALE_MIN = 1.0
LOSS_SCALE_MAX = 2.0 ** 24
#: consecutive finite steps before the scale doubles
LOSS_SCALE_GROWTH_INTERVAL = 200


def init_loss_scale() -> dict:
    """Fresh dynamic-loss-scale state: the scale itself plus the count
    of consecutive good (non-skipped) steps since the last change."""
    return {"scale": jnp.float32(LOSS_SCALE_INIT),
            "good_steps": jnp.int32(0)}


def next_loss_scale(state: dict, skipped) -> dict:
    """One dynamic-loss-scale transition from a step's guard verdict
    (``skipped``: int32/bool scalar, 1 = update dropped on overflow):
    halve on skip (floored), double after ``LOSS_SCALE_GROWTH_INTERVAL``
    consecutive good steps (capped).  Pure ``jnp.where`` — one program
    for both outcomes, and the verdict is already collective under a
    mesh, so the state is replica-consistent by construction."""
    bad = jnp.asarray(skipped) > 0
    good = jnp.where(bad, 0, state["good_steps"] + 1)
    grow = good >= LOSS_SCALE_GROWTH_INTERVAL
    scale = jnp.where(
        bad, jnp.maximum(state["scale"] * 0.5, LOSS_SCALE_MIN),
        jnp.where(grow, jnp.minimum(state["scale"] * 2.0, LOSS_SCALE_MAX),
                  state["scale"]))
    return {"scale": scale,
            "good_steps": jnp.where(grow, 0, good).astype(jnp.int32)}


def mp_cast(tree: PyTree, dtype=None) -> PyTree:
    """Compute-dtype view of an fp32 master pytree: float32 leaves cast
    to ``dtype`` (default bfloat16), everything else (ints, bools,
    already-low-precision leaves) untouched.  Differentiating THROUGH
    this cast is what keeps grads fp32 against fp32 masters."""
    dtype = dtype or jnp.bfloat16
    return jax.tree.map(
        lambda a: a.astype(dtype)
        if getattr(a, "dtype", None) == jnp.float32 else a, tree)


def _with_dispatch_span(compiled, label: str, scanned: bool):
    """HOST-side telemetry shim around an already-compiled engine
    callable: every dispatch gets a ``dp.dispatch`` span (submission
    wall time — XLA execution is async; the caller's post-dispatch sync
    is where the remainder lands).  Outside the jitted region by
    construction, and a disabled tracer costs one global read."""
    def dispatch_traced(*args, **kwargs):
        tr = telemetry.get_tracer()
        if tr is None:
            return compiled(*args, **kwargs)
        with tr.span("dp.dispatch", label=label, scanned=scanned):
            return compiled(*args, **kwargs)

    # preserve the engine-callable surface callers rely on
    dispatch_traced.engine_label = getattr(compiled, "engine_label", label)
    dispatch_traced.jitted = getattr(compiled, "jitted", None)
    return dispatch_traced


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Placement for ONE global batch: leading (example) axis over
    ``data``, everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def stacked_sharding(mesh: Mesh) -> NamedSharding:
    """Placement for a STACKED batch tensor [NB, B, ...]: the scan axis
    replicated, the example axis sharded over ``data``."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def spec_axis_names(specs: PyTree):
    """Every mesh axis name referenced by a ``PartitionSpec`` tree."""
    names = set()
    for s in jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)):
        if not isinstance(s, P):
            continue
        for entry in s:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                names.add(ax)
    return names


def validate_specs_against_mesh(mesh: Mesh, specs: PyTree,
                                what: str = "param_specs") -> None:
    """Every axis a spec tree names must be a declared axis of ``mesh``
    — the runtime twin of jaxlint's ``spec-axis-outside-mesh`` rule.  A
    ``pipe`` spec consumed against a mesh built without a ``pipe`` axis
    would otherwise surface as an opaque XLA partitioning error (or,
    worse, a silent replication); here it fails at build time naming
    the spec axis and the mesh's actual axes."""
    missing = sorted(spec_axis_names(specs) - set(mesh.axis_names))
    if missing:
        raise ValueError(
            f"{what} names mesh axes {missing} that the mesh does not "
            f"declare (mesh axes: {tuple(mesh.axis_names)}) — build the "
            f"mesh with those axes (parallel/mesh.make_mesh declares "
            f"all of data/model/pipe/seq/expert) or drop them from the "
            f"specs")


def named_shardings(mesh: Mesh, specs: PyTree) -> PyTree:
    """``PartitionSpec`` (prefix) tree -> ``NamedSharding`` tree over
    ``mesh`` — the layout half of GSPMD mode.  ``specs=None`` means
    fully replicated."""
    if specs is None:
        return NamedSharding(mesh, P())
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def _gspmd_shardings(mesh: Mesh, param_specs: PyTree, ustate_specs: PyTree,
                     batch_specs: PyTree):
    """(in_shardings, out_shardings) for a GSPMD-mode step signature
    ``(params, ustate, batch, key, it) -> (params, ustate, score,
    skipped)``: params/ustate per their spec trees, batch per
    ``batch_specs``, scalars replicated.  ``ustate_specs`` defaults to
    ``param_specs`` (updater accumulators mirror the weights they
    smooth)."""
    for what, tree in (("param_specs", param_specs),
                       ("ustate_specs", ustate_specs),
                       ("batch_specs", batch_specs)):
        if tree is not None:
            validate_specs_against_mesh(mesh, tree, what)
    psh = named_shardings(mesh, param_specs)
    ush = named_shardings(
        mesh, ustate_specs if ustate_specs is not None else param_specs)
    bsh = named_shardings(mesh, batch_specs)
    repl = NamedSharding(mesh, P())
    return (psh, ush, bsh, repl, repl), (psh, ush, repl, repl)


def _build_gspmd_step(shard_step, mesh, batch_specs, label, engine_key,
                      donate, param_specs, ustate_specs):
    in_sh, out_sh = _gspmd_shardings(mesh, param_specs, ustate_specs,
                                     batch_specs)
    return _with_dispatch_span(
        compile_cache.cached_jit(
            shard_step, key=engine_key, label=label,
            in_shardings=in_sh, out_shardings=out_sh,
            donate_argnums=(0, 1) if donate else ()),
        label, scanned=False)


def _build_shardmap_step(shard_step, mesh, batch_specs, label, engine_key,
                         donate, param_specs, ustate_specs):
    sharded = shard_step if mesh is None else shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(), P(), batch_specs, P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return _with_dispatch_span(
        compile_cache.cached_jit(
            sharded, key=engine_key, label=label,
            donate_argnums=(0, 1) if donate else ()),
        label, scanned=False)


def build_sharded_step(shard_step: ShardStep, mesh: Optional[Mesh], *,
                       batch_specs: PyTree = None, label: str,
                       engine_key: Optional[Hashable] = None,
                       donate: bool = True, param_specs: PyTree = None,
                       ustate_specs: PyTree = None):
    """Per-batch dispatch shape (streaming loops): returns a compiled
    ``fn(params, ustate, batch, key, it)``.  ``batch_specs`` is a pytree
    of ``PartitionSpec`` matching ``batch`` (e.g. ``(P('data'),
    P('data'), P())`` for (x, y, n_valid)).  ``mesh=None`` compiles the
    step unsharded (the step must then avoid collectives — e.g. the
    grad-accumulation-only path).

    ``param_specs`` switches to GSPMD mode (module docstring): the step
    must then be a GLOBAL-view function — its params arrive laid out
    per the specs, its batch sharded per ``batch_specs``, and XLA owns
    the collectives.  ``ustate_specs`` defaults to ``param_specs``."""
    build = (_build_gspmd_step
             if mesh is not None and param_specs is not None
             else _build_shardmap_step)
    return build(shard_step, mesh, batch_specs, label, engine_key, donate,
                 param_specs, ustate_specs)


def build_scanned_epochs(shard_step: ShardStep, mesh: Optional[Mesh], *,
                         batch_specs: PyTree = None, label: str,
                         engine_key: Optional[Hashable] = None,
                         donate: bool = True, param_specs: PyTree = None,
                         ustate_specs: PyTree = None):
    """The single-dispatch fit: ``fn(params, ustate, batches, key, it0,
    num_epochs)`` scans ``shard_step`` over stacked batches [NB, B, ...]
    and again over epochs — one host->device round trip for the whole
    fit, params/updater state donated in place, per-step scores and
    guard-skip flags returned as [num_epochs, NB] for host replay.

    ``num_epochs`` is static (retrace per value, same contract as the
    single-device ``train_epochs``).  ``mesh=None`` keeps the same
    double scan without the shard_map wrap (grad-accumulation on one
    device).  ``param_specs`` switches to GSPMD mode exactly like
    ``build_sharded_step`` — the model-sharded layout threads through
    BOTH scans (the carry keeps each weight shard resident on its
    device across every step of every epoch)."""

    def epochs_body(params, ustate, batches, key, it0, *, num_epochs):
        def body(carry, batch):
            p, u, it = carry
            p, u, score, skipped = shard_step(p, u, batch, key, it)
            return (p, u, it + 1), (score, skipped)

        def epoch_body(carry, _):
            return lax.scan(body, carry, batches)

        (params, ustate, _), (scores, skips) = lax.scan(
            epoch_body, (params, ustate, it0), None, length=num_epochs)
        return params, ustate, scores, skips

    if mesh is not None and param_specs is not None:
        # GSPMD: the same double scan, compiled with the param/ustate
        # layout pinned by in/out shardings; the stacked batch rides
        # with the scan axis replicated and the example axis over `data`
        stacked_specs = jax.tree.map(lambda s: P(None, *s), batch_specs,
                                     is_leaf=lambda x: isinstance(x, P))
        in_sh, out_sh = _gspmd_shardings(mesh, param_specs, ustate_specs,
                                         stacked_specs)

        def epochs_global(params, ustate, batches, key, it0, num_epochs):
            return epochs_body(params, ustate, batches, key, it0,
                               num_epochs=num_epochs)

        return _with_dispatch_span(
            compile_cache.cached_jit(
                epochs_global, key=engine_key, label=label,
                static_argnums=(5,),
                in_shardings=in_sh, out_shardings=out_sh,
                donate_argnums=(0, 1) if donate else ()),
            label, scanned=True)

    if mesh is None:
        def epochs(params, ustate, batches, key, it0, num_epochs):
            return epochs_body(params, ustate, batches, key, it0,
                               num_epochs=num_epochs)
    else:
        # the scan (stacking) axis rides ahead of each batch spec
        stacked_specs = jax.tree.map(lambda s: P(None, *s), batch_specs,
                                     is_leaf=lambda x: isinstance(x, P))

        def epochs(params, ustate, batches, key, it0, num_epochs):
            # num_epochs is jit-static, so binding it BEFORE shard_map
            # keeps the shard_map signature all-arrays (a static python
            # int has no PartitionSpec)
            sharded = shard_map(
                functools.partial(epochs_body, num_epochs=num_epochs),
                mesh=mesh,
                in_specs=(P(), P(), stacked_specs, P(), P()),
                out_specs=(P(), P(), P(), P()),
                check_vma=False,
            )
            return sharded(params, ustate, batches, key, it0)

    return _with_dispatch_span(
        compile_cache.cached_jit(
            epochs, key=engine_key, label=label, static_argnums=(5,),
            donate_argnums=(0, 1) if donate else ()),
        label, scanned=True)
