"""Library work performers for the scaleout runner.

Reference parity: the Akka runtime ships its flagship workloads as
library components, not test helpers —
``scaleout/perform/BaseMultiLayerNetworkWorkPerformer.java`` (setup
rebuilds the net from a JSON conf, perform = ``fit(DataSet)`` then
``job.setResult(params())``, update = ``setParams``) and
``scaleout/perform/NeuralNetWorkPerformer.java`` (same for one pretrain
layer), aggregated by ``scaleout/aggregator/INDArrayAggregator.java``
(running parameter average).

Each performer here is reconstructible from a serializable spec (the conf
JSON), which is what lets the multi-process runner start performers in
worker processes from a string — the analog of the reference's reflective
``WorkerPerformerFactory.WORKER_PERFORMER`` class-name key.
"""

from __future__ import annotations

from typing import Any

from deeplearning4j_tpu.parallel import scaleout as so
from deeplearning4j_tpu.parallel.coordinator import Job


class MultiLayerNetworkPerformer(so.WorkerPerformer):
    """Fit a MultiLayerNetwork on each job's DataSet shard and ship the
    trained params back (BaseMultiLayerNetworkWorkPerformer.java parity).

    ``conf`` may be a ``MultiLayerConfiguration`` or its JSON string —
    the JSON form mirrors the reference's setup-from-serialized-conf and
    is what cross-process workers receive.
    """

    def __init__(self, conf: Any, num_epochs: int = 10, seed: int = 0):
        from deeplearning4j_tpu.nn.conf.configuration import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(conf, str):
            conf = MultiLayerConfiguration.from_json(conf)
        self.net = MultiLayerNetwork(conf).init(seed=seed)
        self.num_epochs = num_epochs

    def perform(self, job: Job) -> None:
        # mesh=None: a scaleout performer IS one data-parallel worker —
        # the control plane owns the parallelism, and auto-sharding each
        # worker's local fit over the whole device set would nest DP
        # inside DP (N workers contending for the same mesh every step)
        self.net.fit_backprop(job.work, num_epochs=self.num_epochs,
                              mesh=None)
        job.result = self.net.params

    def update(self, params) -> None:
        self.net.params = params


class PretrainLayerPerformer(so.WorkerPerformer):
    """Greedy layer-wise pretraining of a configured net on each job's
    DataSet (NeuralNetWorkPerformer.java parity — the reference trains
    pretrain layers per job, no supervised head)."""

    def __init__(self, conf: Any, seed: int = 0):
        from deeplearning4j_tpu.nn.conf.configuration import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(conf, str):
            conf = MultiLayerConfiguration.from_json(conf)
        self.net = MultiLayerNetwork(conf).init(seed=seed)
        self.seed = seed

    def perform(self, job: Job) -> None:
        self.net.pretrain(job.work, seed=self.seed)
        job.result = self.net.params

    def update(self, params) -> None:
        self.net.params = params


class ParameterAveragingAggregator(so.WorkAccumulator):
    """Running average of param pytrees (INDArrayAggregator.java:35-60
    parity).  Identical math to WorkAccumulator; the alias exists so the
    flagship workload reads like the reference topology."""
