"""Transport-agnostic scaleout SPI + in-process distributed runner.

Reference parity, three pieces:

1. The SPI of ``deeplearning4j-scaleout-api`` (SURVEY.md §2.2): ``Job`` /
   ``JobIterator`` (scaleout/job/JobIterator.java:24), ``WorkerPerformer``
   (scaleout/perform/WorkerPerformer.java:27), ``JobAggregator`` +
   ``WorkAccumulator`` (scaleout/aggregator/), ``UpdateSaver`` /
   ``WorkRetriever`` (param blobs / per-worker datasets stored off-tracker),
   ``WorkRouter`` policies (IterativeReduce = synchronous rounds, HogWild =
   always-send async), ``Updateable``.

2. ``DistributedRunner`` — the in-process equivalent of the Akka topology
   (DeepLearning4jDistributed.setup:205 + MasterActor/WorkerActor/
   BatchActor): a master pump thread and N worker threads polling the
   StateTracker, exactly the reference's steady-state loop (§3.2), minus
   the network.  This is ALSO the test-support pattern (§4
   BaseTestDistributed: boot the real runtime in one process with a
   pluggable performer).

3. ``IRUnitDriver`` — the YARN IterativeReduce simulation
   (runtime/irunit/IRUnitDriver.java): ComputableMaster + N
   ComputableWorkers in BSP supersteps, no cluster.

The DATA plane for real training remains XLA collectives
(parallel/data_parallel.py); this module is the CONTROL plane and the
orchestration-testing harness.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.parallel.coordinator import Job, StateTracker

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# SPI (§2.2)
# ---------------------------------------------------------------------------

class JobIterator:
    """next(worker_id)/has_next/reset (JobIterator.java:24)."""

    def next(self, worker_id: str) -> Job:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class CollectionJobIterator(JobIterator):
    def __init__(self, items: Sequence[Any]):
        self.items = list(items)
        self._i = 0

    def next(self, worker_id: str) -> Job:
        job = Job(work=self.items[self._i], worker_id=worker_id)
        self._i += 1
        return job

    def has_next(self) -> bool:
        return self._i < len(self.items)

    def reset(self) -> None:
        self._i = 0


class WorkerPerformer:
    """perform(job) mutates job.result; update(*) absorbs new global state
    (WorkerPerformer.java:27)."""

    def perform(self, job: Job) -> None:
        raise NotImplementedError

    def update(self, *args: Any) -> None:
        pass


class JobAggregator:
    """accumulate/aggregate (JobAggregator.java:30); ``reset`` starts a
    fresh round for synchronous routers.  ``bind_tracker`` lets the
    master pump hand the aggregator its StateTracker so rejections and
    other aggregation events can land in the run's counters — a no-op
    for aggregators that don't care."""

    def accumulate(self, job: Job) -> None:
        raise NotImplementedError

    def aggregate(self) -> Any:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def bind_tracker(self, tracker: StateTracker) -> None:
        pass


class WorkAccumulator(JobAggregator):
    """Running average of numeric results (WorkAccumulator.java:29),
    hardened: a posted result containing non-finite values — or one so
    corrupt it cannot even be flattened — is REJECTED instead of averaged
    (one NaN worker would otherwise poison the whole round's aggregate
    and, through ``set_current``, every replica).  Rejections increment
    the bound tracker's ``updates_rejected`` counter, the process-wide
    ``resilience_metrics``, and ``self.rejected``."""

    def __init__(self, tracker: Optional[StateTracker] = None):
        self._avg = None
        self._n = 0
        self.tracker = tracker
        #: how many posted results this accumulator refused
        self.rejected = 0

    def bind_tracker(self, tracker: StateTracker) -> None:
        self.tracker = tracker

    def reset(self) -> None:
        self._avg = None
        self._n = 0

    def _reject(self, job: Job, why: str) -> None:
        from deeplearning4j_tpu.runtime import telemetry
        from deeplearning4j_tpu.runtime.metrics import resilience_metrics

        self.rejected += 1
        resilience_metrics.note("updates_rejected")
        if self.tracker is not None:
            self.tracker.increment("updates_rejected")
        telemetry.event("scaleout.update_rejected",
                        worker=str(job.worker_id), why=why)
        log.warning("rejecting %s result from worker %r; excluded from "
                    "the round average", why, job.worker_id)

    def accumulate(self, job: Job) -> None:
        import jax

        from deeplearning4j_tpu.runtime.resilience import result_all_finite

        if job.result is None:
            return
        if not result_all_finite(job.result):
            self._reject(job, "non-finite/corrupt")
            return
        if self._avg is None:
            self._n += 1
            self._avg = job.result
            return
        try:
            n = self._n + 1
            avg = jax.tree.map(
                lambda a, r: a + (r - a) / n, self._avg, job.result)
        except Exception:  # noqa: BLE001
            # a result whose SHAPE doesn't match the round (truncated
            # payload, wrong pytree) is corruption too: reject it rather
            # than crash the master pump mid-round
            self._reject(job, "structurally-mismatched")
            return
        self._n, self._avg = n, avg

    def aggregate(self) -> Any:
        return self._avg


class UpdateSaver:
    """Param blobs stored OFF the tracker (UpdateSaver.java:28) — the
    tracker holds ids, the saver holds bytes."""

    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def save(self, worker_id: str, value: Any) -> None:
        with self._lock:
            self._store[worker_id] = pickle.dumps(value)

    def load(self, worker_id: str) -> Any:
        with self._lock:
            blob = self._store.pop(worker_id, None)
        return None if blob is None else pickle.loads(blob)

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._store)


class WorkRetriever:
    """Per-worker dataset storage (WorkRetriever.java:33)."""

    def __init__(self):
        self._store: Dict[str, List[Any]] = {}
        self._lock = threading.Lock()

    def save(self, worker_id: str, data: Any) -> None:
        with self._lock:
            self._store.setdefault(worker_id, []).append(data)

    def load(self, worker_id: str) -> Optional[Any]:
        with self._lock:
            queue = self._store.get(worker_id)
            return queue.pop(0) if queue else None


class Updateable:
    """Typed update envelope (api/ir/Updateable.java:26)."""

    def get(self) -> Any:
        raise NotImplementedError

    def set(self, value: Any) -> None:
        raise NotImplementedError

    def to_bytes(self) -> bytes:
        return pickle.dumps(self.get())

    def from_bytes(self, blob: bytes) -> None:
        self.set(pickle.loads(blob))


class ParameterVectorUpdateable(Updateable):
    """Array-pytree payload (ParameterVectorUpdateable.java:34)."""

    def __init__(self, value: Any = None):
        self._value = value

    def get(self) -> Any:
        return self._value

    def set(self, value: Any) -> None:
        self._value = value


class WorkRouter:
    """When should the master push more work / re-replicate?
    (api/workrouter/WorkRouter.java:29)"""

    #: synchronous routers aggregate a whole round at once and REPLACE the
    #: global state with that round's aggregate; async routers fold updates
    #: in as they arrive
    synchronous_rounds = True

    def __init__(self, tracker: StateTracker):
        self.tracker = tracker

    def send_work(self) -> bool:
        raise NotImplementedError


class IterativeReduceWorkRouter(WorkRouter):
    """Synchronous rounds: only send new work when every outstanding job
    reported back (IterativeReduceWorkRouter.java:32)."""

    synchronous_rounds = True

    def send_work(self) -> bool:
        return not self.tracker.has_pending()


class HogWildWorkRouter(WorkRouter):
    """Always send — async lock-free (HogWildWorkRouter.java:30)."""

    synchronous_rounds = False

    def send_work(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# Master pump (MasterActor steady-state loop, §3.2) — shared by the
# in-process runner below and the multi-process runner in transport.py
# ---------------------------------------------------------------------------

def master_pump(tracker: StateTracker, jobs: JobIterator,
                aggregator: JobAggregator, router: WorkRouter,
                n_slots: Callable[[], int], poll: float,
                timeout_s: float, reap: bool = False) -> Any:
    """Drive the reference's master loop (MasterActor.java:104-137):
    collect results, publish round aggregates, push new work, optionally
    reap stale workers (requeueing their in-flight jobs, :139-169).

    ``n_slots`` is how many jobs one "round" may hold — the worker count,
    read per-iteration because multi-process workers join (and die)
    dynamically.  Synchronous routers REPLACE the current value with each
    round's aggregate (IterativeReduce); async routers fold updates in as
    they arrive (HogWild).

    On timeout, completed-but-unpublished updates are drained and
    published FIRST — hours of finished worker results must not be
    discarded because the last job wedged — and the error carries the
    queued/in-flight/worker counts for debuggability.
    """
    from deeplearning4j_tpu.runtime import telemetry

    deadline = time.time() + timeout_s
    sync = router.synchronous_rounds
    round_jobs: List[Job] = []
    # hand the aggregator the tracker so rejections land in the run's
    # counters; duck-typed aggregators without the hook are fine
    bind = getattr(aggregator, "bind_tracker", None)
    if callable(bind):
        bind(tracker)

    def publish(jobs_done: List[Job]) -> None:
        if not jobs_done:
            return
        if sync:
            aggregator.reset()
        for job in jobs_done:
            aggregator.accumulate(job)
        agg = aggregator.aggregate()
        if agg is not None:
            tracker.set_current(agg)

    with telemetry.span("scaleout.master_pump", timeout_s=timeout_s):
        while time.time() < deadline:
            if reap:
                removed = tracker.remove_stale_workers()
                if removed:
                    log.warning("reaped stale workers %s; jobs requeued",
                                removed)
                    tracker.increment("workers_reaped", len(removed))
                    telemetry.event("scaleout.workers_reaped",
                                    workers=[str(w) for w in removed])
            # 1) collect results; sync publishes only at the round
            #    boundary, async as soon as anything arrived
            round_jobs.extend(tracker.drain_updates())
            if round_jobs and (not sync or not tracker.has_pending()):
                publish(round_jobs)
                round_jobs = []
            # 2) only then push new work — never start round N+1 while
            #    round N results are drained-but-unpublished
            if jobs.has_next():
                if router.send_work() and not (sync and round_jobs):
                    for _ in range(max(1, n_slots())):
                        if not jobs.has_next():
                            break
                        tracker.add_job(jobs.next(""))
            elif not tracker.has_pending() and not round_jobs:
                break
            time.sleep(poll)
        else:
            # drain-and-publish completed updates BEFORE raising: partial
            # progress stays in tracker.get_current() for the caller's
            # post-mortem/checkpoint instead of being discarded
            round_jobs.extend(tracker.drain_updates())
            publish(round_jobs)
            queued, in_flight = tracker.pending_counts()
            telemetry.event("scaleout.timeout", timeout_s=timeout_s,
                            queued=queued, in_flight=in_flight,
                            workers=len(tracker.workers()),
                            published=len(round_jobs))
            raise TimeoutError(
                f"distributed run did not finish within {timeout_s}s: "
                f"{queued} queued + {in_flight} in-flight job(s), "
                f"{len(tracker.workers())} live worker(s); "
                f"{len(round_jobs)} completed update(s) were published — "
                "partial aggregate preserved in tracker.get_current()")
        round_jobs.extend(tracker.drain_updates())
        publish(round_jobs)
        return tracker.get_current()


# ---------------------------------------------------------------------------
# In-process distributed runner (§2.3 topology, §3.2 steady-state loop)
# ---------------------------------------------------------------------------

class DistributedRunner:
    """Master pump + N worker threads over a shared StateTracker.

    The reference flow (§3.2): BatchActor feeds jobs from the JobIterator;
    workers poll ``job_for``, replicate current params if flagged, run the
    performer, post results via ``add_update``; the master aggregates a
    round's updates, sets the new current value, and flags re-replication.
    """

    def __init__(self, job_iterator: JobIterator,
                 performer_factory: Callable[[], WorkerPerformer],
                 aggregator: JobAggregator,
                 n_workers: int = 2,
                 router_cls=IterativeReduceWorkRouter,
                 poll_interval_s: float = 0.005,
                 max_job_retries: int = 5):
        self.tracker = StateTracker(max_job_retries=max_job_retries)
        self.update_saver = UpdateSaver()
        self.jobs = job_iterator
        self.performer_factory = performer_factory
        self.aggregator = aggregator
        self.router = router_cls(self.tracker)
        self.n_workers = n_workers
        self.poll = poll_interval_s
        self._stop = threading.Event()

    # -- worker loop (WorkerActor.checkJobAvailable:287 parity) ------------
    def _worker_loop(self, worker_id: str,
                     stop: Optional[threading.Event] = None) -> None:
        from deeplearning4j_tpu.runtime import telemetry

        # the stop event is bound PER RUN: a worker leaked by a timed-out
        # join must keep watching its own run's (set) event, not a later
        # run's fresh one
        stop = self._stop if stop is None else stop
        performer = self.performer_factory()
        self.tracker.add_worker(worker_id)
        telemetry.event("scaleout.worker_join", worker=worker_id)
        while not stop.is_set():
            self.tracker.heartbeat(worker_id)
            job = self.tracker.job_for(worker_id)
            if job is None:
                time.sleep(self.poll)
                continue
            if self.tracker.needs_replicate(worker_id):
                current = self.tracker.get_current()
                if current is not None:
                    performer.update(current)
                self.tracker.done_replicating(worker_id)
            try:
                performer.perform(job)
            except Exception:
                # JobFailed parity: requeue the work for another worker
                # instead of dying silently and stranding the job
                log.exception("worker %s failed job; requeueing", worker_id)
                # single-lock requeue: clear_job-then-add_job opens a window
                # where has_pending() is False and the master can end the
                # round without this job's work
                self.tracker.requeue(worker_id)
                self.tracker.increment("jobs_failed")
                continue
            self.tracker.complete_job(worker_id, job)

    # -- master loop (MasterActor 1s pump :104-137 parity) -----------------
    def run(self, timeout_s: float = 60.0) -> Any:
        # a fresh stop event per run: the previous run's ``finally``
        # left the shared event SET, so a reused runner's workers would
        # all exit on arrival and the pump would spin to TimeoutError
        # with every job queued and zero live workers
        stop = self._stop = threading.Event()
        workers = [threading.Thread(target=self._worker_loop,
                                    args=(f"worker-{i}", stop),
                                    daemon=True)
                   for i in range(self.n_workers)]
        for w in workers:
            w.start()
        try:
            return master_pump(self.tracker, self.jobs, self.aggregator,
                               self.router, lambda: self.n_workers,
                               self.poll, timeout_s)
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=5)


# ---------------------------------------------------------------------------
# IRUnit: YARN IterativeReduce simulation (§2.5)
# ---------------------------------------------------------------------------

class ComputableMaster:
    """compute(worker_updates, previous) -> new global (ComputableMaster
    .java:30)."""

    def compute(self, worker_updates: List[Updateable],
                previous: Optional[Updateable]) -> Updateable:
        raise NotImplementedError

    def complete(self) -> Any:
        return None


class ComputableWorker:
    """compute(records) -> Updateable; update(master) absorbs the round
    result (ComputableWorker.java:25)."""

    def compute(self, records: Any) -> Updateable:
        raise NotImplementedError

    def update(self, master_update: Updateable) -> None:
        pass


class IRUnitDriver:
    """Master + N workers in one process, BSP supersteps over data splits
    (IRUnitDriver.java parity: the 'IRUnit' test pattern — no cluster)."""

    def __init__(self, master: ComputableMaster,
                 workers: Sequence[ComputableWorker],
                 splits: Sequence[Any], iterations: int = 1):
        if len(workers) != len(splits):
            raise ValueError(f"{len(workers)} workers for "
                             f"{len(splits)} splits")
        self.master = master
        self.workers = list(workers)
        self.splits = list(splits)
        self.iterations = iterations

    def run(self) -> Any:
        previous: Optional[Updateable] = None
        for _ in range(self.iterations):
            updates = [w.compute(split)
                       for w, split in zip(self.workers, self.splits)]
            previous = self.master.compute(updates, previous)
            for w in self.workers:       # fetch + update per superstep
                w.update(previous)
        return self.master.complete() or previous
