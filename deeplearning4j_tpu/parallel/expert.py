"""Expert parallelism — Mixture-of-Experts with all_to_all dispatch.

New capability with no reference counterpart (SURVEY.md §2.9: expert
parallelism absent from the reference).  GShard/Switch-style design, built
for the TPU torus:

- Top-k router with capacity factor; dispatch/combine are dense one-hot
  einsums (MXU-friendly — no scatters, no dynamic shapes under jit).
- Experts are sharded over the mesh ``expert`` axis; tokens travel to their
  experts and back via two ``lax.all_to_all`` collectives (ICI), each shard
  batch-applying only its resident experts.
- Load-balance auxiliary loss (Switch Transformer form): E * Σ_e f_e · p_e
  where f_e is the fraction of tokens routed to expert e and p_e the mean
  router probability.
- Single-shard path (no ``expert`` axis in the mesh) runs the same
  dispatch/combine math without collectives, so the layer is
  topology-agnostic.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from deeplearning4j_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, EXPERT_AXIS

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_model: int = 64
    d_ff: int = 256
    aux_loss_weight: float = 1e-2


def init_moe_params(key: Array, cfg: MoEConfig) -> dict:
    kr, k1, k2 = jax.random.split(key, 3)
    E, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff
    return {
        "router": jax.random.normal(kr, (d, E)) * 0.02,
        "wi": jax.random.normal(k1, (E, d, f)) * (1.0 / jnp.sqrt(d)),
        "wo": jax.random.normal(k2, (E, f, d)) * (1.0 / jnp.sqrt(f)),
    }


def compute_capacity(n_tokens: int, n_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    c = int(capacity_factor * top_k * n_tokens / n_experts)
    return max(c, 1)


def route_topk(gates: Array, top_k: int, capacity: int,
               stat_axes: Tuple[str, ...] = ()
               ) -> Tuple[Array, Array, Array]:
    """Top-k routing with per-expert capacity.

    gates: [N, E] router probabilities.  Returns (dispatch [N,E,C] {0,1},
    combine [N,E,C] gate-weighted, aux_loss scalar).

    ``stat_axes``: mesh axes the token batch is sharded over.  The Switch
    aux loss is NONLINEAR in the routing statistics (f_e · p_e), so a
    mean of per-shard aux values is not the global aux; pmean-ing f_e and
    p_e over the token shards first (equal shard sizes → global means)
    makes the sharded aux exactly equal the pooled-token computation.
    """
    N, E = gates.shape
    topv, topi = lax.top_k(gates, top_k)                # [N, k]
    topv = topv / jnp.maximum(jnp.sum(topv, -1, keepdims=True), 1e-9)

    # Slot accounting is COUNTING, not math on probabilities: keep it in
    # int32.  In bf16 (the usual compute dtype) a cumsum cannot represent
    # counts above 256 exactly, silently colliding tokens into one slot.
    masks = jax.nn.one_hot(topi, E, dtype=jnp.int32)    # [N, k, E]
    # positions: choice-major cumulative count per expert (choice 0 of every
    # token outranks choice 1, GShard-style priority)
    flat = jnp.swapaxes(masks, 0, 1).reshape(top_k * N, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat          # 0-based slot
    pos = jnp.swapaxes(pos_flat.reshape(top_k, N, E), 0, 1)  # [N, k, E]

    dispatch = jnp.zeros((N, E, capacity), gates.dtype)
    combine = jnp.zeros((N, E, capacity), gates.dtype)
    for j in range(top_k):
        m = masks[:, j]                                  # [N, E] int
        slot = jnp.sum(pos[:, j] * m, axis=-1)           # [N] int32
        sel = (m * (slot < capacity)[:, None]).astype(gates.dtype)
        slot_oh = jax.nn.one_hot(slot, capacity, dtype=gates.dtype)
        d_j = sel[:, :, None] * slot_oh[:, None, :]      # [N, E, C]
        dispatch = dispatch + d_j
        combine = combine + d_j * topv[:, j][:, None, None]

    # Switch aux loss: E * sum_e (token fraction to e) * (mean prob of e);
    # accumulated in f32 (a bf16 sum over N tokens is equally lossy).
    f_e = jnp.sum(masks.sum(1), axis=0).astype(jnp.float32) / (N * top_k)
    p_e = jnp.mean(gates.astype(jnp.float32), axis=0)        # [E]
    for ax in stat_axes:
        f_e = lax.pmean(f_e, ax)
        p_e = lax.pmean(p_e, ax)
    aux = E * jnp.sum(f_e * p_e)
    return dispatch, combine, aux


def _expert_ffn(wi: Array, wo: Array, x: Array) -> Array:
    """Batched expert FFN: x [E_local, C', d] through per-expert weights."""
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", x, wi,
                               preferred_element_type=jnp.float32))
    return jnp.einsum("ecf,efd->ecd", h.astype(x.dtype), wo,
                      preferred_element_type=jnp.float32).astype(x.dtype)


def moe_ffn(params: dict, x: Array, cfg: MoEConfig,
            axis_name: Optional[str] = None,
            stat_axes: Tuple[str, ...] = ()) -> Tuple[Array, Array]:
    """MoE FFN over tokens x [N, d] -> (y [N, d], aux_loss).

    When ``axis_name`` is given (running inside shard_map), x holds this
    shard's N local tokens and params hold the LOCAL experts
    ``[E/ep, ...]``; dispatch crosses shards via all_to_all.  The router
    table is replicated.  ``stat_axes`` reduces the aux-loss routing
    statistics across token shards first (see route_topk) so the sharded
    aux equals the pooled computation exactly.
    """
    N, d = x.shape
    E = cfg.n_experts
    gates = jax.nn.softmax(
        jnp.einsum("nd,de->ne", x, params["router"],
                   preferred_element_type=jnp.float32), axis=-1
    ).astype(x.dtype)
    C = compute_capacity(N, E, cfg.top_k, cfg.capacity_factor)
    dispatch, combine, aux = route_topk(gates, cfg.top_k, C, stat_axes)

    # [N,E,C] x [N,d] -> [E,C,d] expert inboxes
    inbox = jnp.einsum("nec,nd->ecd", dispatch, x)

    if axis_name is None:
        out = _expert_ffn(params["wi"], params["wo"], inbox)
    else:
        # [E, C, d] -> each shard holds every source shard's slots for its
        # local experts: [E/ep, ep*C, d] (slot axis blocked by source shard)
        inbox = lax.all_to_all(inbox, axis_name,
                               split_axis=0, concat_axis=1, tiled=True)
        out = _expert_ffn(params["wi"], params["wo"], inbox)
        # route results back to source shards: [E, C, d]
        out = lax.all_to_all(out, axis_name,
                             split_axis=1, concat_axis=0, tiled=True)

    y = jnp.einsum("nec,ecd->nd", combine, out)
    return y, aux.astype(jnp.float32)


def expert_param_specs(cfg: MoEConfig) -> dict:
    """PartitionSpecs: experts sharded over ``expert``, router replicated."""
    return {"router": P(), "wi": P(EXPERT_AXIS), "wo": P(EXPERT_AXIS)}


def make_moe_layer(mesh: Mesh, cfg: MoEConfig):
    """Build ``f(params, x) -> (y, aux)`` for token batch x [N, d], with
    experts sharded over the mesh ``expert`` axis and tokens over
    ``data`` x ``expert`` (falling back to replicated when those axes are
    absent/size-1)."""
    ep = mesh.shape.get(EXPERT_AXIS, 1)
    if cfg.n_experts % ep != 0:
        raise ValueError(f"n_experts={cfg.n_experts} not divisible by "
                         f"expert degree {ep}")
    if ep == 1:
        def apply(params, x):
            return moe_ffn(params, x, cfg, axis_name=None)
        return apply

    # Tokens shard over BOTH data and expert axes: with tokens only on
    # ``data``, every expert shard would route the identical token set and
    # do the full single-device FFN FLOPs — expert parallelism would save
    # weight memory but zero compute.  Splitting tokens across the expert
    # axis cuts per-device routing + FFN work by the expert degree; the
    # all_to_alls then move each sub-batch's slots to their expert owners.
    tok_axes = tuple(a for a in (DATA_AXIS, EXPERT_AXIS)
                     if mesh.shape.get(a, 1) > 1)
    tok_spec = P(tok_axes) if tok_axes else P()
    pspec = expert_param_specs(cfg)

    def inner(params, x):
        # aux forms from routing stats pmean-ed across the token shards
        # (route_topk docstring: the aux is nonlinear in them, so this —
        # not a pmean of per-shard aux values — matches the pooled-token
        # computation); the returned scalar is already identical on all
        # shards.
        y, aux = moe_ffn(params, x, cfg, axis_name=EXPERT_AXIS,
                         stat_axes=tok_axes)
        return y, aux

    return shard_map(inner, mesh=mesh, in_specs=(pspec, tok_spec),
                     out_specs=(tok_spec, P()), check_vma=False)


def make_gspmd_moe_ffn(mesh: Optional[Mesh], cfg: MoEConfig):
    """The per-layer MoE dispatch for the GSPMD fit spine: a callable
    ``(layer_params, tok) -> (y, aux)`` with ``layer_params =
    {"router", "wi" [E,H,F], "wo" [E,F,H]}`` and ``tok [N, H]``, legal
    to call from INSIDE a jitted global-view program (the sharded-fit
    scanned-epoch step calls it from the layer ``lax.scan`` body via
    ``models/moe.encode(..., ffn_fn=...)``).

    With an ``expert`` axis of size > 1 in ``mesh`` this is a nested
    ``shard_map``: tokens shard over (``data``, ``expert``), expert
    tables over ``expert``, and the two ``lax.all_to_all`` dispatch
    collectives from ``moe_ffn`` run on the ``expert`` axis exactly as
    in the standalone ``make_moe_layer`` path.  Without one it degrades
    to the single-shard dispatch math (GSPMD still shards the einsums
    over whatever the specs say).  The aux scalar comes back replicated
    and already globally pmean-ed over the token shards."""
    ep = 1 if mesh is None else int(mesh.shape.get(EXPERT_AXIS, 1))
    if ep == 1:
        def apply(params, x):
            return moe_ffn(params, x, cfg, axis_name=None)
        return apply
    if cfg.n_experts % ep != 0:
        raise ValueError(f"n_experts={cfg.n_experts} not divisible by "
                         f"expert degree {ep}")
    tok_axes = tuple(a for a in (DATA_AXIS, EXPERT_AXIS)
                     if mesh.shape.get(a, 1) > 1)
    tok_spec = P(tok_axes) if tok_axes else P()
    pspec = expert_param_specs(cfg)

    def inner(params, x):
        return moe_ffn(params, x, cfg, axis_name=EXPERT_AXIS,
                       stat_axes=tok_axes)

    return shard_map(inner, mesh=mesh, in_specs=(pspec, tok_spec),
                     out_specs=(tok_spec, P()), check_vma=False)
