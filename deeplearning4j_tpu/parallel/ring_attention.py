"""Ring attention — blockwise sequence-parallel attention over ICI.

New capability with no reference counterpart (SURVEY.md §5.7 documents the
reference has no attention, let alone sequence parallelism).  Design follows
the public ring-attention recipe (Liu et al., blockwise parallel
transformers): shard the sequence over the mesh ``seq`` axis, keep Q local,
and rotate K/V blocks around the ring with ``lax.ppermute`` while
accumulating the softmax online (flash-style running max / running sum), so
peak memory is O(T/n) per chip and the K/V transfer overlaps compute on the
ICI torus.

Also here: ``ulysses_attention`` — the all-to-all alternative (head-scatter /
seq-gather) that trades one a2a for full-sequence local attention, which is
preferable when n_heads >= seq_degree and T is moderate.

Both run under ``shard_map`` with the package mesh axis names.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


def _block_attend(q: Array, k: Array, v: Array,
                  mask_k: Optional[Array],
                  logit_bias: Optional[Array] = None):
    """One (Q-local, K-block) attention tile with fp32 logits.

    Returns (numerator [B,Tq,H,D] fp32, row max [B,H,Tq], row sumexp).
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask_k is not None:
        logits = logits + (1.0 - mask_k[:, None, None, :]) * jnp.float32(-1e9)
    if logit_bias is not None:
        logits = logits + logit_bias
    m = jnp.max(logits, axis=-1)                       # [B,H,Tq]
    p = jnp.exp(logits - m[..., None])                 # [B,H,Tq,Tk]
    l = jnp.sum(p, axis=-1)                            # [B,H,Tq]
    num = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return num, m, l


def ring_attention(q: Array, k: Array, v: Array,
                   mask: Optional[Array] = None,
                   causal: bool = False,
                   axis_name: str = "seq") -> Array:
    """Sequence-parallel attention: every shard holds [B, T/n, H, D].

    MUST run inside shard_map with ``axis_name`` bound.  K/V (+key mask)
    rotate n-1 times via ppermute; the online-softmax accumulators merge
    each block exactly as flash attention does across KV tiles.

    ``causal`` masks by absolute block position (shard i attends to shards
    j <= i; the diagonal block uses the triangular mask).
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    cdt = q.dtype

    def causal_bias(kv_idx, Tk):
        # bias [1, 1, Tq, Tk]: 0 where allowed, -1e9 where future
        iq = my_idx * Tq + jnp.arange(Tq)[:, None]
        ik = kv_idx * Tk + jnp.arange(Tk)[None, :]
        return jnp.where(ik <= iq, 0.0, -1e9)[None, None].astype(jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        (kk, vv, mm, kv_idx, acc_num, acc_max, acc_den) = carry
        bias = causal_bias(kv_idx, kk.shape[1]) if causal else None
        num, m, l = _block_attend(q, kk, vv, mm, bias)
        new_max = jnp.maximum(acc_max, m)
        c_old = jnp.exp(acc_max - new_max)
        c_new = jnp.exp(m - new_max)
        acc_num = (acc_num * c_old[..., None].transpose(0, 2, 1, 3)
                   + num * c_new[..., None].transpose(0, 2, 1, 3))
        acc_den = acc_den * c_old + l * c_new
        # rotate kv to the next shard (ICI neighbor on the ring)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        mm = (lax.ppermute(mm, axis_name, perm) if mm is not None else None)
        kv_idx = lax.ppermute(kv_idx, axis_name, perm)
        return (kk, vv, mm, kv_idx, acc_num, new_max, acc_den), None

    acc_num = jnp.zeros((B, Tq, H, D), jnp.float32)
    acc_max = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    acc_den = jnp.zeros((B, H, Tq), jnp.float32)
    carry = (k, v, mask, my_idx, acc_num, acc_max, acc_den)
    carry, _ = lax.scan(step, carry, None, length=n)
    _, _, _, _, acc_num, acc_max, acc_den = carry
    den = jnp.maximum(acc_den, 1e-30)[..., None].transpose(0, 2, 1, 3)
    return (acc_num / den).astype(cdt)


def make_ring_attn_fn(axis_name: str = "seq"):
    """Adapter matching models.transformer.attention's signature."""
    def attn(q, k, v, mask, causal=False):
        return ring_attention(q, k, v, mask, causal, axis_name)
    return attn


def ulysses_attention(q: Array, k: Array, v: Array,
                      mask: Optional[Array] = None,
                      causal: bool = False,
                      axis_name: str = "seq") -> Array:
    """DeepSpeed-Ulysses style: all_to_all so each shard holds the FULL
    sequence for H/n heads, attends locally, then a2a back to seq-sharded
    layout.  Requires n_heads % seq_degree == 0."""
    n = lax.psum(1, axis_name)
    B, T, H, D = q.shape

    def scatter_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]
        x = x.reshape(B, T, n, H // n, D)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                           tiled=False)
        return x.reshape(B, T * n, H // n, D)

    def gather_seq(x):
        # [B, T, H/n, D] -> [B, T/n, H, D]
        x = x.reshape(B, n, T, H // n, D)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                           tiled=False)
        return x.reshape(B, T, H, D)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    full_mask = (lax.all_gather(mask, axis_name, axis=1, tiled=True)
                 if mask is not None else None)
    num, m, l = _block_attend(qg, kg, vg, full_mask,
                              _full_causal_bias(qg) if causal else None)
    out = num / jnp.maximum(l, 1e-30)[..., None].transpose(0, 2, 1, 3)
    return gather_seq(out.astype(q.dtype))


def _full_causal_bias(q):
    T = q.shape[1]
    i = jnp.arange(T)
    return jnp.where(i[None, :] <= i[:, None], 0.0, -1e9)[None, None]
