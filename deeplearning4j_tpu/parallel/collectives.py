"""Named collective wrappers used inside shard_map'd code.

These are the data-plane primitives that replace the reference's four
message/RPC stacks (SURVEY.md §5.8): gradient sharing = ``pmean`` (≡ Spark
``fold(Add)``/÷N and YARN ``Master.compute`` averaging), ``ppermute`` rings
for sequence parallelism, ``all_to_all`` for Ulysses-style head scatter.
Thin by design — the names document intent at call sites.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

PyTree = Any


def grad_share(grads: PyTree, axis: str = "data") -> PyTree:
    """Mean-allreduce gradients over the data axis — the IterativeReduce/
    parameter-averaging equivalence: averaging gradients each step IS the
    reference's synchronous parameter averaging done right."""
    return jax.tree.map(lambda g: lax.pmean(g, axis), grads)


def param_average(params: PyTree, axis: str = "data") -> PyTree:
    """Mean-allreduce parameters (Spark fitDataSet / YARN Master.compute
    parity — average AFTER local training rather than per-step)."""
    return jax.tree.map(lambda p: lax.pmean(p, axis), params)


def psum(x, axis: str):
    return lax.psum(x, axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis)


def all_gather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=True)


def ring_permute(x, axis: str, shift: int = 1):
    """Send each shard to its ring neighbor (ppermute) — the building block
    of ring attention / pipelined halo exchange over ICI."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, split_axis: int, concat_axis: int):
    """Ulysses-style resharding: scatter one array axis, gather another."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis: str):
    return lax.axis_index(axis)
