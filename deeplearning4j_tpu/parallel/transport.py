"""Cross-process control plane: a socket-served StateTracker and a
multi-process distributed runner.

Reference parity: the Akka runtime's control plane spans OS processes and
machines — workers join a master by connection string and share job/param
state through an embedded Hazelcast server
(``DeepLearning4jDistributed.java:205,301-315``,
``BaseHazelCastStateTracker.java:495-562`` — server or client mode chosen
by the connection string).  Here the same split:

- ``StateTrackerServer`` — *embedded server mode*: hosts the real
  in-process :class:`StateTracker` and serves its method surface over a
  length-prefixed pickle RPC on a TCP socket.  The master process uses
  the tracker object directly; remote workers dial in.
- ``RemoteStateTracker`` — *client mode*: same method surface, every call
  forwarded over the socket, so ``worker_main`` below and
  ``DistributedRunner``'s worker loop are written against one API.
- ``worker_main`` — the worker-process entry point (WorkerActor parity):
  registers, starts a heartbeat thread (the YARN worker pattern,
  ``ApplicationWorkerService.java:83-95``), polls ``job_for``, replicates
  current params when flagged, performs, posts updates; exits when the
  master sets the done flag (ShutdownMessage parity).
- ``MultiProcessRunner`` — ``DeepLearning4jDistributed`` parity: embeds
  the server, spawns N worker processes (or lets external ones join via
  the connection string), drives the shared ``master_pump`` with stale-
  worker reaping ON (a killed worker's heartbeats stop; the reaper
  requeues its in-flight job — MasterActor.java:139-169).

The performer reaches worker processes as a *spec*, not an object: a
``"module:callable"`` string plus pickled constructor args — the analog
of the reference's reflective ``WorkerPerformerFactory.WORKER_PERFORMER``
class-name config key.

Wire layer: stdlib ``multiprocessing.connection`` — length-prefixed
pickle over TCP with HMAC challenge-response authentication (a shared
``authkey``), so unauthenticated peers cannot deliver pickles.  Within
that authenticated channel the trust model matches the reference's Java
serialization over Akka remoting: peers holding the key are trusted.
"""

from __future__ import annotations

import importlib
import logging
import multiprocessing
import os
import secrets
import sys
import threading
import time
from multiprocessing.connection import Client, Connection, Listener
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

from deeplearning4j_tpu.parallel.coordinator import StateTracker
from deeplearning4j_tpu.parallel.scaleout import (
    IterativeReduceWorkRouter, JobAggregator, JobIterator, WorkerPerformer,
    master_pump)

log = logging.getLogger(__name__)

# The tracker surface served over the wire.  Everything the worker loop
# and the pump need; underscore methods stay private to the process.
_TRACKER_METHODS = frozenset({
    "add_worker", "heartbeat", "heartbeats", "workers",
    "remove_stale_workers", "worker_enabled", "enable_worker",
    "add_job", "job_for", "clear_job", "requeue", "has_pending",
    "pending_counts",
    "set_current", "get_current", "needs_replicate", "done_replicating",
    "add_update", "complete_job", "updates", "drain_updates",
    "increment", "count", "set_done", "is_done",
})


# ---------------------------------------------------------------------------
# Server (embedded mode) — wire layer is stdlib multiprocessing.connection:
# length-prefixed pickle over TCP with HMAC challenge-response auth, so an
# unauthenticated peer can never deliver a pickle to this process.
# ---------------------------------------------------------------------------

class StateTrackerServer:
    """Serve a StateTracker on a TCP port (Hazelcast embedded-server-mode
    parity).  The hosting process keeps using ``self.tracker`` directly;
    remote processes connect with :class:`RemoteStateTracker` via
    ``connection_string`` + the shared ``authkey``."""

    def __init__(self, tracker: Optional[StateTracker] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 authkey: Optional[bytes] = None):
        self.tracker = tracker or StateTracker()
        self.authkey = authkey if authkey is not None else (
            secrets.token_bytes(16))
        self._listener = Listener((host, port), authkey=self.authkey)
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._closing = False

    @property
    def connection_string(self) -> str:
        host, port = self._listener.address[:2]
        return f"{host}:{port}"

    def _serve_connection(self, conn: Connection) -> None:
        with conn:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return                   # client went away (or died)
                except Exception as exc:  # noqa: BLE001
                    # malformed request pickle: the frame was consumed, so
                    # the connection is still usable — reply with the error
                    reply = (False, exc)
                else:
                    try:
                        name, args, kwargs = msg
                        if name not in _TRACKER_METHODS:
                            raise AttributeError(
                                f"no tracker method {name!r}")
                        reply = (True, getattr(self.tracker, name)(
                            *args, **kwargs))
                    except Exception as exc:  # noqa: BLE001 — to client
                        reply = (False, exc)
                try:
                    conn.send(reply)
                except (BrokenPipeError, ConnectionError, OSError):
                    return
                except Exception:            # unpicklable payload/exception
                    try:
                        conn.send((False, RuntimeError(repr(reply[1]))))
                    except (BrokenPipeError, ConnectionError, OSError):
                        return

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):      # closed, or failed auth
                if self._closing:
                    return
                continue
            except Exception:
                if self._closing:
                    return
                log.exception("tracker server accept failed")
                continue
            # prune finished connection threads so reconnect churn (worker
            # crash/restart cycles) doesn't grow the list forever
            self._conn_threads = [t for t in self._conn_threads
                                  if t.is_alive()]
            t = threading.Thread(target=self._serve_connection,
                                 args=(conn,), daemon=True,
                                 name="tracker-conn")
            t.start()
            self._conn_threads.append(t)

    def start(self) -> "StateTrackerServer":
        if self._accept_thread is not None and self._accept_thread.is_alive():
            return self                      # idempotent: already serving
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="state-tracker-server")
        self._accept_thread.start()
        return self

    def shutdown(self) -> None:
        self._closing = True
        try:
            self._listener.close()           # accept() unblocks with OSError
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def __enter__(self) -> "StateTrackerServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()


# ---------------------------------------------------------------------------
# Client (worker mode)
# ---------------------------------------------------------------------------

class RemoteStateTracker:
    """StateTracker proxy over an authenticated connection: the
    client-mode counterpart of ``StateTrackerServer`` with the identical
    method surface (generated below from ``_TRACKER_METHODS``), safe for
    concurrent use from the worker loop and its heartbeat thread."""

    def __init__(self, connection_string: str,
                 authkey: Optional[bytes] = None,
                 timeout_s: float = 60.0):
        host, _, port = connection_string.rpartition(":")
        self._conn = Client((host, int(port)), authkey=authkey)
        self._lock = threading.Lock()
        self.timeout_s = timeout_s

    def _call(self, name: str, *args: Any, **kwargs: Any) -> Any:
        with self._lock:
            self._conn.send((name, args, kwargs))
            # bounded wait: a hung/deadlocked master must not wedge the
            # worker forever — TimeoutError is an OSError, so the worker
            # loop treats it as a lost connection, exits, and the reaper
            # requeues its job
            if not self._conn.poll(self.timeout_s):
                # the reply stream is now out of sync — close so any later
                # call fails fast instead of reading a stale reply
                self._conn.close()
                raise TimeoutError(
                    f"no reply to {name!r} within {self.timeout_s}s")
            ok, value = self._conn.recv()
        if not ok:
            raise value
        return value

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass

    def __enter__(self) -> "RemoteStateTracker":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _make_proxy(name: str):
    def proxy(self, *args, **kwargs):
        return self._call(name, *args, **kwargs)
    proxy.__name__ = name
    proxy.__qualname__ = f"RemoteStateTracker.{name}"
    proxy.__doc__ = f"Forward ``{name}`` to the remote StateTracker."
    return proxy


for _name in sorted(_TRACKER_METHODS):
    setattr(RemoteStateTracker, _name, _make_proxy(_name))
del _name


# ---------------------------------------------------------------------------
# Performer specs (reflective WORKER_PERFORMER parity)
# ---------------------------------------------------------------------------

PerformerSpec = Union[str, Tuple[str, tuple, dict],
                      Callable[[], WorkerPerformer]]


def resolve_performer_factory(spec: PerformerSpec
                              ) -> Callable[[], WorkerPerformer]:
    """``"module:callable"`` or ``("module:callable", args, kwargs)`` →
    zero-arg factory.  A plain callable passes through (in-process use).
    String specs are what cross the process boundary — the analog of the
    reference's ``WORKER_PERFORMER`` class-name key resolved reflectively
    (BaseWorkPerformerFactory parity)."""
    if callable(spec):
        return spec
    if isinstance(spec, tuple):
        path, args, kwargs = spec
    else:
        path, args, kwargs = spec, (), {}
    module, sep, attr = path.partition(":")
    if not sep or not attr:
        raise ValueError(f"performer spec {path!r} is not 'module:callable'")
    obj = importlib.import_module(module)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return lambda: obj(*args, **kwargs)


# ---------------------------------------------------------------------------
# Worker process entry point (WorkerActor parity)
# ---------------------------------------------------------------------------

def _fix_child_platform() -> None:
    """A sitecustomize may pre-import jax pinned to the hardware plugin in
    EVERY new interpreter — including spawned workers.  If the parent
    chose a platform via JAX_PLATFORMS (the conftest/run_cpu pattern),
    honor it here before the performer touches a backend."""
    want = os.environ.get("JAX_PLATFORMS")
    if want and "jax" in sys.modules:
        import jax
        jax.config.update("jax_platforms", want)


def _join_tracker(connection_string: str, worker_id: str,
                  authkey: Optional[bytes], retries: int,
                  backoff_s: float):
    """Open both tracker connections and register, retrying with
    exponential backoff.  A worker racing the master's listener bring-up
    (or a transient network blip on a real cluster) must not be lost for
    the whole run over one refused connect — the reference worker simply
    dies there and YARN restarts it; retrying in-process is cheaper.
    Returns (tracker, beat_tracker) or None when the budget is spent
    (master genuinely gone — exit cleanly, the reaper handles the rest).
    """
    from deeplearning4j_tpu.runtime import telemetry
    from deeplearning4j_tpu.runtime.metrics import resilience_metrics

    for attempt in range(retries + 1):
        tracker = None
        try:
            tracker = RemoteStateTracker(connection_string, authkey=authkey)
            tracker.add_worker(worker_id)
            telemetry.event("scaleout.worker_join", worker=worker_id,
                            attempts=attempt + 1)
            # The heartbeat gets its OWN connection: the main loop's
            # socket is held for a full RPC round-trip, so a large
            # add_update (MLN params) would otherwise block heartbeats
            # past the stale threshold and get a healthy worker reaped
            # mid-report.
            beat_tracker = RemoteStateTracker(connection_string,
                                              authkey=authkey)
            return tracker, beat_tracker
        except (EOFError, ConnectionError, OSError) as exc:
            if tracker is not None:
                tracker.close()
            if attempt >= retries:
                telemetry.event("scaleout.worker_join_failed",
                                worker=worker_id, attempts=attempt + 1)
                log.warning("worker %s could not join %s after %d "
                            "attempt(s) (%s); exiting", worker_id,
                            connection_string, attempt + 1, exc)
                return None
            delay = backoff_s * (2 ** attempt)
            resilience_metrics.note("worker_join_retries")
            telemetry.event("scaleout.worker_join_retry",
                            worker=worker_id, attempt=attempt + 1)
            log.warning("worker %s join attempt %d/%d to %s failed "
                        "(%s); retrying in %.2fs", worker_id, attempt + 1,
                        retries + 1, connection_string, exc, delay)
            time.sleep(delay)
    return None


def worker_main(connection_string: str, performer_spec: PerformerSpec,
                worker_id: Optional[str] = None,
                poll_interval_s: float = 0.01,
                heartbeat_interval_s: Optional[float] = None,
                authkey: Optional[bytes] = None,
                join_retries: int = 4,
                join_backoff_s: float = 0.25) -> None:
    """Run one worker process against a remote tracker until the master
    sets the done flag.  The loop is the reference's
    WorkerActor.checkJobAvailable:287 — poll ``job_for``, replicate
    current params if flagged, perform, ``add_update`` — plus the YARN
    worker's dedicated heartbeat thread so a long ``perform`` doesn't
    look stale, while a killed process stops heartbeating and gets its
    job requeued by the master's reaper.  Joining retries with
    exponential backoff (``join_retries`` × ``join_backoff_s``-doubling)
    so a worker racing the master's bring-up isn't lost for the run."""
    _fix_child_platform()
    worker_id = worker_id or f"worker-{os.getpid()}"
    performer = resolve_performer_factory(performer_spec)()
    joined = _join_tracker(connection_string, worker_id, authkey,
                           join_retries, join_backoff_s)
    if joined is None:
        return
    tracker, beat_tracker = joined

    if heartbeat_interval_s is None:
        heartbeat_interval_s = 0.25
    stop_beat = threading.Event()

    def beat() -> None:
        while not stop_beat.is_set():
            try:
                if not beat_tracker.heartbeat(worker_id):
                    # reaped (e.g. a long GC-like stall) but still alive:
                    # re-join, the Akka MemberEvent re-register
                    beat_tracker.add_worker(worker_id)
            except Exception:
                return                        # master gone; main loop exits
            stop_beat.wait(heartbeat_interval_s)

    beater = threading.Thread(target=beat, daemon=True, name="heartbeat")
    beater.start()
    try:
        while not tracker.is_done():
            job = tracker.job_for(worker_id)
            if job is None:
                time.sleep(poll_interval_s)
                continue
            if tracker.needs_replicate(worker_id):
                current = tracker.get_current()
                if current is not None:
                    performer.update(current)
                tracker.done_replicating(worker_id)
            try:
                performer.perform(job)
            except Exception:
                log.exception("worker %s failed job; requeueing", worker_id)
                tracker.requeue(worker_id)
                tracker.increment("jobs_failed")
                continue
            tracker.complete_job(worker_id, job)
    except (EOFError, ConnectionError, OSError):
        log.warning("worker %s lost the tracker connection; exiting",
                    worker_id)
    finally:
        stop_beat.set()
        tracker.close()
        beat_tracker.close()


# ---------------------------------------------------------------------------
# Multi-process runner (DeepLearning4jDistributed parity)
# ---------------------------------------------------------------------------

class MultiProcessRunner:
    """Master pump + N worker *processes* over a socket-served tracker.

    The master embeds the tracker server (Hazelcast embedded-server
    parity) and runs the same ``master_pump`` as the in-process runner,
    with the stale-worker reaper ON: when a worker process dies mid-job,
    its heartbeats stop, the reaper drops it and requeues the job, and a
    surviving worker completes the work — the fault-tolerance loop of
    MasterActor.java:139-169.

    External workers (other hosts in a real deployment) can also join by
    running ``worker_main(connection_string, spec)`` — spawning here is a
    convenience for tests and single-host runs, exactly the role of the
    reference's in-process BaseTestDistributed bring-up.

    Worker processes use the ``spawn`` start method, so a script driving
    this runner must be importable: wrap the driving code in the standard
    ``if __name__ == "__main__":`` guard.
    """

    def __init__(self, job_iterator: JobIterator,
                 performer_spec: PerformerSpec,
                 aggregator: JobAggregator,
                 n_workers: int = 2,
                 router_cls=IterativeReduceWorkRouter,
                 stale_after_s: float = 2.0,
                 poll_interval_s: float = 0.01,
                 host: str = "127.0.0.1", port: int = 0,
                 authkey: Optional[bytes] = None):
        self.tracker = StateTracker(stale_after_s=stale_after_s)
        self.server = StateTrackerServer(self.tracker, host=host, port=port,
                                         authkey=authkey)
        self.jobs = job_iterator
        self.performer_spec = performer_spec
        self.aggregator = aggregator
        self.router = router_cls(self.tracker)
        self.n_workers = n_workers
        self.poll = poll_interval_s
        self.processes: List[multiprocessing.process.BaseProcess] = []

    @property
    def connection_string(self) -> str:
        return self.server.connection_string

    def spawn_workers(self, n: Optional[int] = None) -> None:
        """Start worker processes against this runner's tracker.  Uses
        the ``spawn`` start method: a fresh interpreter per worker, no
        inherited JAX backend state (fork would copy a live XLA client)."""
        ctx = multiprocessing.get_context("spawn")
        base = len(self.processes)
        for i in range(self.n_workers if n is None else n):
            p = ctx.Process(
                target=worker_main,
                args=(self.connection_string, self.performer_spec),
                kwargs={"worker_id": f"proc-worker-{base + i}",
                        "poll_interval_s": self.poll,
                        "authkey": self.server.authkey},
                daemon=True, name=f"proc-worker-{base + i}")
            p.start()
            self.processes.append(p)

    def _wait_for_workers(self, n: int, timeout_s: float) -> None:
        """Barrier until ``n`` workers registered (cluster-join parity:
        the reference master waits for worker cluster membership)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if len(self.tracker.workers()) >= n:
                return
            time.sleep(0.02)
        raise TimeoutError(
            f"only {len(self.tracker.workers())}/{n} workers joined "
            f"within {timeout_s}s")

    def run(self, timeout_s: float = 120.0, min_workers: Optional[int] = None,
            spawn: bool = True, join_timeout_s: float = 30.0) -> Any:
        self.server.start()
        try:
            if spawn:
                self.spawn_workers()
            self._wait_for_workers(
                self.n_workers if min_workers is None else min_workers,
                timeout_s=min(timeout_s, join_timeout_s))
            return master_pump(
                self.tracker, self.jobs, self.aggregator, self.router,
                n_slots=lambda: max(1, len(self.tracker.workers())),
                poll=self.poll, timeout_s=timeout_s, reap=True)
        finally:
            self.tracker.set_done()
            for p in self.processes:
                p.join(timeout=join_timeout_s)
            for p in self.processes:
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=5)
            self.server.shutdown()
