"""Multi-host (multi-process) training runtime: launcher + control plane.

ROADMAP item 2: the production spine (``parallel/sharded_fit.py``,
``ResilientFit``, ``AsyncCheckpointer``, ``PreemptionGuard``,
``elastic_remesh``) was strictly single-process — the bench already
measured a 2-process DCN grad-psum, but nothing a user runs could span
hosts.  This module is the host-level half of that story, the
fault-tolerance + scale design of TensorFlow (arXiv 1605.08695) applied
at the process level and the operational regime Gemma-class pod training
assumes (arXiv 2605.25645):

- **Launcher**: :func:`resolve_cluster_config` (ONE source of truth for
  the ``--coordinator/--num-processes/--process-id`` CLI flags and the
  ``DL4J_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` env trio that
  ``cloud/provision.py`` launch scripts export — flags win over env) and
  :func:`initialize` (``jax.distributed.initialize`` under a bounded
  join retry/backoff loop with TYPED timeout errors, because a pod
  bring-up where one host races ahead of the coordinator is the normal
  case, not the exception).

- **Control plane**: :class:`Cluster` — barriers, cluster-wide flag OR,
  and lost-member agreement built on the jax.distributed coordination
  service's KEY-VALUE store (host-side gRPC), NOT on device
  collectives.  Device collectives need every member's devices healthy
  and hang when a host dies; the KV store keeps working for the
  survivors, times out with a typed :class:`ClusterSyncTimeout` when a
  peer goes silent, and — unlike ``multihost_utils
  .sync_global_devices`` — is safe to call from the async checkpoint
  writer thread without interleaving with training collectives.  An
  in-process backend (:class:`InProcessKV`) lets tests and the CI gate
  run REAL multi-member protocol drills inside one process.

- **Failure detection**: :class:`HostHeartbeat` — per-process heartbeat
  files on the shared filesystem the checkpoint dir already requires;
  a member whose heartbeat goes stale (SIGKILL, kernel panic, fabric
  partition) is translated into a cross-host
  ``runtime.resilience.DeviceLossError`` naming its devices, which
  drives the coordinated ``elastic_remesh`` + restore-from-committed
  recovery in ``ResilientFit``.

- **Data/mesh plumbing**: :func:`global_data_mesh` (data axis spanning
  hosts over DCN per ``parallel/mesh.py``'s layout contract — model
  groups stay inside a host's ICI domain), per-process worker splits of
  a ``StoreDataSetIterator`` stream, and :func:`stage_global_batch`
  (each process contributes only ITS shard's rows of a global batch via
  ``jax.make_array_from_process_local_data``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

import jax

from deeplearning4j_tpu.runtime import telemetry
from deeplearning4j_tpu.runtime.metrics import multihost_metrics

log = logging.getLogger(__name__)

# -- cluster wiring: ONE source of truth ------------------------------------
# The env trio the cloud/provision.py launch scripts export on every pod
# host, and the cli.py launcher flags that override it (flags > env).
# Everything that consumes or documents the wiring (parallel/mesh
# .initialize_from_env, cloud/provision.py, cli.py train) references
# THESE names — a renamed variable cannot silently fork the contract.
ENV_COORDINATOR = "DL4J_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "DL4J_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "DL4J_TPU_PROCESS_ID"
ENV_TRIO = (ENV_COORDINATOR, ENV_NUM_PROCESSES, ENV_PROCESS_ID)
FLAG_COORDINATOR = "--coordinator"
FLAG_NUM_PROCESSES = "--num-processes"
FLAG_PROCESS_ID = "--process-id"
FLAG_TRIO = (FLAG_COORDINATOR, FLAG_NUM_PROCESSES, FLAG_PROCESS_ID)


class ClusterJoinError(RuntimeError):
    """``jax.distributed.initialize`` failed for a non-timeout reason
    (bad address, version skew, duplicate process id) after the bounded
    retry budget."""


class ClusterJoinTimeout(ClusterJoinError):
    """The cluster never formed within the join deadline — some host
    did not show up.  Typed separately because the launcher's correct
    reaction differs: a timeout usually means re-run the launch (a
    peer is still booting), other join errors mean fix the wiring."""


class ClusterSyncTimeout(RuntimeError):
    """A LIVE cluster's control-plane operation (barrier, flag sync,
    agreement) timed out — a peer has stopped participating.  The
    training driver translates this into a host-loss event via
    :class:`HostHeartbeat` staleness (``ResilientFit``'s elastic path)
    rather than treating it as a crash."""


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Resolved multi-process wiring (the reference's MASTER_URL role,
    DeepLearning4jDistributed.setup:301-315)."""

    coordinator: str
    num_processes: int
    process_id: int

    def __post_init__(self):
        if self.num_processes < 1:
            raise ValueError(
                f"num_processes must be >= 1, got {self.num_processes}")
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError(
                f"process_id {self.process_id} not in "
                f"[0, {self.num_processes})")


def resolve_cluster_config(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           env: Optional[Dict[str, str]] = None
                           ) -> Optional[ClusterConfig]:
    """Merge launcher flags with the ``DL4J_TPU_*`` env trio — flags
    win PER FIELD (a launch script may export the trio while an
    operator overrides just ``--process-id`` on one host).  Returns
    None when nothing is wired (single-process run); raises ValueError
    naming BOTH spellings when the wiring is partial — a partial trio
    is always a launch-script bug and the error must be actionable
    from either side (env or flags)."""
    env = os.environ if env is None else env

    def pick(flag_val, env_key, cast):
        if flag_val is not None:
            return cast(flag_val)
        raw = env.get(env_key)
        return cast(raw) if raw not in (None, "") else None

    coord = pick(coordinator, ENV_COORDINATOR, str)
    nproc = pick(num_processes, ENV_NUM_PROCESSES, int)
    pid = pick(process_id, ENV_PROCESS_ID, int)
    values = {"coordinator": coord, "num_processes": nproc,
              "process_id": pid}
    missing = [k for k, v in values.items() if v is None]
    if len(missing) == 3:
        return None
    if missing:
        raise ValueError(
            f"partial cluster wiring: {sorted(set(values) - set(missing))} "
            f"set but {missing} missing — the trio must be provided "
            f"together, either as launcher flags "
            f"({', '.join(FLAG_TRIO)}) or as environment variables "
            f"({', '.join(ENV_TRIO)}); flags override env per field")
    return ClusterConfig(coord, nproc, pid)


def initialize(config: ClusterConfig, *, attempts: int = 3,
               backoff_s: float = 2.0,
               timeout_s: float = 300.0) -> "Cluster":
    """``jax.distributed.initialize`` with a bounded join retry loop.

    Pod bring-up is racy by nature: hosts boot at different speeds, the
    coordinator's port may not be listening yet, a preempted VM may
    rejoin late.  Each attempt gets ``timeout_s`` (jax's own
    ``initialization_timeout``); failures back off exponentially from
    ``backoff_s``.  Exhausting the budget raises
    :class:`ClusterJoinTimeout` when the last failure was a deadline,
    else :class:`ClusterJoinError` — both carrying the attempt count
    and the coordinator address, so the launcher log is actionable.
    A single-process config skips ``jax.distributed`` entirely."""
    if config.num_processes == 1:
        return local_cluster()
    last: Optional[BaseException] = None
    for attempt in range(1, max(attempts, 1) + 1):
        try:
            with telemetry.span("multihost.join", attempt=attempt,
                                coordinator=config.coordinator,
                                process_id=config.process_id):
                jax.distributed.initialize(
                    coordinator_address=config.coordinator,
                    num_processes=config.num_processes,
                    process_id=config.process_id,
                    initialization_timeout=int(timeout_s))
            multihost_metrics.note("joins")
            log.info("joined %d-process cluster at %s as process %d "
                     "(attempt %d)", config.num_processes,
                     config.coordinator, config.process_id, attempt)
            return active_cluster()
        except Exception as e:  # noqa: BLE001 — backend raises several types
            last = e
            # a failed initialize leaves jax's distributed State half
            # set (the client object is assigned BEFORE connect(), so a
            # connect timeout would make every retry fail instantly
            # with "should only be called once") — tear it down so the
            # next attempt starts clean; if shutdown() itself refuses
            # (an unconnected client), null the fields directly
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 — nothing to tear down
                try:
                    from jax._src import distributed as _dist
                    _dist.global_state.client = None
                    _dist.global_state.service = None
                except Exception:  # noqa: BLE001 — private-API drift
                    pass
            if attempt <= max(attempts, 1) - 1:
                delay = backoff_s * (2 ** (attempt - 1))
                multihost_metrics.note("join_retries")
                log.warning(
                    "cluster join attempt %d/%d failed (%s: %s); "
                    "retrying in %.1fs", attempt, attempts,
                    type(e).__name__, e, delay)
                time.sleep(delay)
    multihost_metrics.note("join_failures")
    msg = (f"could not join {config.num_processes}-process cluster at "
           f"{config.coordinator} as process {config.process_id} after "
           f"{attempts} attempt(s): {type(last).__name__}: {last}")
    if "deadline" in str(last).lower() or "timeout" in str(last).lower() \
            or isinstance(last, TimeoutError):
        raise ClusterJoinTimeout(msg) from last
    raise ClusterJoinError(msg) from last


def initialize_from_env(env: Optional[Dict[str, str]] = None,
                        **retry) -> bool:
    """Join from the ``DL4J_TPU_*`` env trio when present (the
    provision-script path); no-op returning False when nothing is
    wired.  ``parallel.mesh.initialize_from_env`` delegates here so the
    env contract has exactly one implementation."""
    config = resolve_cluster_config(env=env)
    if config is None:
        return False
    initialize(config, **retry)
    return True


# ---------------------------------------------------------------------------
# KV backends — the substrate every cross-host protocol rides
# ---------------------------------------------------------------------------

class InProcessKV:
    """In-memory KV store with blocking gets: the SAME protocol surface
    as the jax.distributed coordination service, shareable between
    threads of one process.  This is what makes the cluster-commit,
    preemption-propagation, and eviction protocols testable tier-1:
    N thread-"hosts" share one InProcessKV and run the real
    :class:`Cluster` code paths, byte for byte."""

    def __init__(self):
        self._data: Dict[str, str] = {}
        self._cond = threading.Condition()

    def put(self, key: str, value: str) -> None:
        with self._cond:
            self._data[key] = value
            self._cond.notify_all()

    def get(self, key: str, timeout_s: float) -> str:
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while key not in self._data:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ClusterSyncTimeout(
                        f"key {key!r} not published within {timeout_s}s")
                self._cond.wait(remaining)
            return self._data[key]

    def delete(self, key: str) -> None:
        with self._cond:
            self._data.pop(key, None)


class DistributedKV:
    """The real backend: the jax.distributed coordination service's
    key-value store (``blocking_key_value_get`` blocks SERVER-side until
    a peer publishes — no polling traffic).  Timeouts surface as
    :class:`ClusterSyncTimeout` so callers never have to pattern-match
    backend exception strings."""

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed
            client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized — call "
                "multihost.initialize (or initialize_from_env) first")
        self._client = client

    def put(self, key: str, value: str) -> None:
        self._client.key_value_set(key, value)

    def get(self, key: str, timeout_s: float) -> str:
        try:
            return self._client.blocking_key_value_get(
                key, int(timeout_s * 1000))
        except Exception as e:  # noqa: BLE001 — XlaRuntimeError and kin
            raise ClusterSyncTimeout(
                f"key {key!r} not published within {timeout_s}s "
                f"({type(e).__name__}: {e})") from e

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(key)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass


# ---------------------------------------------------------------------------
# Cluster — membership + host-side coordination primitives
# ---------------------------------------------------------------------------

class Cluster:
    """Handle for THIS process's view of the training cluster.

    Built on the KV store only: every primitive works for an arbitrary
    SUBSET of the original processes, which is what host-loss recovery
    needs — after an eviction the survivors :meth:`shrink` to a new
    generation whose barriers/flags involve only them, while a device-
    collective barrier would wait on the dead host forever.

    Protocol discipline: every member must make the SAME sequence of
    cluster calls (the host program is SPMD too).  Rounds are numbered
    by a per-handle counter so repeated barriers/flags never collide,
    and the generation id namespaces a shrunk cluster away from its
    ancestor's keys."""

    def __init__(self, process_id: int, members: Sequence[int], kv,
                 *, timeout_s: float = 120.0, generation: int = 0,
                 namespace: str = "dl4j",
                 device_map: Optional[Dict[int, Tuple[int, ...]]] = None):
        self.process_id = int(process_id)
        self.members: Tuple[int, ...] = tuple(sorted(set(members)))
        if self.process_id not in self.members:
            raise ValueError(
                f"process {process_id} is not a member of {self.members}")
        self.kv = kv
        self.timeout_s = timeout_s
        self.generation = generation
        self._namespace = namespace
        #: per-TAG round counters: rounds must line up across members
        #: per call SITE, and different sites run on different threads
        #: (the step loop's flag sync vs the async writer's commit
        #/ barriers) whose interleaving is not deterministic — a single
        #: shared counter would hand the same round number to different
        #: tags on different members.  Each tag's own sequence is
        #: deterministic because every member makes the same sequence
        #: of calls per site.
        self._rounds: Dict[str, int] = {}
        self._lock = threading.Lock()
        #: member -> global device ids.  None = read the real process
        #: topology off jax.devices(); an explicit map is the
        #: simulated-cluster hook (thread-"hosts" over one process's
        #: virtual devices — the tier-1 drill substrate).
        self.device_map = (None if device_map is None else
                           {int(m): tuple(int(i) for i in ids)
                            for m, ids in device_map.items()})

    # -- identity ----------------------------------------------------------
    @property
    def process_count(self) -> int:
        return len(self.members)

    @property
    def coordinator(self) -> int:
        """Lowest surviving member id — deterministic, so a shrink
        re-elects without a message."""
        return self.members[0]

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == self.coordinator

    @property
    def member_rank(self) -> int:
        """This process's dense rank among the CURRENT members (the
        worker-split index — stays dense after evictions)."""
        return self.members.index(self.process_id)

    def _next_round(self, tag: str) -> int:
        with self._lock:
            self._rounds[tag] = self._rounds.get(tag, 0) + 1
            return self._rounds[tag]

    def _key(self, tag: str, rnd: int, pid: int) -> str:
        return (f"{self._namespace}/g{self.generation}/{tag}/{rnd}/"
                f"p{pid}")

    def _publish(self, tag: str, rnd: int, value: str) -> None:
        """Put this member's round key — and garbage-collect its own
        key from round ``rnd - 2`` of the same tag.  The two-round lag
        makes the delete safe: a member can only START round ``rnd``
        after putting ``rnd - 1``, which requires having fully
        COMPLETED ``rnd - 2`` — so by the time anyone deletes an
        ``rnd - 2`` key, every member has finished reading it.
        (Deleting the just-read ``rnd - 1`` keys would race a slower
        peer still inside that round.)  Without this, the per-step
        preemption flag sync would grow the coordination service's KV
        state by members x steps over a long run."""
        if rnd > 2:
            self.kv.delete(self._key(tag, rnd - 2, self.process_id))
        self.kv.put(self._key(tag, rnd, self.process_id), value)

    # -- primitives --------------------------------------------------------
    def barrier(self, tag: str,
                timeout_s: Optional[float] = None) -> None:
        """Host-side rendezvous of every CURRENT member.  Raises
        :class:`ClusterSyncTimeout` when a member fails to show within
        the deadline — the caller's cue to consult the heartbeat."""
        if self.process_count == 1:
            return
        timeout = self.timeout_s if timeout_s is None else timeout_s
        rnd = self._next_round(tag)
        t0 = time.perf_counter()
        self._publish(tag, rnd, "1")
        for m in self.members:
            if m != self.process_id:
                self.kv.get(self._key(tag, rnd, m), timeout)
        multihost_metrics.note("barriers")
        multihost_metrics.note_wait((time.perf_counter() - t0) * 1e3)

    def any_flag(self, flag: bool, tag: str = "flag",
                 timeout_s: Optional[float] = None) -> bool:
        """Cluster-wide OR of a per-member boolean — the preemption
        propagation primitive: one host's SIGTERM flag becomes every
        host's stop verdict in the SAME round, so all members drain at
        the same step boundary."""
        if self.process_count == 1:
            return bool(flag)
        timeout = self.timeout_s if timeout_s is None else timeout_s
        rnd = self._next_round(tag)
        self._publish(tag, rnd, "1" if flag else "0")
        result = bool(flag)
        for m in self.members:
            if m != self.process_id:
                result = (self.kv.get(self._key(tag, rnd, m), timeout)
                          == "1") or result
        multihost_metrics.note("flag_syncs")
        return result

    def agree_lost_ids(self, local_ids: Iterable[int],
                       suspects: Iterable[int] = (),
                       timeout_s: Optional[float] = None
                       ) -> Tuple[int, ...]:
        """Union of every RESPONSIVE member's lost-device view.
        ``suspects`` (members already believed dead, e.g. from
        heartbeat staleness) are not waited on — their silence is the
        finding, not a protocol failure."""
        mine = sorted(set(int(i) for i in local_ids))
        if self.process_count == 1:
            return tuple(mine)
        timeout = self.timeout_s if timeout_s is None else timeout_s
        suspects = set(int(s) for s in suspects)
        rnd = self._next_round("lost")
        self._publish("lost", rnd, json.dumps(mine))
        agreed = set(mine)
        for m in self.members:
            if m == self.process_id or m in suspects:
                continue
            agreed.update(json.loads(
                self.kv.get(self._key("lost", rnd, m), timeout)))
        return tuple(sorted(agreed))

    def gather(self, value: str, tag: str,
               timeout_s: Optional[float] = None
               ) -> Optional[Dict[int, str]]:
        """Every member publishes a blob; the COORDINATOR returns the
        full ``{member: blob}`` map, everyone else None.  The shard-crc
        collection step of the cluster-commit protocol."""
        if self.process_count == 1:
            return {self.process_id: value}
        timeout = self.timeout_s if timeout_s is None else timeout_s
        rnd = self._next_round(tag)
        self._publish(tag, rnd, value)
        if not self.is_coordinator:
            return None
        out = {self.process_id: value}
        for m in self.members:
            if m != self.process_id:
                out[m] = self.kv.get(self._key(tag, rnd, m), timeout)
        return out

    def exchange(self, value: str, tag: str,
                 timeout_s: Optional[float] = None) -> Dict[int, str]:
        """Every member publishes a blob and reads EVERY member's —
        the symmetric form of :meth:`gather` (same key layout, same
        two-round-lag GC safety: a member only starts round ``rnd``
        after fully completing ``rnd - 1``'s reads).  The substrate of
        the data service's staging row-count agreement.  Named
        ``exchange`` rather than the SPMD spelling ``all_gather``: this
        is a host-side KV rendezvous, not a device collective over a
        mesh axis."""
        if self.process_count == 1:
            return {self.process_id: value}
        timeout = self.timeout_s if timeout_s is None else timeout_s
        rnd = self._next_round(tag)
        self._publish(tag, rnd, value)
        out = {self.process_id: value}
        for m in self.members:
            if m != self.process_id:
                out[m] = self.kv.get(self._key(tag, rnd, m), timeout)
        return out

    def broadcast(self, value: str, tag: str,
                  timeout_s: Optional[float] = None) -> str:
        """The COORDINATOR's blob becomes every member's return value —
        one agreed value per round (the data service's epoch-seed
        primitive).  Implemented as an :meth:`exchange` so every
        member still acknowledges the round: the coordinator never runs
        ahead of a slow reader, which keeps the per-tag key GC safe."""
        return self.exchange(value, tag, timeout_s)[self.coordinator]

    # -- device topology ---------------------------------------------------
    def devices_of(self, member: int) -> Tuple[int, ...]:
        """Global device ids a member owns (explicit ``device_map`` for
        simulated clusters, else the real jax process topology)."""
        if self.device_map is not None:
            return self.device_map.get(int(member), ())
        return process_device_ids(int(member))

    def owners_of(self, device_ids: Iterable[int]) -> Tuple[int, ...]:
        """Members owning any of ``device_ids`` (unknown ids — e.g. a
        virtual-host chaos drill outside the map — own nothing)."""
        wanted = set(int(i) for i in device_ids)
        return tuple(sorted(
            m for m in self.members
            if wanted & set(self.devices_of(m))))

    def shrink(self, lost_members: Iterable[int]) -> "Cluster":
        """The surviving cluster after a host loss: same KV store, a
        NEW generation (fresh key namespace + round counter), members
        minus the lost.  The caller must be a survivor."""
        lost = set(int(m) for m in lost_members)
        survivors = [m for m in self.members if m not in lost]
        if self.process_id in lost:
            raise ValueError(
                f"process {self.process_id} is itself among the lost "
                f"members {sorted(lost)} — an evicted process exits, "
                "it does not shrink")
        if not survivors:
            raise ValueError("no surviving members")
        return Cluster(self.process_id, survivors, self.kv,
                       timeout_s=self.timeout_s,
                       generation=self.generation + 1,
                       namespace=self._namespace,
                       device_map=self.device_map)


def local_cluster() -> Cluster:
    """The degenerate single-process cluster: every primitive is a
    no-op, so single-host code paths stay byte-for-byte unchanged."""
    return Cluster(0, (0,), InProcessKV())


def active_cluster(timeout_s: float = 120.0) -> Cluster:
    """The cluster this process is actually in: jax.distributed wiring
    when initialized (KV store = the coordination service), else the
    local single-member cluster."""
    if jax.process_count() <= 1:
        return local_cluster()
    return Cluster(jax.process_index(), range(jax.process_count()),
                   DistributedKV(), timeout_s=timeout_s)


# ---------------------------------------------------------------------------
# device <-> process mapping
# ---------------------------------------------------------------------------

def process_device_ids(process_index: int) -> Tuple[int, ...]:
    """Global device ids owned by ``process_index`` — what a host
    LOSS means in device terms (the unit ``elastic_remesh`` consumes)."""
    return tuple(int(d.id) for d in jax.devices()
                 if d.process_index == process_index)


def global_data_mesh(model: int = 1,
                     devices: Optional[Sequence[jax.Device]] = None):
    """The multi-host training mesh: EVERY process's devices on one
    global ``data``(×``model``) mesh.  ``parallel.mesh.make_mesh``'s
    data-first layout puts each host's contiguous device block in the
    same data region, so ``model`` groups stay inside a host (ICI) and
    only the data-axis gradient reduction crosses hosts (DCN) — the
    layout contract the module docstring of ``parallel/mesh.py``
    promises."""
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=-1, model=model), devices=devices)


# ---------------------------------------------------------------------------
# per-process data shards
# ---------------------------------------------------------------------------

def worker_store_iterator(store, prefix: str, cluster: Cluster,
                          **kwargs):
    """Each process's OWN shard of a serialized-minibatch stream: a
    ``StoreDataSetIterator`` worker split keyed by the cluster's dense
    member rank (BucketIterator's role in the reference's multi-worker
    S3 reads).  After an eviction, re-calling with the SHRUNK cluster
    re-splits the stream over the survivors.

    Data contract: a worker split feeds PER-HOST pipelines (streaming
    ``fit_iterator`` on a host-local mesh, per-host preprocessing).
    It is NOT the input to ``ResilientFit`` on a mesh that SPANS
    hosts — feeding disjoint shards there would silently train on a
    rank-slice of a shard and desynchronize the members' step counts.
    For spanning meshes use ``datasets.data_service.DataService`` (the
    default ingest ``ResilientFit`` wires for a multi-host cluster):
    it keeps the global sample order single-host-identical while each
    process reads and stages only its own row slice."""
    from deeplearning4j_tpu.datasets.store_iterator import \
        StoreDataSetIterator

    return StoreDataSetIterator(store, prefix,
                                shard_index=cluster.member_rank,
                                num_shards=cluster.process_count,
                                **kwargs)


class StagingMismatchError(RuntimeError):
    """The processes of a cluster tried to stage DIFFERENT global
    batches: their row counts disagree.  Raised by
    :func:`stage_global_batch`'s KV-store agreement check — naming the
    disagreeing ranks — instead of letting the mismatch surface as an
    opaque XLA shape error mid-dispatch (or worse, as silently
    divergent training).  ``counts`` maps member id -> (rows_x,
    rows_y) as published."""

    def __init__(self, counts: Dict[int, Tuple[int, int]]):
        self.counts = dict(counts)
        majority = max(set(self.counts.values()),
                       key=list(self.counts.values()).count)
        outliers = sorted(m for m, c in self.counts.items()
                          if c != majority)
        super().__init__(
            f"staging row-count disagreement across the cluster: "
            f"member(s) {outliers} staged "
            f"{ {m: self.counts[m] for m in outliers} } rows while the "
            f"majority staged {majority} — every process must pass the "
            f"same logical global batch to stage_global_batch")
        self.outliers = tuple(outliers)


def _agree_staging_rows(cluster: Cluster, rows_x: int,
                        rows_y: int) -> None:
    """One KV agreement round per DISTINCT (rows_x, rows_y) this
    cluster generation stages: every member publishes its counts and
    every member checks the full map, so all of them raise the same
    typed :class:`StagingMismatchError` at the same call site (a
    divergent raise would strand the agreeing members at their next
    rendezvous).  Memoized on the cluster handle — steady-state
    training re-stages one shape forever and must not pay a KV round
    per step."""
    seen = getattr(cluster, "_staging_rows_ok", None)
    if seen is None:
        seen = cluster._staging_rows_ok = set()
    if (rows_x, rows_y) in seen:
        return
    counts = {
        m: tuple(json.loads(blob)) for m, blob in cluster.exchange(
            json.dumps([int(rows_x), int(rows_y)]),
            "stage_rows").items()}
    if len(set(counts.values())) > 1:
        raise StagingMismatchError(counts)
    seen.add((rows_x, rows_y))


def local_rows(arr, cluster: Cluster):
    """This process's contiguous row slice of a GLOBAL batch (rows
    assumed divisible by member count — the padding contract upstream
    guarantees it)."""
    n = cluster.process_count
    if n == 1:
        return arr
    per = arr.shape[0] // n
    r = cluster.member_rank
    return arr[r * per:(r + 1) * per]


def stage_global_batch(x, y, mesh, cluster: Optional[Cluster] = None):
    """Stage one padded global batch onto a (possibly multi-host) mesh
    with the example axis over ``data``.  Single-process: a plain
    sharded ``device_put`` (byte-identical to the existing staging).
    Multi-process: each process contributes only ITS row slice via
    ``jax.make_array_from_process_local_data`` — no host ever holds or
    sends rows that land on another host's devices.

    Contract: every process must pass the SAME logical global ``x``/
    ``y`` (same values, same row order, rows divisible by the member
    count) — this function slices rank-local rows out of it, it does
    not gather disjoint per-host shards into a global batch.  The row
    counts are AGREED over the cluster KV store once per distinct
    shape: a process staging a different global batch raises a typed
    :class:`StagingMismatchError` naming the disagreeing ranks, on
    every member, before anything is dispatched."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.sharded_fit import batch_sharding

    sharding = batch_sharding(mesh)
    if cluster is None or cluster.process_count == 1:
        return (jax.device_put(jnp.asarray(x), sharding),
                jax.device_put(jnp.asarray(y), sharding))
    import numpy as np

    x, y = np.asarray(x), np.asarray(y)
    _agree_staging_rows(cluster, x.shape[0], y.shape[0])
    return (jax.make_array_from_process_local_data(
                sharding, np.asarray(local_rows(x, cluster))),
            jax.make_array_from_process_local_data(
                sharding, np.asarray(local_rows(y, cluster))))


# ---------------------------------------------------------------------------
# heartbeat-based host-loss detection
# ---------------------------------------------------------------------------

class HostHeartbeat:
    """Shared-filesystem heartbeats: each member's background thread
    touches ``<dir>/hb_p<pid>`` every ``interval_s``; a member whose
    file goes ``timeout_s`` stale is presumed LOST (SIGKILLed VM,
    kernel panic, fabric partition — failures that never get to say
    goodbye).  The filesystem is the same one the checkpoint dir
    already requires, so this adds no infrastructure — it is the
    reference's Akka heartbeat reaper (MasterActor.java:139-169)
    rebuilt on the storage layer.

    ``stale_members()`` is the detector ``ResilientFit`` consults when
    a control-plane op times out; ``lost_device_ids()`` translates the
    finding into the device-id vocabulary ``elastic_remesh`` speaks."""

    def __init__(self, directory: str, cluster: Cluster,
                 interval_s: float = 2.0, timeout_s: float = 20.0):
        self.directory = directory
        self.cluster = cluster
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: grace anchor for MISSING files (reset when the monitor
        #: starts): a peer whose first heartbeat hasn't landed yet
        #: (slow start, NFS attribute-cache delay) must not read as
        #: dead the instant a sync timeout sends us looking
        self._t0 = time.time()
        os.makedirs(directory, exist_ok=True)

    def _path(self, pid: int) -> str:
        return os.path.join(self.directory, f"hb_p{pid}")

    def _beat_once(self) -> None:
        path = self._path(self.cluster.process_id)
        with open(path + ".tmp", "w") as f:
            f.write(str(time.time()))
        os.replace(path + ".tmp", path)

    def _runner(self) -> None:
        while not self._stop.is_set():
            try:
                self._beat_once()
            except OSError:
                log.exception("heartbeat write failed")
            self._stop.wait(self.interval_s)

    def start(self) -> "HostHeartbeat":
        if self._thread is None:
            self._t0 = time.time()
            self._beat_once()          # visible before the first interval
            self._thread = threading.Thread(
                target=self._runner, name="host-heartbeat", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "HostHeartbeat":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def stale_members(self) -> Tuple[int, ...]:
        """Members (excluding self) whose heartbeat is older than
        ``timeout_s`` — or missing entirely AFTER the grace of one
        timeout from monitor start (a member that never wrote one is
        as dead as one that stopped, but a peer whose FIRST beat just
        hasn't landed yet must not be declared lost)."""
        now = time.time()
        stale = []
        for m in self.cluster.members:
            if m == self.cluster.process_id:
                continue
            try:
                age = now - os.path.getmtime(self._path(m))
            except OSError:
                # missing file: age it from monitor start, not -inf
                age = now - self._t0
            if age > self.timeout_s:
                stale.append(m)
        if stale:
            multihost_metrics.note("heartbeat_stale_events")
        return tuple(stale)

    def lost_device_ids(self) -> Tuple[int, ...]:
        """Device ids owned by every currently-stale member."""
        out = []
        for m in self.stale_members():
            out.extend(self.cluster.devices_of(m))
        return tuple(sorted(out))
