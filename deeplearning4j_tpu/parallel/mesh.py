"""Device mesh construction.

The scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
collectives.  Axis names are fixed package-wide so layers/trainers can
annotate against them without knowing the topology:

- ``data``   — data parallelism (gradient sharing ≡ the reference's
               IterativeReduce/parameter averaging)
- ``model``  — tensor parallelism (new capability; SURVEY.md §2.9)
- ``pipe``   — pipeline parallelism (new capability)
- ``seq``    — sequence/context parallelism (ring attention; §5.7)
- ``expert`` — expert parallelism (MoE)

Multi-host: ``initialize_distributed`` wraps ``jax.distributed.initialize``;
mesh axes laid out so ``data`` spans hosts last (DCN-friendly: gradient
allreduce rides ICI within a slice, only crossing DCN once per step), while
``model``/``seq`` stay inside a slice (ICI-only collectives).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"

ALL_AXES = (DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS, EXPERT_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism degrees. ``data=-1`` means "absorb remaining
    devices" (like the reference sizing its worker pool to cores,
    MasterActor.java:181)."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        for name in ("model", "pipe", "seq", "expert"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} degree must be >= 1, got "
                                 f"{getattr(self, name)}")
        if self.data != -1 and self.data < 1:
            raise ValueError(f"data degree must be >= 1 or -1 (absorb), "
                             f"got {self.data}")
        fixed = self.model * self.pipe * self.seq * self.expert
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by model*pipe*seq*expert="
                f"{fixed}")
        data = self.data if self.data > 0 else n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{fixed} != {n_devices} devices")
        return {DATA_AXIS: data, MODEL_AXIS: self.model, PIPE_AXIS: self.pipe,
                SEQ_AXIS: self.seq, EXPERT_AXIS: self.expert}


def make_mesh(spec: MeshSpec | None = None,
              devices: Optional[Sequence[jax.Device]] = None,
              axis_order: Tuple[str, ...] = ALL_AXES) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    ``data`` is the FIRST axis so contiguous device blocks — which JAX
    orders hosts-major — fall into the same data shard: model/seq
    collectives then run between neighboring chips (ICI), and only the
    data-axis allreduce crosses host boundaries (DCN).
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in axis_order)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_order)


def data_sharding(mesh: Mesh, *, extra_axes: int = 1) -> NamedSharding:
    """Batch sharding: leading axis over ``data``, rest replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * extra_axes)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (jax.distributed over DCN) — the replacement for
    the reference's Akka cluster join (WorkerActor joining MASTER_URL,
    DeepLearning4jDistributed.setup:301-315).  No-op when single-process."""
    if coordinator_address is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def initialize_from_env() -> bool:
    """Cluster wiring from the environment the cloud/provision.py launch
    scripts export: ``DL4J_TPU_COORDINATOR`` (host:port),
    ``DL4J_TPU_NUM_PROCESSES``, ``DL4J_TPU_PROCESS_ID`` — the MASTER_URL
    role of the reference's worker env (DeepLearning4jDistributed).
    Returns False (no-op) when no wiring is present; on real TPU pods
    the launch may instead rely on jax's own pod auto-detection."""
    import os

    coord = os.environ.get("DL4J_TPU_COORDINATOR")
    if not coord:
        return False
    missing = [k for k in ("DL4J_TPU_NUM_PROCESSES", "DL4J_TPU_PROCESS_ID")
               if k not in os.environ]
    if missing:
        raise ValueError(
            f"DL4J_TPU_COORDINATOR is set but {missing} missing — the "
            f"wiring trio (DL4J_TPU_COORDINATOR, DL4J_TPU_NUM_PROCESSES, "
            f"DL4J_TPU_PROCESS_ID) must be exported together")
    initialize_distributed(
        coord,
        int(os.environ["DL4J_TPU_NUM_PROCESSES"]),
        int(os.environ["DL4J_TPU_PROCESS_ID"]))
    return True


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    n = mesh.shape[DATA_AXIS]
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"data-parallel degree {n}")
    return global_batch // n
