"""Device mesh construction.

The scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
collectives.  Axis names are fixed package-wide so layers/trainers can
annotate against them without knowing the topology:

- ``data``   — data parallelism (gradient sharing ≡ the reference's
               IterativeReduce/parameter averaging)
- ``model``  — tensor parallelism (new capability; SURVEY.md §2.9)
- ``pipe``   — pipeline parallelism (new capability)
- ``seq``    — sequence/context parallelism (ring attention; §5.7)
- ``expert`` — expert parallelism (MoE)

Multi-host: ``initialize_distributed`` wraps ``jax.distributed.initialize``;
mesh axes laid out so ``data`` spans hosts last (DCN-friendly: gradient
allreduce rides ICI within a slice, only crossing DCN once per step), while
``model``/``seq`` stay inside a slice (ICI-only collectives).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"

ALL_AXES = (DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS, EXPERT_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism degrees. ``data=-1`` means "absorb remaining
    devices" (like the reference sizing its worker pool to cores,
    MasterActor.java:181)."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        for name in ("model", "pipe", "seq", "expert"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} degree must be >= 1, got "
                                 f"{getattr(self, name)}")
        if self.data != -1 and self.data < 1:
            raise ValueError(f"data degree must be >= 1 or -1 (absorb), "
                             f"got {self.data}")
        fixed = self.model * self.pipe * self.seq * self.expert
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by model*pipe*seq*expert="
                f"{fixed}")
        data = self.data if self.data > 0 else n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{fixed} != {n_devices} devices")
        return {DATA_AXIS: data, MODEL_AXIS: self.model, PIPE_AXIS: self.pipe,
                SEQ_AXIS: self.seq, EXPERT_AXIS: self.expert}


def make_mesh(spec: MeshSpec | None = None,
              devices: Optional[Sequence[jax.Device]] = None,
              axis_order: Tuple[str, ...] = ALL_AXES) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    ``data`` is the FIRST axis so contiguous device blocks — which JAX
    orders hosts-major — fall into the same data shard: model/seq
    collectives then run between neighboring chips (ICI), and only the
    data-axis allreduce crosses host boundaries (DCN).
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in axis_order)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_order)


def data_sharding(mesh: Mesh, *, extra_axes: int = 1) -> NamedSharding:
    """Batch sharding: leading axis over ``data``, rest replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * extra_axes)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (jax.distributed over DCN) — the replacement for
    the reference's Akka cluster join (WorkerActor joining MASTER_URL,
    DeepLearning4jDistributed.setup:301-315).  No-op when single-process."""
    if coordinator_address is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def initialize_from_env() -> bool:
    """Cluster wiring from the environment the cloud/provision.py launch
    scripts export: ``DL4J_TPU_COORDINATOR`` (host:port),
    ``DL4J_TPU_NUM_PROCESSES``, ``DL4J_TPU_PROCESS_ID`` — the MASTER_URL
    role of the reference's worker env (DeepLearning4jDistributed).
    Returns False (no-op) when no wiring is present; on real TPU pods
    the launch may instead rely on jax's own pod auto-detection.

    Thin delegate: ``parallel/multihost.py`` owns the ONE
    implementation of the env/flag contract (``resolve_cluster_config``
    merges the trio with the ``cli.py train`` launcher flags, flags >
    env; ``multihost.initialize`` adds bounded join retry/backoff with
    typed timeout errors on top of the plain ``initialize_distributed``
    wrapper above)."""
    from deeplearning4j_tpu.parallel import multihost

    return multihost.initialize_from_env()


def local_batch_size(global_batch: int, mesh: Mesh, *,
                     pad: bool = True) -> int:
    """Per-shard batch size for a global batch over ``mesh``'s data axis.

    Non-divisible batches are legal: the trailing remainder is zero-PADDED
    up to the next multiple and its rows masked out of the loss/grad
    (the serving engine's zero-pad + slice-out idiom applied to training;
    ``pad_global_batch`` builds the padded arrays + valid count).  Only a
    batch smaller than the data-parallel degree is a hard error — there
    is no shard assignment where every device holds at least one real
    row, so the caller picked the wrong mesh (or should train
    single-device).  ``pad=False`` restores the strict divisibility
    check for callers that cannot mask (arbitrary external loss fns)."""
    n = mesh.shape[DATA_AXIS]
    if global_batch < n:
        raise ValueError(
            f"global batch {global_batch} < data-parallel degree {n}: "
            f"at least one example per shard is required — use a bigger "
            f"batch or a smaller mesh (MeshSpec(data=...))")
    if global_batch % n != 0:
        if not pad:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"data-parallel degree {n} (pad=False)")
        return -(-global_batch // n)        # ceil: trailing shard padded
    return global_batch // n


def padded_global_batch(global_batch: int, mesh: Mesh,
                        multiple: int = 1) -> int:
    """Smallest padded size >= ``global_batch`` divisible by
    ``data_degree * multiple`` (``multiple`` = microbatch accumulation
    factor, so every shard's local batch splits evenly into
    microbatches)."""
    local_batch_size(global_batch, mesh)    # batch >= degree check
    chunk = mesh.shape[DATA_AXIS] * max(multiple, 1)
    return -(-global_batch // chunk) * chunk


def pad_rows(arr, target: int):
    """Zero-pad the example (leading) axis up to ``target`` rows — THE
    padding primitive every DP path shares (fit paths, the sharded
    prefetch stage, ResilientFit); padded rows carry zero weight in the
    masked loss so they contribute nothing to loss or gradient."""
    import jax.numpy as jnp

    b = arr.shape[0]
    if b == target:
        return jnp.asarray(arr)
    return jnp.pad(jnp.asarray(arr),
                   [(0, target - b)] + [(0, 0)] * (arr.ndim - 1))


def pad_global_batch(x, y, mesh: Mesh, multiple: int = 1):
    """Zero-pad ``x``/``y`` rows up to ``padded_global_batch`` — returns
    ``(x_pad, y_pad, n_valid)``.  Padding rows carry zero weight in the
    sharded step's masked loss, so the gradient equals the unpadded
    batch's exactly (tests assert it)."""
    b = x.shape[0]
    target = padded_global_batch(b, mesh, multiple)
    return pad_rows(x, target), pad_rows(y, target), b


def mesh_signature(mesh: Optional[Mesh]):
    """Hashable identity for compile-cache keys: axis layout AND the
    concrete device assignment.  Two meshes of the same shape over
    DIFFERENT devices must not share a cached executable (the compiled
    shard_map closure pins its devices), so the device ids are part of
    the signature — no silent cross-mesh cache hits.  The axis SIZES are
    equally load-bearing: a 2x4 data×model mesh and an 8x1 data mesh
    over the SAME eight devices compile different programs (different
    param layouts, different collectives), and the signature keeps them
    distinct entries."""
    if mesh is None:
        return None
    return (tuple(zip(mesh.axis_names,
                      (mesh.shape[a] for a in mesh.axis_names))),
            tuple(int(d.id) for d in mesh.devices.flat))


def model_degree(mesh: Optional[Mesh]) -> int:
    """Tensor-parallel degree of ``mesh`` (1 when absent/None)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(MODEL_AXIS, 1))


def pipe_degree(mesh: Optional[Mesh]) -> int:
    """Pipeline-parallel degree of ``mesh`` (1 when absent/None)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(PIPE_AXIS, 1))


def expert_degree(mesh: Optional[Mesh]) -> int:
    """Expert-parallel degree of ``mesh`` (1 when absent/None)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(EXPERT_AXIS, 1))


def seq_degree(mesh: Optional[Mesh]) -> int:
    """Sequence-parallel degree of ``mesh`` (1 when absent/None)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get(SEQ_AXIS, 1))


def per_device_bytes(tree) -> Dict[int, int]:
    """Bytes each device ACTUALLY holds for ``tree``'s arrays, summed
    from their addressable shards — the HBM-accounting primitive behind
    the model-parallel bench row and the per-chip ~1/model_degree
    assertion (a replicated layout charges every device the full
    footprint; a model-sharded one charges each device its shard plus
    the replicated leftovers).  Host-resident leaves without shards
    (plain numpy) contribute nothing."""
    out: Dict[int, int] = {}
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue
        for s in shards:
            did = int(s.device.id)
            out[did] = out.get(did, 0) + int(s.data.nbytes)
    return out


#: memoized auto-detected data mesh (keyed on the live device list so a
#: re-initialized backend rebuilds it)
_AUTO_MESH: Optional[Tuple[Tuple[int, ...], Mesh]] = None


def auto_data_mesh(devices: Optional[Sequence[jax.Device]] = None
                   ) -> Optional[Mesh]:
    """The default-fit mesh: every visible device on the ``data`` axis.
    Returns None on a single device (nothing to shard over) — callers
    fall back to the single-device path.  This is the auto-detection
    behind ``MultiLayerNetwork.fit_backprop(mesh="auto")``; pass an
    explicit ``make_mesh(...)`` to override per call.

    An explicit ``devices`` list (the elastic-resume path: the
    SURVIVORS of a device loss) bypasses the process-wide memo — the
    memo caches the healthy-fleet answer and must not be poisoned by a
    degraded run's subset."""
    global _AUTO_MESH
    if devices is not None:
        devices = list(devices)
        if len(devices) < 2:
            return None
        return make_mesh(MeshSpec(data=-1), devices=devices)
    devices = jax.devices()
    if len(devices) < 2:
        return None
    dev_ids = tuple(d.id for d in devices)
    if _AUTO_MESH is None or _AUTO_MESH[0] != dev_ids:
        _AUTO_MESH = (dev_ids, make_mesh(MeshSpec(data=-1),
                                         devices=devices))
    return _AUTO_MESH[1]


# -- elastic re-meshing (device loss / preemption survival) -----------------

class RemeshError(ValueError):
    """A device loss the host-side driver cannot recover from by
    shrinking the data axis: the survivors cannot field even ONE intact
    ``model``×``pipe``(×``seq``×``expert``) group, or nothing survived
    at all.  Typed (not a silent fallback) so ``ResilientFit`` and the
    multihost drills can distinguish "re-mesh and continue" from "this
    fleet is dead — restore onto new hardware"."""


def surviving_devices(mesh: Mesh, lost_ids) -> list:
    """The mesh's devices minus the lost ones, in mesh order."""
    lost = set(int(i) for i in lost_ids)
    return [d for d in mesh.devices.flat if int(d.id) not in lost]


def elastic_remesh(mesh: Mesh, lost_ids,
                   grad_accum: int = 1) -> Tuple[Optional[Mesh], int]:
    """Rebuild a mesh over the survivors of a device loss while
    PRESERVING the effective batch: returns ``(new_mesh, new_accum)``
    with ``new_data_degree * new_accum == old_data_degree * grad_accum``
    — the PR 5 sum-loss formulation makes the re-meshed run
    BIT-identical to the uninterrupted one at equal effective batch, so
    "same run, smaller mesh" is an equivalence, not an approximation.

    Only the DATA axis shrinks.  Every OTHER degree — ``model``,
    ``pipe``, ``seq``, ``expert`` — is preserved verbatim: those
    layouts are baked into the weight/activation shards, so the
    recovery keeps whole ``model``×``pipe``(×``seq``×``expert``) groups
    and drops data replicas.  The new data degree is the LARGEST group
    count the survivors can field that divides the old effective
    factor.  When the survivors cannot hold even ONE intact group, the
    loss is unrecoverable by a host-side driver and raises a typed
    ``RemeshError`` naming the surviving count and the required divisor
    (restoring onto fewer devices than one group needs a resharding
    restore onto a shape chosen by the operator, see
    ``load_pytree_sharded``).

    For pure data meshes, ``new_mesh`` is None when only one device
    survives or only degree 1 divides: the caller continues
    single-device with ``new_accum = old_degree * grad_accum``.  A
    mesh with any non-data degree > 1 never collapses to None — a
    ``1×model×pipe`` mesh is still a mesh (the weights stay sharded)."""
    survivors = surviving_devices(mesh, lost_ids)
    if not survivors:
        raise RemeshError(
            f"device loss {sorted(set(int(i) for i in lost_ids))} leaves "
            "no survivors in this mesh — nothing to resume on")
    model = int(mesh.shape.get(MODEL_AXIS, 1))
    pipe = int(mesh.shape.get(PIPE_AXIS, 1))
    seq = int(mesh.shape.get(SEQ_AXIS, 1))
    expert = int(mesh.shape.get(EXPERT_AXIS, 1))
    group = model * pipe * seq * expert
    eff = mesh.shape[DATA_AXIS] * max(grad_accum, 1)
    if group > 1:
        groups = len(survivors) // group
        if groups < 1:
            raise RemeshError(
                f"device loss leaves {len(survivors)} surviving "
                f"device(s), fewer than one intact model×pipe group of "
                f"model*pipe*seq*expert={group}: the survivor count must "
                f"be divisible into groups of {group} (required divisor "
                f"{group}) to keep the sharded weight layout — restore "
                f"onto a fleet of at least {group} devices instead")
        degree = next(n for n in range(groups, 0, -1) if eff % n == 0)
        return (make_mesh(MeshSpec(data=degree, model=model, pipe=pipe,
                                   seq=seq, expert=expert),
                          devices=survivors[:degree * group]),
                eff // degree)
    degree = next(n for n in range(len(survivors), 0, -1) if eff % n == 0)
    new_accum = eff // degree
    if degree < 2:
        return None, new_accum
    return (make_mesh(MeshSpec(data=degree), devices=survivors[:degree]),
            new_accum)
