"""Pipeline parallelism — GPipe-style microbatched SPMD pipeline.

New capability with no reference counterpart (SURVEY.md §2.9: the reference
has no pipeline parallelism; its only stage-wise scheduling is greedy
layer-wise pretraining, MultiLayerNetwork.pretrain).  Built TPU-first:

- The net is split into ``n_stages`` equal stages laid out over the mesh
  ``pipe`` axis; every device holds ONLY its stage's parameters (stacked
  ``[n_stages, ...]`` pytree sharded on the leading axis).
- One jitted SPMD program runs on all stages (shard_map): at each tick every
  device applies its stage to its resident activation, then the activation
  ring-shifts to the next stage via ``lax.ppermute`` (neighbor ICI hop — the
  cheapest collective on a TPU torus).
- Microbatches enter at stage 0 one per tick and exit at the last stage
  after ``n_stages - 1`` ticks of fill; total ticks =
  ``n_micro + n_stages - 1`` (the GPipe bubble).  Reverse-mode autodiff
  through the scan+ppermute yields the mirrored backward pipeline
  automatically — no hand-written schedule.
- Composes with data parallelism: the microbatch's batch dim may be sharded
  over ``data``; XLA inserts the gradient psum when the loss is reduced.

Typical use: ``stage_fn(stage_params, x) -> y`` with ``y.shape == x.shape``
(e.g. a run of transformer blocks); embed/unembed live inside the first and
last stage respectively, or outside the pipelined region.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from deeplearning4j_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS

Array = jax.Array
PyTree = Any
StageFn = Callable[[PyTree, Array], Array]


def pipeline_forward(stage_fn: StageFn, stage_params: PyTree,
                     microbatches: PyTree,
                     axis_name: str = PIPE_AXIS) -> PyTree:
    """SPMD pipelined forward.  MUST run inside shard_map with ``axis_name``
    bound; every shard holds its own ``stage_params`` and the same
    ``microbatches`` — a ``[n_micro, mb, ...]`` array or a pytree of such
    arrays (e.g. ``(hidden, attention_mask)``: everything a stage needs that
    varies per microbatch rides the ring together); returns the same
    structure of ``[n_micro, mb, ...]`` outputs (identical on every shard).
    ``stage_fn`` must map its input structure to the SAME structure/shapes
    (pass riders like masks through unchanged).

    Tick ``t``: stage ``s`` processes microbatch ``t - s`` (when in range),
    so the last stage emits microbatch ``t - (n_stages-1)`` at tick ``t``.
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = jax.tree.leaves(microbatches)[0].shape[0]
    shift = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    is_first = stage == 0
    is_last = stage == n_stages - 1

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t; everyone else takes the ring input.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inject = jax.tree.map(
            lambda m: lax.dynamic_index_in_dim(m, mb_idx, 0, keepdims=False),
            microbatches)
        x = jax.tree.map(lambda i, s: jnp.where(is_first, i, s),
                         inject, state)
        y = stage_fn(stage_params, x)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        valid = jnp.logical_and(is_last, t >= n_stages - 1)

        def upd(outs, yl):
            prev = lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, yl, prev), out_idx, 0)

        outputs = jax.tree.map(upd, outputs, y)
        state = jax.tree.map(lambda yl: lax.ppermute(yl, axis_name, shift), y)
        return (state, outputs), None

    state0 = jax.tree.map(lambda m: jnp.zeros_like(m[0]), microbatches)
    out0 = jax.tree.map(jnp.zeros_like, microbatches)
    (state, outputs), _ = lax.scan(
        tick, (state0, out0), jnp.arange(n_micro + n_stages - 1))
    # outputs are only populated on the last stage; psum-broadcast them so
    # every shard (and the caller outside shard_map) sees the result.
    return jax.tree.map(
        lambda o: lax.psum(jnp.where(is_last, o, jnp.zeros_like(o)),
                           axis_name), outputs)


def to_microbatches(x: PyTree, n_micro: int) -> PyTree:
    """[B, ...] -> [n_micro, B/n_micro, ...] on every leaf."""
    def split(leaf):
        b = leaf.shape[0]
        if b % n_micro != 0:
            raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
        return leaf.reshape((n_micro, b // n_micro) + leaf.shape[1:])
    return jax.tree.map(split, x)


def from_microbatches(x: PyTree) -> PyTree:
    return jax.tree.map(
        lambda l: l.reshape((l.shape[0] * l.shape[1],) + l.shape[2:]), x)


def stack_stage_params(per_stage: Sequence[PyTree]) -> PyTree:
    """List of per-stage param pytrees -> stacked [n_stages, ...] pytree
    (leading axis is what gets sharded over ``pipe``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def make_pipeline_fn(mesh: Mesh, stage_fn: StageFn, n_micro: int,
                     data_sharded: bool = True):
    """Build ``f(stacked_params, batch) -> out`` running the GPipe pipeline
    over ``mesh``'s ``pipe`` axis (and batch over ``data`` if present).

    ``stacked_params`` leaves have leading dim n_stages = mesh.shape['pipe'];
    ``batch`` is ``[B, ...]`` with ``B`` divisible by ``n_micro`` (and the
    microbatch size divisible by the data degree).
    """
    bdim = DATA_AXIS if data_sharded and mesh.shape.get(DATA_AXIS, 1) > 1 \
        else None
    xspec = P(None, bdim)          # [n_micro, mb, ...]: mb over data
    pspec = P(PIPE_AXIS)           # prefix spec: leading stage axis

    def inner(stacked, micro):
        own = jax.tree.map(lambda p: p[0], stacked)   # this shard's stage
        return pipeline_forward(stage_fn, own, micro)

    sharded = shard_map(inner, mesh=mesh, in_specs=(pspec, xspec),
                        out_specs=xspec, check_vma=False)

    n_stages = mesh.shape[PIPE_AXIS]

    def apply(stacked_params, batch):
        for leaf in jax.tree.leaves(stacked_params):
            if leaf.shape[0] != n_stages:
                raise ValueError(
                    f"stacked params leading dim {leaf.shape[0]} != pipe "
                    f"degree {n_stages}; each shard must own exactly one "
                    f"stage (use split_layers_into_stages for deeper nets)")
        micro = to_microbatches(batch, n_micro)
        return from_microbatches(sharded(stacked_params, micro))

    return apply


def make_pipeline_train_step(mesh: Mesh, stage_fn: StageFn,
                             loss_fn: Callable[[Array, Array], Array],
                             n_micro: int, optimizer=None,
                             learning_rate: float = 1e-2):
    """Full dp+pp training step: pipelined forward, loss vs targets, grads
    through the mirrored backward pipeline, SGD (or optax) update.

    Returns ``(init_opt_state, step)`` where
    ``step(params, opt_state, batch, targets) -> (params, opt_state, loss)``.
    ``params`` is the stacked [n_stages, ...] pytree (shard it with
    ``stage_param_sharding`` before passing for zero relayout).
    """
    fwd = make_pipeline_fn(mesh, stage_fn, n_micro)

    def loss_of(params, batch, targets):
        out = fwd(params, batch)
        return loss_fn(out, targets)

    if optimizer is None:
        def init_opt(params):
            return ()

        @jax.jit
        def step(params, opt_state, batch, targets):
            loss, grads = jax.value_and_grad(loss_of)(params, batch, targets)
            params = jax.tree.map(lambda p, g: p - learning_rate * g,
                                  params, grads)
            return params, opt_state, loss
        return init_opt, step

    def init_opt(params):
        return optimizer.init(params)

    @jax.jit
    def step(params, opt_state, batch, targets):
        loss, grads = jax.value_and_grad(loss_of)(params, batch, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss
    return init_opt, step


def stage_param_sharding(mesh: Mesh, stacked_params: PyTree) -> PyTree:
    """NamedShardings placing each stage's params on its pipe shard."""
    from jax.sharding import NamedSharding
    sh = NamedSharding(mesh, P(PIPE_AXIS))
    return jax.tree.map(lambda _: sh, stacked_params)


def split_layers_into_stages(stacked_layer_params: PyTree,
                             n_stages: int) -> PyTree:
    """Reshape a ``[n_layers, ...]`` scanned-blocks pytree (e.g. the
    transformer's) into ``[n_stages, layers_per_stage, ...]`` so each pipe
    shard scans its own run of blocks."""
    def resh(p):
        n_layers = p.shape[0]
        if n_layers % n_stages != 0:
            raise ValueError(
                f"n_layers={n_layers} not divisible by n_stages={n_stages}")
        return p.reshape((n_stages, n_layers // n_stages) + p.shape[1:])
    return jax.tree.map(resh, stacked_layer_params)
