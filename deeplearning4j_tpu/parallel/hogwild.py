"""Asynchronous (Hogwild-style) training — the reference's async path.

Reference parity: ``HogWildWorkRouter.java:30`` ("always send; async
lock-free") + the Hazelcast StateTracker update flow: workers pull current
params, train locally, push deltas; the master folds deltas in as they
arrive with NO barrier — races embraced by design (SURVEY.md §5.2).

TPU-native design: SPMD collectives are inherently synchronous, so async
lives on the HOST (SURVEY.md §7 "hard parts" — a deliberate async-update
design that preserves the capability without fighting XLA):

- each worker thread drives its own jit-compiled train step (on its own
  device when several are visible, else time-sharing one chip);
- the ``StateTracker`` coordinator holds the current global params;
- workers push PARAMETER DELTAS (new - pulled) which the aggregator thread
  applies immediately — stale-gradient semantics identical to Hogwild;
- an ``IterateAndUpdate``-style drain folds updates through an aggregator
  (INDArrayAggregator parity = running mean) when sync rounds are wanted.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.updaters import Dl4jUpdater, apply_updates
from deeplearning4j_tpu.parallel.coordinator import Job, StateTracker

Array = jax.Array
PyTree = Any
LossFn = Callable[[PyTree, Array, Array, Array], Array]


class INDArrayAggregator:
    """Running parameter average (scaleout/aggregator/INDArrayAggregator
    .java:35-60 parity)."""

    def __init__(self):
        self._sum: Optional[PyTree] = None
        self._n = 0

    def accumulate(self, params: PyTree) -> None:
        if self._sum is None:
            self._sum = params
        else:
            self._sum = jax.tree.map(jnp.add, self._sum, params)
        self._n += 1

    def aggregate(self) -> PyTree:
        assert self._sum is not None, "nothing accumulated"
        return jax.tree.map(lambda s: s / self._n, self._sum)


class HogwildTrainer:
    """Async param-delta training over worker threads + StateTracker."""

    def __init__(self, loss_fn: LossFn, updater: Dl4jUpdater,
                 num_workers: int = 2, local_steps: int = 1,
                 devices: Optional[Sequence[jax.Device]] = None):
        self.loss_fn = loss_fn
        self.updater = updater
        self.num_workers = num_workers
        self.local_steps = local_steps
        self.devices = list(devices) if devices else jax.devices()
        self.tracker = StateTracker()
        self._lock = threading.Lock()  # protects the global-param fold only
        self._abort = threading.Event()  # set on worker crash -> all exit

        def local_train(params, ustate, x, y, key, it0):
            def body(carry, i):
                p, u = carry
                k = jax.random.fold_in(key, i)
                score, grads = jax.value_and_grad(self.loss_fn)(p, x, y, k)
                upd, u = self.updater.update(u, grads, p, it0 + i, 1)
                return (apply_updates(p, upd), u), score

            (params, ustate), scores = jax.lax.scan(
                body, (params, ustate), jnp.arange(self.local_steps))
            return params, ustate, scores[-1]

        self._local_train = jax.jit(local_train)

    def _worker(self, wid: str, key: Array, errors: List[BaseException]) -> None:
        job = None
        try:
            dev = self.devices[int(wid.split("-")[-1]) % len(self.devices)]
            ustate = None
            local = None  # this worker's params replica
            while not self._abort.is_set():
                self.tracker.heartbeat(wid)
                job = self.tracker.job_for(wid)
                if job is None:
                    if not self.tracker.has_pending():
                        return
                    time.sleep(0.001)
                    continue
                x, y = job.work
                # replicate-on-demand (WorkerActor.checkJobAvailable parity):
                # pull global params only when the tracker flagged them stale
                if local is None or self.tracker.needs_replicate(wid):
                    local = self.tracker.get_current()
                    self.tracker.done_replicating(wid)
                pulled = local
                if ustate is None:
                    ustate = self.updater.init(pulled)
                key, sub = jax.random.split(key)
                with jax.default_device(dev):
                    new_params, ustate, score = self._local_train(
                        pulled, ustate, x, y, sub,
                        jnp.asarray(self.tracker.count("iterations")))
                local = new_params
                # push the DELTA and fold it into the global params NOW —
                # async, stale-tolerant (Hogwild)
                delta = jax.tree.map(jnp.subtract, new_params, pulled)
                with self._lock:
                    current = self.tracker.get_current()
                    self.tracker.set_current(
                        jax.tree.map(jnp.add, current, delta))
                self.tracker.done_replicating(wid)  # our own fold isn't stale
                job.result = float(score)
                self.tracker.add_update(wid, job)
                self.tracker.increment("iterations")
                self.tracker.clear_job(wid)
                job = None
        except BaseException as e:  # surface worker crashes to the driver
            errors.append(e)
            self._abort.set()  # stop peers: don't spin on an orphaned job
            if job is not None:
                self.tracker.clear_job(wid)

    def fit(self, params: PyTree, batches: Iterable[Tuple[Array, Array]],
            seed: int = 0) -> PyTree:
        self.tracker.set_current(params)
        for b in batches:
            self.tracker.add_job(Job(work=b))
        errors: List[BaseException] = []
        threads = []
        for w in range(self.num_workers):
            wid = f"worker-{w}"
            self.tracker.add_worker(wid)
            t = threading.Thread(
                target=self._worker,
                args=(wid, jax.random.key(seed + w), errors), daemon=True)
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return self.tracker.get_current()
