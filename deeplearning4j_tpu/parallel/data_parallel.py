"""Data-parallel trainers over a device mesh.

Two sync strategies, matching the reference's two sync semantics
(SURVEY.md §2.9), both compiled as single XLA programs via ``shard_map``:

1. ``DataParallelTrainer`` — gradient sharing: every step computes local
   grads on the batch shard and ``pmean``s them over the ``data`` axis
   before the update.  This is the faithful TPU-native equivalent of
   IterativeReduce (YARN ``Master.compute`` averaging, Akka
   ``INDArrayAggregator``, Spark ``AVERAGE_EACH_ITERATION``) — averaging
   one-step-trained parameters from identical starts == averaging gradients.

2. ``ParameterAveragingTrainer`` — Spark ``SparkDl4jMultiLayer.fitDataSet``
   semantics (spark/.../SparkDl4jMultiLayer.java:155-209): each data shard
   trains LOCALLY for k steps from the same broadcast params, then
   parameters are mean-allreduced; repeat per round.  ``average_each_round``
   mirrors the ``org.deeplearning4j.spark.iteration.average`` key.

Both trainers take an arbitrary differentiable ``loss_fn(params, x, y, key)``
so they serve MultiLayerNetwork, BERT, or any model family.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from deeplearning4j_tpu.compat import shard_map

from deeplearning4j_tpu.ops.updaters import Dl4jUpdater, apply_updates
from deeplearning4j_tpu.parallel import collectives
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS
from deeplearning4j_tpu.runtime import compile_cache, resilience

Array = jax.Array
PyTree = Any
LossFn = Callable[[PyTree, Array, Array, Array], Array]


def _note_skips(skips) -> None:
    """Book guard-skipped DP steps — one device sync per fit; shared
    impl in runtime/resilience.py."""
    resilience.note_skips(skips, where="data-parallel")


class DataParallelTrainer:
    """Synchronous gradient-sharing DP (grads pmean'd over ICI each step)."""

    def __init__(self, loss_fn: LossFn, updater: Dl4jUpdater, mesh: Mesh,
                 donate: bool = True):
        self.loss_fn = loss_fn
        self.updater = updater
        self.mesh = mesh
        self.donate = donate

        # All mesh axes except `data` are unused here; Replicate over them.
        param_spec = P()
        batch_spec = P(DATA_AXIS)

        def step(params, ustate, x, y, key, it):
            # Per-shard loss/grads; each shard sees its local batch slice.
            # Fold the data-axis index into the key so dropout/sampling
            # noise differs per shard.
            shard_key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
            score, grads = jax.value_and_grad(self.loss_fn)(
                params, x, y, shard_key)
            grads = collectives.grad_share(grads, DATA_AXIS)
            score = lax.pmean(score, DATA_AXIS)
            updates, new_ustate = self.updater.update(
                ustate, grads, params, it, 1)
            # in-step anomaly guard AFTER the collective: one shard's
            # non-finite gradient poisons every replica's pmean, so the
            # guard sees the shared grads/score and all replicas skip
            # identically (no divergence).  Same XLA program either way.
            new_params, new_ustate, skipped = resilience.guard_update(
                params, ustate, apply_updates(params, updates),
                new_ustate, (score, grads))
            return new_params, new_ustate, score, skipped

        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(param_spec, param_spec, batch_spec, batch_spec,
                      P(), P()),
            out_specs=(param_spec, param_spec, P(), P()),
            check_vma=False,
        )
        # through the compile engine for the compile counters; no
        # cross-instance key (loss_fn is an arbitrary user closure).
        # step() donates params/ustate raw; fit() copies on entry.
        self._step = compile_cache.cached_jit(
            sharded, label="parallel.dp_step",
            donate_argnums=(0, 1) if donate else ())

    def init_state(self, params: PyTree) -> PyTree:
        return self.updater.init(params)

    def step(self, params: PyTree, ustate: PyTree, x: Array, y: Array,
             key: Array, iteration: int | Array):
        """One global step. x/y are GLOBAL batches (leading dim divisible by
        the data-parallel degree)."""
        return self._step(params, ustate, x, y, key,
                          jnp.asarray(iteration))

    def fit(self, params: PyTree, batches: Iterable[Tuple[Array, Array]],
            key: Array, listeners=()) -> PyTree:
        # donation guard: the first step consumes its params/ustate args;
        # copy once so the caller's arrays stay valid (pointless when the
        # trainer was built non-donating, so skip the traffic then)
        if self.donate:
            params = jax.tree.map(jnp.copy, params)
        ustate = self.init_state(params)
        skips = []
        for it, (x, y) in enumerate(batches):
            key, sub = jax.random.split(key)
            params, ustate, score, skipped = self.step(
                params, ustate, x, y, sub, it)
            skips.append(skipped)
            for ls in listeners:
                ls.iteration_done(self, it, float(score))
        _note_skips(skips)
        return params


class ParameterAveragingTrainer:
    """Spark-semantics DP: local k-step training then parameter averaging."""

    def __init__(self, loss_fn: LossFn, updater: Dl4jUpdater, mesh: Mesh,
                 local_steps: int = 1, average_each_round: bool = True):
        self.loss_fn = loss_fn
        self.updater = updater
        self.mesh = mesh
        self.local_steps = local_steps
        self.average_each_round = average_each_round

        # Params are carried with an explicit per-shard leading axis
        # [ndp, ...] sharded over `data` — each shard owns its replica
        # (the Spark executors' local nets), letting replicas DIVERGE
        # between averages when average_each_round=False.
        def round_fn(stacked, x, y, key, it0):
            params = jax.tree.map(lambda a: a[0], stacked)  # this shard's copy
            shard_key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
            ustate = self.updater.init(params)

            def local_step(carry, i):
                p, u = carry
                k = jax.random.fold_in(shard_key, i)
                score, grads = jax.value_and_grad(self.loss_fn)(p, x, y, k)
                upd, new_u = self.updater.update(u, grads, p, it0 + i, 1)
                # per-replica guard: this shard's bad batch skips ONLY
                # its local update; the round's param_average then mixes
                # the healthy replicas back in (self-healing averaging)
                new_p, new_u, skipped = resilience.guard_update(
                    p, u, apply_updates(p, upd), new_u, (score, grads))
                return (new_p, new_u), (score, skipped)

            (params, _), (scores, skipped) = lax.scan(
                local_step, (params, ustate), jnp.arange(self.local_steps))
            if self.average_each_round:
                params = collectives.param_average(params, DATA_AXIS)
            score = lax.pmean(scores[-1], DATA_AXIS)
            n_skipped = lax.psum(jnp.sum(skipped), DATA_AXIS)
            return (jax.tree.map(lambda a: a[None], params), score,
                    n_skipped)

        # the stacked [ndp, ...] replicas are the big HBM tenant here and
        # are loop-threaded (born fresh from the broadcast in fit) —
        # donate them so each round updates replicas in place
        self._round = compile_cache.cached_jit(shard_map(
            round_fn, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
            out_specs=(P(DATA_AXIS), P(), P()),
            check_vma=False,
        ), label="parallel.param_avg_round", donate_argnums=(0,))

        def avg(stacked):
            def inner(s):
                p = collectives.param_average(
                    jax.tree.map(lambda a: a[0], s), DATA_AXIS)
                return jax.tree.map(lambda a: a[None], p)
            return shard_map(inner, mesh=mesh, in_specs=(P(DATA_AXIS),),
                             out_specs=P(DATA_AXIS), check_vma=False)(stacked)

        self._final_avg = compile_cache.cached_jit(
            avg, label="parallel.param_avg_final")
        self._ndp = mesh.shape[DATA_AXIS]

    def fit(self, params: PyTree, batches: Iterable[Tuple[Array, Array]],
            key: Array, listeners=()) -> PyTree:
        """Rounds over global batches (repartition ≡ batch iteration).
        Takes and returns UNSTACKED (single-replica) params — the broadcast
        and final collect are internal, like Spark's driver."""
        stacked = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (self._ndp,) + a.shape),
            params)
        it = 0
        skips = []
        for rnd, (x, y) in enumerate(batches):
            key, sub = jax.random.split(key)
            stacked, score, n_skipped = self._round(
                stacked, x, y, sub, jnp.asarray(it))
            skips.append(n_skipped)
            it += self.local_steps
            for ls in listeners:
                ls.iteration_done(self, rnd, float(score))
        _note_skips(skips)
        stacked = self._final_avg(stacked)
        return jax.tree.map(lambda a: a[0], stacked)
