"""Data-parallel trainers over a device mesh.

Two sync strategies, matching the reference's two sync semantics
(SURVEY.md §2.9), both compiled as single XLA programs via ``shard_map``:

1. ``DataParallelTrainer`` — gradient sharing: every step computes local
   grads on the batch shard and ``pmean``s them over the ``data`` axis
   before the update.  This is the faithful TPU-native equivalent of
   IterativeReduce (YARN ``Master.compute`` averaging, Akka
   ``INDArrayAggregator``, Spark ``AVERAGE_EACH_ITERATION``) — averaging
   one-step-trained parameters from identical starts == averaging gradients.

2. ``ParameterAveragingTrainer`` — Spark ``SparkDl4jMultiLayer.fitDataSet``
   semantics (spark/.../SparkDl4jMultiLayer.java:155-209): each data shard
   trains LOCALLY for k steps from the same broadcast params, then
   parameters are mean-allreduced; repeat per round.  ``average_each_round``
   mirrors the ``org.deeplearning4j.spark.iteration.average`` key.

Both trainers take an arbitrary differentiable ``loss_fn(params, x, y, key)``
so they serve MultiLayerNetwork, BERT, or any model family.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from deeplearning4j_tpu.compat import shard_map

from deeplearning4j_tpu.ops.updaters import Dl4jUpdater, apply_updates
from deeplearning4j_tpu.parallel import collectives, sharded_fit
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, local_batch_size
from deeplearning4j_tpu.runtime import compile_cache, resilience
from deeplearning4j_tpu.runtime.metrics import dp_metrics

Array = jax.Array
PyTree = Any
LossFn = Callable[[PyTree, Array, Array, Array], Array]


def _note_skips(skips) -> None:
    """Book guard-skipped DP steps — one device sync per fit; shared
    impl in runtime/resilience.py."""
    resilience.note_skips(skips, where="data-parallel")


class DataParallelTrainer:
    """Synchronous gradient-sharing DP (grads pmean'd over ICI each step)."""

    def __init__(self, loss_fn: LossFn, updater: Dl4jUpdater, mesh: Mesh,
                 donate: bool = True):
        self.loss_fn = loss_fn
        self.updater = updater
        self.mesh = mesh
        self.donate = donate

        # All mesh axes except `data` are unused here; Replicate over them.
        param_spec = P()
        batch_spec = P(DATA_AXIS)

        def step(params, ustate, x, y, key, it):
            # Per-shard loss/grads; each shard sees its local batch slice.
            # Fold the step index (the scanned-epoch path feeds every
            # step the same run key) and the data-axis index into the
            # key so dropout/sampling noise differs per step AND shard.
            shard_key = jax.random.fold_in(
                jax.random.fold_in(key, it), lax.axis_index(DATA_AXIS))
            score, grads = jax.value_and_grad(self.loss_fn)(
                params, x, y, shard_key)
            grads = collectives.grad_share(grads, DATA_AXIS)
            score = lax.pmean(score, DATA_AXIS)
            updates, new_ustate = self.updater.update(
                ustate, grads, params, it, 1)
            # in-step anomaly guard AFTER the collective: one shard's
            # non-finite gradient poisons every replica's pmean, so the
            # guard sees the shared grads/score and all replicas skip
            # identically (no divergence).  Same XLA program either way.
            new_params, new_ustate, skipped = resilience.guard_update(
                params, ustate, apply_updates(params, updates),
                new_ustate, (score, grads))
            return new_params, new_ustate, score, skipped

        def shard_step(params, ustate, batch, key, it):
            x, y = batch
            return step(params, ustate, x, y, key, it)

        # both dispatch shapes come from the SAME shared builder the
        # multilayer engine uses (parallel/sharded_fit.py): the per-batch
        # step for streaming, and the scanned-epoch program — ONE device
        # dispatch per fit over stacked [NB, B, ...] batches — for
        # materialized batch lists.  No cross-instance engine key
        # (loss_fn is an arbitrary user closure); steps donate
        # params/ustate raw, fit() copies on entry.
        specs = (batch_spec, batch_spec)
        self._step = sharded_fit.build_sharded_step(
            shard_step, mesh, batch_specs=specs, label="parallel.dp_step",
            donate=donate)
        self._epochs = sharded_fit.build_scanned_epochs(
            shard_step, mesh, batch_specs=specs, label="parallel.dp_epochs",
            donate=donate)

    def init_state(self, params: PyTree) -> PyTree:
        return self.updater.init(params)

    def step(self, params: PyTree, ustate: PyTree, x: Array, y: Array,
             key: Array, iteration: int | Array):
        """One global step. x/y are GLOBAL batches (leading dim divisible by
        the data-parallel degree)."""
        local_batch_size(x.shape[0], self.mesh, pad=False)
        return self._step(params, ustate, (x, y), key,
                          jnp.asarray(iteration))

    def fit(self, params: PyTree, batches: Iterable[Tuple[Array, Array]],
            key: Array, listeners=(), num_epochs: int = 1,
            scan: bool = True) -> PyTree:
        """Uniform-shape batch lists run as ONE scanned dispatch for the
        whole fit (batches stacked [NB, B, ...] and staged pre-sharded;
        listeners replayed from the scanned per-step scores afterwards —
        MIGRATION.md).  Ragged lists, or ``scan=False``, keep the
        per-batch dispatch loop.  ``num_epochs`` repeats the batch list
        with updater state carried through (scanned path only)."""
        # donation guard: the first step consumes its params/ustate args;
        # copy once so the caller's arrays stay valid (pointless when the
        # trainer was built non-donating, so skip the traffic then)
        if self.donate:
            params = jax.tree.map(jnp.copy, params)
        ustate = self.init_state(params)
        batches = list(batches)
        for x, _ in batches:
            local_batch_size(x.shape[0], self.mesh, pad=False)
        # stacking puts the whole list on device: only scan while it
        # comfortably fits in HBM (same budget as the multilayer path),
        # else keep streaming batch by batch
        total_bytes = sum(x.nbytes + y.nbytes for x, y in batches)
        uniform = (scan and len(batches) > 1
                   and total_bytes <= sharded_fit.SCAN_MAX_DATASET_BYTES
                   and len({(x.shape, y.shape) for x, y in batches}) == 1)
        if uniform:
            t0 = time.perf_counter()
            sharding = sharded_fit.stacked_sharding(self.mesh)
            xs = jax.device_put(jnp.stack([x for x, _ in batches]), sharding)
            ys = jax.device_put(jnp.stack([y for _, y in batches]), sharding)
            dp_metrics.note_staged(xs.nbytes + ys.nbytes,
                                   (time.perf_counter() - t0) * 1e3)
            params, ustate, scores, skips = self._epochs(
                params, ustate, (xs, ys), key, jnp.int32(0), num_epochs)
            dp_metrics.note_dispatch(
                steps=num_epochs * len(batches), accum=1,
                data_degree=self.mesh.shape[DATA_AXIS])
            _note_skips(skips)
            if listeners:
                for it, s in enumerate(np.asarray(scores).ravel()):
                    for ls in listeners:
                        ls.iteration_done(self, it, float(s))
            return params
        skips = []
        it = 0
        for _ in range(num_epochs):
            for (x, y) in batches:
                key, sub = jax.random.split(key)
                params, ustate, score, skipped = self._step(
                    params, ustate, (x, y), sub, jnp.asarray(it))
                skips.append(skipped)
                dp_metrics.note_dispatch(
                    steps=1, accum=1,
                    data_degree=self.mesh.shape[DATA_AXIS])
                for ls in listeners:
                    ls.iteration_done(self, it, float(score))
                it += 1
        _note_skips(skips)
        return params


class ParameterAveragingTrainer:
    """Spark-semantics DP: local k-step training then parameter averaging."""

    def __init__(self, loss_fn: LossFn, updater: Dl4jUpdater, mesh: Mesh,
                 local_steps: int = 1, average_each_round: bool = True):
        self.loss_fn = loss_fn
        self.updater = updater
        self.mesh = mesh
        self.local_steps = local_steps
        self.average_each_round = average_each_round

        # Params are carried with an explicit per-shard leading axis
        # [ndp, ...] sharded over `data` — each shard owns its replica
        # (the Spark executors' local nets), letting replicas DIVERGE
        # between averages when average_each_round=False.
        def round_fn(stacked, x, y, key, it0):
            params = jax.tree.map(lambda a: a[0], stacked)  # this shard's copy
            shard_key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
            ustate = self.updater.init(params)

            def local_step(carry, i):
                p, u = carry
                k = jax.random.fold_in(shard_key, i)
                score, grads = jax.value_and_grad(self.loss_fn)(p, x, y, k)
                upd, new_u = self.updater.update(u, grads, p, it0 + i, 1)
                # per-replica guard: this shard's bad batch skips ONLY
                # its local update; the round's param_average then mixes
                # the healthy replicas back in (self-healing averaging)
                new_p, new_u, skipped = resilience.guard_update(
                    p, u, apply_updates(p, upd), new_u, (score, grads))
                return (new_p, new_u), (score, skipped)

            (params, _), (scores, skipped) = lax.scan(
                local_step, (params, ustate), jnp.arange(self.local_steps))
            if self.average_each_round:
                params = collectives.param_average(params, DATA_AXIS)
            score = lax.pmean(scores[-1], DATA_AXIS)
            n_skipped = lax.psum(jnp.sum(skipped), DATA_AXIS)
            return (jax.tree.map(lambda a: a[None], params), score,
                    n_skipped)

        # the stacked [ndp, ...] replicas are the big HBM tenant here and
        # are loop-threaded (born fresh from the broadcast in fit) —
        # donate them so each round updates replicas in place
        self._round = compile_cache.cached_jit(shard_map(
            round_fn, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
            out_specs=(P(DATA_AXIS), P(), P()),
            check_vma=False,
        ), label="parallel.param_avg_round", donate_argnums=(0,))

        def avg(stacked):
            def inner(s):
                p = collectives.param_average(
                    jax.tree.map(lambda a: a[0], s), DATA_AXIS)
                return jax.tree.map(lambda a: a[None], p)
            return shard_map(inner, mesh=mesh, in_specs=(P(DATA_AXIS),),
                             out_specs=P(DATA_AXIS), check_vma=False)(stacked)

        self._final_avg = compile_cache.cached_jit(
            avg, label="parallel.param_avg_final")
        self._ndp = mesh.shape[DATA_AXIS]

    def fit(self, params: PyTree, batches: Iterable[Tuple[Array, Array]],
            key: Array, listeners=()) -> PyTree:
        """Rounds over global batches (repartition ≡ batch iteration).
        Takes and returns UNSTACKED (single-replica) params — the broadcast
        and final collect are internal, like Spark's driver."""
        stacked = jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (self._ndp,) + a.shape),
            params)
        it = 0
        skips = []
        for rnd, (x, y) in enumerate(batches):
            key, sub = jax.random.split(key)
            stacked, score, n_skipped = self._round(
                stacked, x, y, sub, jnp.asarray(it))
            skips.append(n_skipped)
            it += self.local_steps
            for ls in listeners:
                ls.iteration_done(self, rnd, float(score))
        _note_skips(skips)
        stacked = self._final_avg(stacked)
        return jax.tree.map(lambda a: a[0], stacked)
