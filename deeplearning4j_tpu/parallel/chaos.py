"""Fault injection for the scaleout runtime — chaos testing as a
first-class capability.

The reference's fault story is detection/recovery only (heartbeat reaper
``MasterActor.java:139-169``, job re-delivery, worker enable/disable);
SURVEY.md §5.3 notes it ships NO fault *injection* anywhere.  This module
adds it: deterministic, seedable failure wrappers so the recovery paths
(requeue, drop-after-retries, elastic rejoin) are exercised on purpose in
tests and soak runs rather than only when something really breaks.

``ChaosPerformer`` wraps any ``WorkerPerformer`` and injects, per
``perform`` call and independently per worker:
- crashes (raise) with probability ``p_fail``;
- stalls of ``stall_s`` seconds with probability ``p_stall`` (exercises
  the heartbeat/stale-reaper path when stalls exceed the reaper window);
- result corruption (the ``corrupt`` callable rewrites ``job.result``)
  with probability ``p_corrupt`` — the end-to-end exercise for the
  hardened aggregator's non-finite rejection path.

Failures are drawn from a counter-based hash of (seed, worker calls), so
a given seed produces the same fault schedule every run — flaky-test
debugging stays deterministic.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from deeplearning4j_tpu.parallel.coordinator import Job
from deeplearning4j_tpu.parallel import scaleout as so
# DeviceLossError is DEFINED in runtime/resilience.py (the driver that
# catches it cannot import this module — chaos -> scaleout -> resilience
# would cycle) and re-exported here where the injectors that raise it
# live.
from deeplearning4j_tpu.runtime.resilience import DeviceLossError  # noqa: F401


class InjectedFault(RuntimeError):
    """Raised by ChaosPerformer for an injected crash."""


class DeviceLossChaos:
    """Step-boundary device-loss injector for ``ResilientFit``'s
    ``fault_hook``: raises :class:`DeviceLossError` for ``lost_ids``
    the first time the step counter reaches ``at_step`` (exactly once —
    the recovery path re-runs the boundary check after re-meshing, and
    a fault that re-fires forever would starve the resume instead of
    testing it)."""

    def __init__(self, at_step: int, lost_ids):
        self.at_step = at_step
        self.lost_ids = tuple(int(i) for i in lost_ids)
        self.fired = False

    def __call__(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            raise DeviceLossError(
                self.lost_ids,
                f"injected device loss at step {step}: ids "
                f"{sorted(self.lost_ids)}")


class HostLossChaos:
    """Step-boundary HOST-loss injector for ``ResilientFit``'s
    ``fault_hook``: raises :class:`DeviceLossError` for EVERY device of
    one host, exactly once.  The host's devices come from the real
    process topology when the fleet spans processes
    (``device.process_index == host_index``), else from partitioning
    the device list into ``n_hosts`` contiguous blocks — the
    virtual-host proxy that lets a single 8-device CPU process drill
    the "lost a whole host" recovery path (2 hosts x 4 devices).

    In a multi-member drill every member installs the SAME injector
    arguments, so all members raise at the same boundary and the
    cluster's lost-id agreement sees one consistent finding — the
    signal-free stand-in for a real host death (which the heartbeat
    detector covers instead)."""

    def __init__(self, at_step: int, host_index: int,
                 n_hosts: Optional[int] = None, devices=None):
        import jax

        self.at_step = at_step
        self.host_index = host_index
        self.fired = False
        devices = list(devices if devices is not None else jax.devices())
        by_proc = {d.process_index for d in devices}
        if len(by_proc) > 1:
            self.lost_ids = tuple(
                int(d.id) for d in devices
                if d.process_index == host_index)
        else:
            n_hosts = n_hosts or max(len(by_proc), 2)
            per = len(devices) // n_hosts
            if per < 1:
                raise ValueError(
                    f"{len(devices)} device(s) cannot form {n_hosts} "
                    "virtual hosts")
            block = devices[host_index * per:(host_index + 1) * per]
            self.lost_ids = tuple(int(d.id) for d in block)
        if not self.lost_ids:
            raise ValueError(
                f"host {host_index} owns no devices in this fleet")

    def __call__(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            raise DeviceLossError(
                self.lost_ids,
                f"injected loss of host {self.host_index} at step "
                f"{step}: device ids {sorted(self.lost_ids)}")


class PreemptionChaos:
    """Step-boundary preemption drill for ``ResilientFit``'s
    ``fault_hook``: flags the driver's PreemptionGuard at ``at_step`` —
    the signal-free way to exercise the final-snapshot-and-clean-exit
    path in benches and CI gates (the SIGTERM-driven path is tested via
    subprocess)."""

    def __init__(self, at_step: int, guard):
        self.at_step = at_step
        self.guard = guard
        self.fired = False

    def __call__(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            self.guard.request()


def _hash01(seed: int, n: int) -> float:
    """Deterministic uniform [0, 1) from (seed, call index)."""
    h = (seed * 2654435761 + n * 40503) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 2246822519) & 0xFFFFFFFF
    h ^= h >> 13
    return (h & 0xFFFFFF) / float(1 << 24)


class ChaosPerformer(so.WorkerPerformer):
    """Wrap ``inner`` with a deterministic fault schedule."""

    def __init__(self, inner: so.WorkerPerformer, *, p_fail: float = 0.0,
                 p_stall: float = 0.0, stall_s: float = 0.0,
                 p_corrupt: float = 0.0,
                 corrupt: Optional[Callable] = None, seed: int = 0):
        self.inner = inner
        self.p_fail = p_fail
        self.p_stall = p_stall
        self.stall_s = stall_s
        self.p_corrupt = p_corrupt
        self.corrupt = corrupt
        self.seed = seed
        self._calls = 0
        self._lock = threading.Lock()
        #: observability: how many of each fault fired
        self.injected = {"fail": 0, "stall": 0, "corrupt": 0}

    def _next_call(self) -> int:
        with self._lock:
            self._calls += 1
            return self._calls

    def perform(self, job: Job) -> None:
        n = self._next_call()
        u = _hash01(self.seed, n)
        if u < self.p_fail:
            self.injected["fail"] += 1
            raise InjectedFault(
                f"injected crash (call {n}, u={u:.3f} < {self.p_fail})")
        if _hash01(self.seed + 1, n) < self.p_stall:
            self.injected["stall"] += 1
            time.sleep(self.stall_s)
        self.inner.perform(job)
        # p_corrupt gates the hook like the other faults (was a
        # hardcoded 0.5 — corruption fired on half of all calls the
        # moment a hook was supplied, with no way to tune the rate)
        if self.corrupt is not None \
                and _hash01(self.seed + 2, n) < self.p_corrupt:
            self.injected["corrupt"] += 1
            job.result = self.corrupt(job.result)

    def update(self, *args) -> None:
        self.inner.update(*args)


def chaos_factory(inner_factory: Callable[[], so.WorkerPerformer], *,
                  p_fail: float = 0.0, p_stall: float = 0.0,
                  stall_s: float = 0.0, p_corrupt: float = 0.0,
                  corrupt: Optional[Callable] = None, seed: int = 0
                  ) -> Callable[[], so.WorkerPerformer]:
    """Performer factory wrapper for ``DistributedRunner``: each worker
    gets its own ChaosPerformer with a distinct derived seed, so faults
    are spread across workers but stay reproducible.  The returned
    factory records every performer it makes on ``factory.instances`` so
    soak tests can sum the per-worker ``injected`` counters afterwards."""
    counter = {"n": 0}
    lock = threading.Lock()
    instances = []

    def make() -> ChaosPerformer:
        with lock:
            counter["n"] += 1
            worker_seed = seed + 1000 * counter["n"]
        perf = ChaosPerformer(inner_factory(), p_fail=p_fail,
                              p_stall=p_stall, stall_s=stall_s,
                              p_corrupt=p_corrupt, corrupt=corrupt,
                              seed=worker_seed)
        with lock:
            instances.append(perf)
        return perf

    make.instances = instances
    return make
