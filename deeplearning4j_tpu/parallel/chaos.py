"""Fault injection for the scaleout runtime — chaos testing as a
first-class capability.

The reference's fault story is detection/recovery only (heartbeat reaper
``MasterActor.java:139-169``, job re-delivery, worker enable/disable);
SURVEY.md §5.3 notes it ships NO fault *injection* anywhere.  This module
adds it: deterministic, seedable failure wrappers so the recovery paths
(requeue, drop-after-retries, elastic rejoin) are exercised on purpose in
tests and soak runs rather than only when something really breaks.

``ChaosPerformer`` wraps any ``WorkerPerformer`` and injects, per
``perform`` call and independently per worker:
- crashes (raise) with probability ``p_fail``;
- stalls of ``stall_s`` seconds with probability ``p_stall`` (exercises
  the heartbeat/stale-reaper path when stalls exceed the reaper window);
- result corruption (the ``corrupt`` callable rewrites ``job.result``)
  with probability ``p_corrupt`` — the end-to-end exercise for the
  hardened aggregator's non-finite rejection path.

Failures are drawn from a counter-based hash of (seed, worker calls), so
a given seed produces the same fault schedule every run — flaky-test
debugging stays deterministic.

``ServingChaos`` extends the same philosophy to the serving fleet: it
arms one-shot faults against ONE decode replica (a ``ContinuousBatcher``
over a ``DecodeEngine``) — worker-thread death, dispatch poison, stalls,
KV page-pool exhaustion — each fired deterministically at the replica's
next step boundary ON its own worker thread (the engine and its page
allocator are single-driver by contract; chaos must not become the
second driver).  ``tools/serving_chaos_gate.py`` drives it in CI.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from deeplearning4j_tpu.parallel.coordinator import Job
from deeplearning4j_tpu.parallel import scaleout as so
# DeviceLossError is DEFINED in runtime/resilience.py (the driver that
# catches it cannot import this module — chaos -> scaleout -> resilience
# would cycle) and re-exported here where the injectors that raise it
# live.
from deeplearning4j_tpu.runtime.resilience import DeviceLossError  # noqa: F401


class InjectedFault(RuntimeError):
    """Raised by ChaosPerformer for an injected crash."""


class DeviceLossChaos:
    """Step-boundary device-loss injector for ``ResilientFit``'s
    ``fault_hook``: raises :class:`DeviceLossError` for ``lost_ids``
    the first time the step counter reaches ``at_step`` (exactly once —
    the recovery path re-runs the boundary check after re-meshing, and
    a fault that re-fires forever would starve the resume instead of
    testing it)."""

    def __init__(self, at_step: int, lost_ids):
        self.at_step = at_step
        self.lost_ids = tuple(int(i) for i in lost_ids)
        self.fired = False

    def __call__(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            raise DeviceLossError(
                self.lost_ids,
                f"injected device loss at step {step}: ids "
                f"{sorted(self.lost_ids)}")


class HostLossChaos:
    """Step-boundary HOST-loss injector for ``ResilientFit``'s
    ``fault_hook``: raises :class:`DeviceLossError` for EVERY device of
    one host, exactly once.  The host's devices come from the real
    process topology when the fleet spans processes
    (``device.process_index == host_index``), else from partitioning
    the device list into ``n_hosts`` contiguous blocks — the
    virtual-host proxy that lets a single 8-device CPU process drill
    the "lost a whole host" recovery path (2 hosts x 4 devices).

    In a multi-member drill every member installs the SAME injector
    arguments, so all members raise at the same boundary and the
    cluster's lost-id agreement sees one consistent finding — the
    signal-free stand-in for a real host death (which the heartbeat
    detector covers instead)."""

    def __init__(self, at_step: int, host_index: int,
                 n_hosts: Optional[int] = None, devices=None):
        import jax

        self.at_step = at_step
        self.host_index = host_index
        self.fired = False
        devices = list(devices if devices is not None else jax.devices())
        by_proc = {d.process_index for d in devices}
        if len(by_proc) > 1:
            self.lost_ids = tuple(
                int(d.id) for d in devices
                if d.process_index == host_index)
        else:
            n_hosts = n_hosts or max(len(by_proc), 2)
            per = len(devices) // n_hosts
            if per < 1:
                raise ValueError(
                    f"{len(devices)} device(s) cannot form {n_hosts} "
                    "virtual hosts")
            block = devices[host_index * per:(host_index + 1) * per]
            self.lost_ids = tuple(int(d.id) for d in block)
        if not self.lost_ids:
            raise ValueError(
                f"host {host_index} owns no devices in this fleet")

    def __call__(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            raise DeviceLossError(
                self.lost_ids,
                f"injected loss of host {self.host_index} at step "
                f"{step}: device ids {sorted(self.lost_ids)}")


class PreemptionChaos:
    """Step-boundary preemption drill for ``ResilientFit``'s
    ``fault_hook``: flags the driver's PreemptionGuard at ``at_step`` —
    the signal-free way to exercise the final-snapshot-and-clean-exit
    path in benches and CI gates (the SIGTERM-driven path is tested via
    subprocess)."""

    def __init__(self, at_step: int, guard):
        self.at_step = at_step
        self.guard = guard
        self.fired = False

    def __call__(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            self.guard.request()


def _hash01(seed: int, n: int) -> float:
    """Deterministic uniform [0, 1) from (seed, call index)."""
    h = (seed * 2654435761 + n * 40503) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 2246822519) & 0xFFFFFFFF
    h ^= h >> 13
    return (h & 0xFFFFFF) / float(1 << 24)


class ChaosPerformer(so.WorkerPerformer):
    """Wrap ``inner`` with a deterministic fault schedule."""

    def __init__(self, inner: so.WorkerPerformer, *, p_fail: float = 0.0,
                 p_stall: float = 0.0, stall_s: float = 0.0,
                 p_corrupt: float = 0.0,
                 corrupt: Optional[Callable] = None, seed: int = 0):
        self.inner = inner
        self.p_fail = p_fail
        self.p_stall = p_stall
        self.stall_s = stall_s
        self.p_corrupt = p_corrupt
        self.corrupt = corrupt
        self.seed = seed
        self._calls = 0
        self._lock = threading.Lock()
        #: observability: how many of each fault fired
        self.injected = {"fail": 0, "stall": 0, "corrupt": 0}

    def _next_call(self) -> int:
        with self._lock:
            self._calls += 1
            return self._calls

    def perform(self, job: Job) -> None:
        n = self._next_call()
        u = _hash01(self.seed, n)
        if u < self.p_fail:
            self.injected["fail"] += 1
            raise InjectedFault(
                f"injected crash (call {n}, u={u:.3f} < {self.p_fail})")
        if _hash01(self.seed + 1, n) < self.p_stall:
            self.injected["stall"] += 1
            time.sleep(self.stall_s)
        self.inner.perform(job)
        # p_corrupt gates the hook like the other faults (was a
        # hardcoded 0.5 — corruption fired on half of all calls the
        # moment a hook was supplied, with no way to tune the rate)
        if self.corrupt is not None \
                and _hash01(self.seed + 2, n) < self.p_corrupt:
            self.injected["corrupt"] += 1
            job.result = self.corrupt(job.result)

    def update(self, *args) -> None:
        self.inner.update(*args)


class WorkerKilled(BaseException):
    """Injected decode-worker death.  Deliberately a ``BaseException``:
    the batcher's dispatch-failure handler catches ``Exception`` (the
    replay path), and a KILL must sail past it and terminate the worker
    thread exactly like an interpreter-level death would — leaving
    ``worker_alive()`` False and the replica's in-flight requests
    stranded for the health monitor to evacuate."""


_orig_thread_excepthook: Optional[Callable] = None


def _install_kill_excepthook() -> None:
    """Silence ONLY :class:`WorkerKilled` escaping a thread — an
    injected death is the drill's expected outcome, and its traceback
    spew would make every chaos run look like a failing one.  All other
    thread exceptions still reach the previous hook.  Idempotent;
    installed on first ``ServingChaos`` construction."""
    global _orig_thread_excepthook
    if _orig_thread_excepthook is not None:
        return
    _orig_thread_excepthook = threading.excepthook

    def hook(args) -> None:
        if args.exc_type is not WorkerKilled:
            _orig_thread_excepthook(args)

    threading.excepthook = hook


class ServingChaos:
    """Deterministic fault injection for ONE serving replica.

    Every injector ARMS a fault rather than performing it: the fault
    fires at the replica's next touch of an engine step-boundary entry
    point (``advance`` / ``advance_spec``, plus ``can_admit`` for the
    faults that are legal under the batcher's condition variable), so
    the mutation happens on the replica's OWN worker thread — the
    engine and its ``PageAllocator`` are single-driver by contract, and
    chaos must not become a second driver racing it.

    - :meth:`kill_worker`: next step raises :class:`WorkerKilled`
      (a BaseException — escapes the replay handler, thread dies);
    - :meth:`poison_dispatch`: next ``n`` decode dispatches raise
      :class:`InjectedFault` — exercises the donated-state poison reset
      and bit-exact request replay;
    - :meth:`stall_dispatch`: next decode dispatch sleeps first — trips
      the monitor's ``progress_age`` stall detector while the zombie
      worker later wakes into detached request handles;
    - :meth:`exhaust_pages` / :meth:`release_pages`: grab (then return)
      the replica's free KV pages — admissions stall, then shed with
      the typed ``KVPagesExhausted``.

    ``injected`` counts what actually fired; :meth:`restore` disarms
    anything still pending (a dead worker never fires armed faults).
    """

    #: entry points legal for faults that may fire under the batcher's
    #: condition variable (can_admit is called inside the admit scan)
    _ANY = ("advance", "advance_spec", "can_admit")
    #: entry points for faults that must fire OUTSIDE every lock
    #: (sleeps) or that only make sense for a decode dispatch (poison)
    _DISPATCH = ("advance", "advance_spec")

    def __init__(self, batcher) -> None:
        self.batcher = batcher
        self.engine = batcher.engine
        self.injected = {"kill": 0, "poison": 0, "stall": 0,
                         "exhaust": 0, "release": 0}
        self._held_pages: list = []
        # RLock: page-bookkeeping hooks fire INSIDE the lock region
        # (atomic with the fire decision) yet keep their own ``with``
        self._lock = threading.RLock()
        self._restores: list = []
        self._exhaust_restores: list = []
        _install_kill_excepthook()

    # -- arming machinery --------------------------------------------------
    def _arm(self, hook: Callable, methods, times: int = 1, *,
             locked_hook: bool = False) -> Callable:
        """Wrap ``methods`` on the engine so the next ``times`` calls
        (across all of them) run ``hook(name)`` first — on the calling
        (worker) thread — then restore the originals and delegate.  A
        raising hook still restores first: an injected fault must fire
        its scheduled count, never forever.  Returns the disarm
        closure (idempotent; a no-op once the fault has fired).

        Every setattr — install, fire-restore, disarm — happens under
        ``self._lock``: arming runs on the host thread while faults
        fire on the worker thread, and an unsynchronized disarm racing
        a fire could resurrect a wrapper that was already retired.
        ``locked_hook=True`` additionally runs the hook inside the
        lock region, making the fire ATOMIC with the fire decision —
        required for page bookkeeping, where a disarm racing a
        half-fired grab would mis-read what is held.  Blocking hooks
        (sleeps) must keep the default and fire outside the lock."""
        eng = self.engine
        state = {"left": int(times)}
        with self._lock:
            origs = {m: getattr(eng, m) for m in methods}

        def restore() -> None:
            with self._lock:
                if state["left"] == 0:
                    return
                state["left"] = 0
                for m, o in origs.items():
                    setattr(eng, m, o)

        def make(name: str, orig: Callable) -> Callable:
            def wrapped(*a, **kw):
                with self._lock:
                    fire = state["left"] > 0
                    if fire:
                        state["left"] -= 1
                        if state["left"] == 0:
                            for m, o in origs.items():
                                setattr(eng, m, o)
                        if locked_hook:
                            hook(name)
                if fire and not locked_hook:
                    hook(name)
                return orig(*a, **kw)
            return wrapped

        with self._lock:
            for m, o in origs.items():
                setattr(eng, m, make(m, o))
        self._restores.append(restore)
        return restore

    def restore(self) -> None:
        """Disarm every armed-but-unfired fault (fired ones already
        restored themselves) and return any held pages.  Call only when
        the replica's worker is dead or quiescent — see
        :meth:`release_pages` for the held-page caveat."""
        for r in self._restores:
            r()
        self._restores = []
        self.release_pages(armed=False)

    # -- injectors ---------------------------------------------------------
    def kill_worker(self) -> None:
        """Arm a one-shot :class:`WorkerKilled` on the replica's next
        step boundary."""
        def hook(name: str) -> None:
            self.injected["kill"] += 1
            raise WorkerKilled(f"injected worker death (at {name})")
        self._arm(hook, self._ANY)

    def poison_dispatch(self, n: int = 1) -> None:
        """Arm :class:`InjectedFault` on the next ``n`` decode
        dispatches (an ordinary RuntimeError — the batcher's replay
        handler owns it)."""
        if n < 1:
            raise ValueError(f"poison count must be >= 1: {n}")

        def hook(name: str) -> None:
            self.injected["poison"] += 1
            raise InjectedFault(f"injected dispatch poison (at {name})")
        self._arm(hook, self._DISPATCH, times=n)

    def stall_dispatch(self, seconds: float) -> None:
        """Arm a one-shot pre-dispatch sleep — long enough and the
        health monitor's ``progress_age`` detector replaces the
        replica while this worker is still inside the sleep."""
        if seconds <= 0:
            raise ValueError(f"stall must be > 0 s: {seconds}")

        def hook(name: str) -> None:
            self.injected["stall"] += 1
            time.sleep(seconds)
        self._arm(hook, self._DISPATCH)

    def exhaust_pages(self, leave: int = 0) -> None:
        """Arm a one-shot grab of the replica's free KV pages (leaving
        ``leave``), held by this injector: admissions stall, then shed
        with the typed ``KVPagesExhausted``.  Paged engines only."""
        if self.engine._alloc is None:
            raise ValueError("exhaust_pages requires a paged engine")
        if leave < 0:
            raise ValueError(f"leave must be >= 0: {leave}")

        def hook(name: str) -> None:
            alloc = self.engine._alloc
            n = max(alloc.n_free() - int(leave), 0)
            if n:
                with self._lock:
                    self._held_pages.extend(alloc.alloc(n))
            self.injected["exhaust"] += 1
        self._exhaust_restores.append(
            self._arm(hook, self._ANY, locked_hook=True))

    def release_pages(self, armed: bool = True) -> None:
        """End the exhaustion episode and return every held page.

        A still-ARMED (unfired) exhaust is disarmed first: without
        this, a release racing a slow-to-wake worker would free
        nothing, then the pending grab would fire AFTER it and hold
        the pool forever.  ``armed=True`` (default) frees on the
        worker thread at the replica's next step boundary — the
        allocator's single-driver contract.  ``armed=False`` frees
        from the calling thread immediately; legal only when the
        worker is dead or parked (e.g. auditing occupancy after a
        drill)."""
        for r in self._exhaust_restores:
            r()
        self._exhaust_restores = []

        def hook(name: str) -> None:
            with self._lock:
                held, self._held_pages = self._held_pages, []
            alloc = self.engine._alloc
            if alloc is not None and held:
                alloc.free(held)
                self.injected["release"] += 1
        with self._lock:
            holding = bool(self._held_pages)
        if not holding:
            return                       # the grab never fired: no-op
        if armed:
            self._arm(hook, self._ANY, locked_hook=True)
        else:
            hook("direct")


def chaos_factory(inner_factory: Callable[[], so.WorkerPerformer], *,
                  p_fail: float = 0.0, p_stall: float = 0.0,
                  stall_s: float = 0.0, p_corrupt: float = 0.0,
                  corrupt: Optional[Callable] = None, seed: int = 0
                  ) -> Callable[[], so.WorkerPerformer]:
    """Performer factory wrapper for ``DistributedRunner``: each worker
    gets its own ChaosPerformer with a distinct derived seed, so faults
    are spread across workers but stay reproducible.  The returned
    factory records every performer it makes on ``factory.instances`` so
    soak tests can sum the per-worker ``injected`` counters afterwards."""
    counter = {"n": 0}
    lock = threading.Lock()
    instances = []

    def make() -> ChaosPerformer:
        with lock:
            counter["n"] += 1
            worker_seed = seed + 1000 * counter["n"]
        perf = ChaosPerformer(inner_factory(), p_fail=p_fail,
                              p_stall=p_stall, stall_s=stall_s,
                              p_corrupt=p_corrupt, corrupt=corrupt,
                              seed=worker_seed)
        with lock:
            instances.append(perf)
        return perf

    make.instances = instances
    return make
