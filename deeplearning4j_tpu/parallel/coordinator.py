"""Control-plane coordinator — ``StateTracker`` parity.

The reference's distributed control plane is a Hazelcast-backed parameter
server (api/statetracker/StateTracker.java:43): job assignment, worker
registry + heartbeats, current global params, update collection, counters,
enable/disable switches, plus a stale-worker reaper in the master
(MasterActor.java:139-169).

In the TPU-native design the DATA plane is XLA collectives, so this
coordinator is deliberately thin host-side state: it orchestrates workers
(threads driving device slices, or host processes over DCN), routes jobs,
tracks heartbeats, and buffers async updates for the Hogwild path.  The
same API works in-process (threading — like the reference's in-JVM
BaseTestDistributed pattern) and could be served over RPC without changing
callers.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)


@dataclasses.dataclass
class Job:
    """Unit of distributable work (scaleout/job/Job.java:24 parity).
    ``retries`` counts requeues after worker failure/death."""
    work: Any
    worker_id: str = ""
    result: Any = None
    retries: int = 0


@dataclasses.dataclass
class WorkerRecord:
    worker_id: str
    last_heartbeat: float
    enabled: bool = True


class StateTracker:
    """In-process StateTracker: thread-safe job/worker/update bookkeeping.

    API parity (StateTracker.java): add_update:223/updates:229,
    set_current:88/get_current:95, job_for/clear_job, heartbeats,
    worker_enabled:182, increment/count:52-54.
    """

    def __init__(self, stale_after_s: float = 120.0,
                 max_job_retries: int = 5):
        self._lock = threading.RLock()
        self._workers: Dict[str, WorkerRecord] = {}
        self._jobs: Dict[str, Job] = {}
        self._pending: List[Job] = []
        self._updates: List[Job] = []
        self._current: Any = None
        self._counters: Dict[str, int] = {}
        self._needs_replicate: Dict[str, bool] = {}
        self._done = False
        self.stale_after_s = stale_after_s
        self.max_job_retries = max_job_retries

    # -- run lifecycle (ShutdownMessage parity) -----------------------------
    def set_done(self, done: bool = True) -> None:
        """Master broadcasts end-of-run; polling workers exit their loop
        (the reference's ShutdownMessage / FinishMessage)."""
        with self._lock:
            self._done = done

    def is_done(self) -> bool:
        with self._lock:
            return self._done

    # -- worker registry + heartbeats --------------------------------------
    def add_worker(self, worker_id: str) -> None:
        with self._lock:
            self._workers[worker_id] = WorkerRecord(worker_id, time.time())
            self._needs_replicate[worker_id] = True

    def heartbeat(self, worker_id: str) -> bool:
        """Record liveness.  Returns False for an unknown worker (e.g.
        one the reaper removed) so the caller can re-register — the Akka
        cluster-membership re-join (WorkerActor.preStart:280-283)."""
        with self._lock:
            if worker_id in self._workers:
                self._workers[worker_id].last_heartbeat = time.time()
                return True
            return False

    def heartbeats(self) -> Dict[str, float]:
        with self._lock:
            return {w: r.last_heartbeat for w, r in self._workers.items()}

    def workers(self) -> List[str]:
        with self._lock:
            return list(self._workers)

    def remove_stale_workers(self) -> List[str]:
        """MasterActor reaper parity (stale >= stale_after_s; :139-169):
        drops the worker and re-queues its in-flight job."""
        now = time.time()
        removed = []
        with self._lock:
            for wid, rec in list(self._workers.items()):
                if now - rec.last_heartbeat >= self.stale_after_s:
                    removed.append(wid)
                    del self._workers[wid]
                    self._needs_replicate.pop(wid, None)
                    self._requeue_locked(wid)
        return removed

    def worker_enabled(self, worker_id: str) -> bool:
        with self._lock:
            rec = self._workers.get(worker_id)
            return bool(rec and rec.enabled)

    def enable_worker(self, worker_id: str, enabled: bool = True) -> None:
        with self._lock:
            if worker_id in self._workers:
                self._workers[worker_id].enabled = enabled

    # -- job routing --------------------------------------------------------
    def add_job(self, job: Job) -> None:
        with self._lock:
            self._pending.append(job)

    def job_for(self, worker_id: str) -> Optional[Job]:
        """Assign (or return the already-assigned) job for a worker —
        pull-based like WorkerActor.checkJobAvailable:287."""
        with self._lock:
            if worker_id in self._jobs:
                return self._jobs[worker_id]
            if not self.worker_enabled(worker_id):
                return None
            if self._pending:
                job = self._pending.pop(0)
                job.worker_id = worker_id
                self._jobs[worker_id] = job
                return job
            return None

    def clear_job(self, worker_id: str) -> None:
        with self._lock:
            self._jobs.pop(worker_id, None)

    def _requeue_locked(self, worker_id: str) -> None:
        """Requeue body; caller must hold the lock.  Resets any partial
        result so the next worker starts the job clean.  A job that keeps
        failing is DROPPED after ``max_job_retries`` requeues (counter
        ``jobs_dropped``) — otherwise one deterministically-failing job
        (bad shard, poisoned input) requeues forever, ``has_pending``
        never clears, and the whole run times out discarding every
        healthy worker's results."""
        job = self._jobs.pop(worker_id, None)
        if job is not None:
            job.worker_id = ""
            job.result = None
            job.retries += 1
            if job.retries > self.max_job_retries:
                self._counters["jobs_dropped"] = (
                    self._counters.get("jobs_dropped", 0) + 1)
                log.warning(
                    "dropping job after %d failed attempts; its work is "
                    "EXCLUDED from the aggregate (check jobs_dropped)",
                    job.retries)
                return
            self._pending.append(job)

    def requeue(self, worker_id: str) -> None:
        """Atomically move a worker's assigned job back to the pending
        queue (JobFailed parity).  Single lock acquisition so a concurrent
        ``has_pending()`` can never observe the job missing from both
        ``_jobs`` and ``_pending`` mid-requeue — which would let the master
        finish the round and drop the failed job's work."""
        with self._lock:
            self._requeue_locked(worker_id)

    def has_pending(self) -> bool:
        with self._lock:
            return bool(self._pending) or bool(self._jobs)

    def pending_counts(self) -> tuple:
        """(queued, in_flight) job counts — debuggability for timeout
        and stall reporting (the master pump's error message)."""
        with self._lock:
            return len(self._pending), len(self._jobs)

    # -- current global state (the "parameter server" role) ----------------
    def set_current(self, value: Any) -> None:
        with self._lock:
            self._current = value
            for w in self._needs_replicate:
                self._needs_replicate[w] = True

    def get_current(self) -> Any:
        with self._lock:
            return self._current

    def needs_replicate(self, worker_id: str) -> bool:
        with self._lock:
            return self._needs_replicate.get(worker_id, True)

    def done_replicating(self, worker_id: str) -> None:
        with self._lock:
            self._needs_replicate[worker_id] = False

    # -- update collection (UpdateSaver/addUpdate parity) -------------------
    def add_update(self, worker_id: str, job: Job) -> None:
        with self._lock:
            self._updates.append(job)

    def complete_job(self, worker_id: str, job: Job) -> bool:
        """Atomically post the result, clear the assignment, and count the
        completion — IF the worker still owns a job.  Closes both
        double-count windows: a worker dying between separate
        add_update/clear_job calls, and a slow-but-alive worker whose job
        the reaper already requeued to a peer (its late result is
        discarded here, since the peer's recompute is the one that
        counts).  Returns False when the update was discarded as stale."""
        with self._lock:
            if worker_id not in self._jobs:
                self._counters["updates_discarded"] = (
                    self._counters.get("updates_discarded", 0) + 1)
                return False
            self._updates.append(job)
            del self._jobs[worker_id]
            self._counters["jobs_done"] = (
                self._counters.get("jobs_done", 0) + 1)
            return True

    def updates(self) -> List[Job]:
        with self._lock:
            return list(self._updates)

    def drain_updates(self) -> List[Job]:
        with self._lock:
            out, self._updates = self._updates, []
            return out

    # -- counters -----------------------------------------------------------
    def increment(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def count(self, key: str) -> int:
        with self._lock:
            return self._counters.get(key, 0)
