"""Control-plane coordinator — ``StateTracker`` parity.

The reference's distributed control plane is a Hazelcast-backed parameter
server (api/statetracker/StateTracker.java:43): job assignment, worker
registry + heartbeats, current global params, update collection, counters,
enable/disable switches, plus a stale-worker reaper in the master
(MasterActor.java:139-169).

In the TPU-native design the DATA plane is XLA collectives, so this
coordinator is deliberately thin host-side state: it orchestrates workers
(threads driving device slices, or host processes over DCN), routes jobs,
tracks heartbeats, and buffers async updates for the Hogwild path.  The
same API works in-process (threading — like the reference's in-JVM
BaseTestDistributed pattern) and could be served over RPC without changing
callers.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Job:
    """Unit of distributable work (scaleout/job/Job.java:24 parity)."""
    work: Any
    worker_id: str = ""
    result: Any = None


@dataclasses.dataclass
class WorkerRecord:
    worker_id: str
    last_heartbeat: float
    enabled: bool = True


class StateTracker:
    """In-process StateTracker: thread-safe job/worker/update bookkeeping.

    API parity (StateTracker.java): add_update:223/updates:229,
    set_current:88/get_current:95, job_for/clear_job, heartbeats,
    worker_enabled:182, increment/count:52-54.
    """

    def __init__(self, stale_after_s: float = 120.0):
        self._lock = threading.RLock()
        self._workers: Dict[str, WorkerRecord] = {}
        self._jobs: Dict[str, Job] = {}
        self._pending: List[Job] = []
        self._updates: List[Job] = []
        self._current: Any = None
        self._counters: Dict[str, int] = {}
        self._needs_replicate: Dict[str, bool] = {}
        self.stale_after_s = stale_after_s

    # -- worker registry + heartbeats --------------------------------------
    def add_worker(self, worker_id: str) -> None:
        with self._lock:
            self._workers[worker_id] = WorkerRecord(worker_id, time.time())
            self._needs_replicate[worker_id] = True

    def heartbeat(self, worker_id: str) -> None:
        with self._lock:
            if worker_id in self._workers:
                self._workers[worker_id].last_heartbeat = time.time()

    def heartbeats(self) -> Dict[str, float]:
        with self._lock:
            return {w: r.last_heartbeat for w, r in self._workers.items()}

    def workers(self) -> List[str]:
        with self._lock:
            return list(self._workers)

    def remove_stale_workers(self) -> List[str]:
        """MasterActor reaper parity (stale >= stale_after_s; :139-169):
        drops the worker and re-queues its in-flight job."""
        now = time.time()
        removed = []
        with self._lock:
            for wid, rec in list(self._workers.items()):
                if now - rec.last_heartbeat >= self.stale_after_s:
                    removed.append(wid)
                    del self._workers[wid]
                    self._needs_replicate.pop(wid, None)
                    self._requeue_locked(wid)
        return removed

    def worker_enabled(self, worker_id: str) -> bool:
        with self._lock:
            rec = self._workers.get(worker_id)
            return bool(rec and rec.enabled)

    def enable_worker(self, worker_id: str, enabled: bool = True) -> None:
        with self._lock:
            if worker_id in self._workers:
                self._workers[worker_id].enabled = enabled

    # -- job routing --------------------------------------------------------
    def add_job(self, job: Job) -> None:
        with self._lock:
            self._pending.append(job)

    def job_for(self, worker_id: str) -> Optional[Job]:
        """Assign (or return the already-assigned) job for a worker —
        pull-based like WorkerActor.checkJobAvailable:287."""
        with self._lock:
            if worker_id in self._jobs:
                return self._jobs[worker_id]
            if not self.worker_enabled(worker_id):
                return None
            if self._pending:
                job = self._pending.pop(0)
                job.worker_id = worker_id
                self._jobs[worker_id] = job
                return job
            return None

    def clear_job(self, worker_id: str) -> None:
        with self._lock:
            self._jobs.pop(worker_id, None)

    def _requeue_locked(self, worker_id: str) -> None:
        """Requeue body; caller must hold the lock.  Resets any partial
        result so the next worker starts the job clean."""
        job = self._jobs.pop(worker_id, None)
        if job is not None:
            job.worker_id = ""
            job.result = None
            self._pending.append(job)

    def requeue(self, worker_id: str) -> None:
        """Atomically move a worker's assigned job back to the pending
        queue (JobFailed parity).  Single lock acquisition so a concurrent
        ``has_pending()`` can never observe the job missing from both
        ``_jobs`` and ``_pending`` mid-requeue — which would let the master
        finish the round and drop the failed job's work."""
        with self._lock:
            self._requeue_locked(worker_id)

    def has_pending(self) -> bool:
        with self._lock:
            return bool(self._pending) or bool(self._jobs)

    # -- current global state (the "parameter server" role) ----------------
    def set_current(self, value: Any) -> None:
        with self._lock:
            self._current = value
            for w in self._needs_replicate:
                self._needs_replicate[w] = True

    def get_current(self) -> Any:
        with self._lock:
            return self._current

    def needs_replicate(self, worker_id: str) -> bool:
        with self._lock:
            return self._needs_replicate.get(worker_id, True)

    def done_replicating(self, worker_id: str) -> None:
        with self._lock:
            self._needs_replicate[worker_id] = False

    # -- update collection (UpdateSaver/addUpdate parity) -------------------
    def add_update(self, worker_id: str, job: Job) -> None:
        with self._lock:
            self._updates.append(job)

    def updates(self) -> List[Job]:
        with self._lock:
            return list(self._updates)

    def drain_updates(self) -> List[Job]:
        with self._lock:
            out, self._updates = self._updates, []
            return out

    # -- counters -----------------------------------------------------------
    def increment(self, key: str, by: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + by

    def count(self, key: str) -> int:
        with self._lock:
            return self._counters.get(key, 0)
