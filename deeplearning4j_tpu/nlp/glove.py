"""GloVe — co-occurrence counting + AdaGrad weighted-least-squares fit.

Reference parity: ``models/glove/Glove.java:57`` (fit:106, parallel
minibatch loop :172-212), ``GloveWeightLookupTable.iterateSample`` (the
f(X) = (X/xMax)^0.75-weighted WLS update with per-row AdaGrad), and
``CoOccurrences.java`` (actor-parallel, disk-buffered counting).

TPU-native redesign:
- co-occurrence counting is a host-side hash accumulation (string work),
  emitted as COO triples (i, j, X_ij);
- training shuffles the triples once per epoch and runs fixed-size batches
  through ONE jitted step: gathers of w/w~/b/b~ rows, the weighted-squared-
  error gradient, AdaGrad accumulator updates, and count-normalized
  scatter-adds (same stability treatment as word2vec).
- the final embedding is w + w~ (standard GloVe practice).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from functools import partial
from typing import Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word_vectors import WordVectors

Array = jax.Array


@dataclasses.dataclass
class GloveConfig:
    vector_size: int = 100
    window: int = 5
    min_word_frequency: int = 1
    alpha: float = 0.05          # AdaGrad master step
    x_max: float = 100.0
    weight_power: float = 0.75
    epochs: int = 5
    batch_size: int = 4096
    symmetric: bool = True
    seed: int = 13


def count_cooccurrences(sentences: Iterable[str], tokenizer,
                        cache: VocabCache, window: int = 5,
                        symmetric: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COO triples (rows, cols, counts); weight 1/d by distance d
    (standard GloVe counting; CoOccurrences.java equivalent)."""
    counts: Dict[Tuple[int, int], float] = defaultdict(float)
    for sent in sentences:
        idx = [cache.index_of(t) for t in tokenizer(sent)]
        idx = [i for i in idx if i >= 0]
        n = len(idx)
        for pos in range(n):
            for off in range(1, window + 1):
                j = pos + off
                if j >= n:
                    break
                w = 1.0 / off
                counts[(idx[pos], idx[j])] += w
                if symmetric:
                    counts[(idx[j], idx[pos])] += w
    if not counts:
        return (np.empty(0, np.int32),) * 2 + (np.empty(0, np.float32),)
    keys = np.asarray(list(counts.keys()), np.int32)
    vals = np.asarray(list(counts.values()), np.float32)
    return keys[:, 0], keys[:, 1], vals


@partial(jax.jit, donate_argnums=(0,))
def _glove_step(state, rows: Array, cols: Array, x: Array, mask: Array,
                alpha: Array, x_max: float, power: float):
    """One batched AdaGrad WLS step on COO triples."""
    w, wt, b, bt, gw, gwt, gb, gbt = state
    wi, wj = w[rows], wt[cols]                        # [B, D]
    diff = (jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bt[cols]
            - jnp.log(jnp.maximum(x, 1e-12)))
    fx = jnp.minimum((x / x_max) ** power, 1.0)
    g = fx * diff * mask                              # [B]

    dwi = g[:, None] * wj
    dwj = g[:, None] * wi
    db = g

    def adagrad_scatter(table, gsq, idx, grad, hit):
        # count-normalized scatter (stability under duplicate rows)
        cnt = jnp.zeros(table.shape[0]).at[idx].add(hit, mode="drop")
        norm = jnp.maximum(cnt, 1.0)[idx]
        if grad.ndim == 2:
            norm = norm[:, None]
        grad = grad / norm
        gsq = gsq.at[idx].add(grad * grad, mode="drop")
        step = alpha * grad / jnp.sqrt(gsq[idx] + 1e-8)
        table = table.at[idx].add(-step, mode="drop")
        return table, gsq

    w, gw = adagrad_scatter(w, gw, rows, dwi, mask)
    wt, gwt = adagrad_scatter(wt, gwt, cols, dwj, mask)
    b, gb = adagrad_scatter(b, gb, rows, db, mask)
    bt, gbt = adagrad_scatter(bt, gbt, cols, db, mask)
    loss = 0.5 * jnp.sum(fx * diff * diff * mask) / jnp.maximum(
        jnp.sum(mask), 1.0)
    return (w, wt, b, bt, gw, gwt, gb, gbt), loss


class Glove:
    def __init__(self, sentences: Iterable[str],
                 config: Optional[GloveConfig] = None,
                 tokenizer=None, cache: Optional[VocabCache] = None):
        self.config = config or GloveConfig()
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.sentences = sentences
        self.cache = cache
        self._wv: Optional[WordVectors] = None
        self.state: Optional[Tuple] = None
        self.losses: list = []

    def fit(self, initial_weights: Optional[Tuple] = None,
            cooccurrences: Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]] = None
            ) -> WordVectors:
        """Train; ``initial_weights`` (an 8-tuple of w/w~/b/b~ tables plus
        their AdaGrad accumulators, as produced in ``self.state``) warm-
        starts from a previous or globally-averaged state — the hook the
        distributed GloVe performer uses (GlovePerformer.java parity).
        ``cooccurrences`` = precomputed (rows, cols, counts) COO triples;
        when given, the counting pass is skipped."""
        cfg = self.config
        if self.cache is None:
            self.cache = build_vocab(self.sentences, self.tokenizer,
                                     cfg.min_word_frequency)
        V, D = len(self.cache), cfg.vector_size
        if V == 0:
            raise ValueError("empty vocabulary")
        if cooccurrences is None:
            cooccurrences = count_cooccurrences(
                self.sentences, self.tokenizer, self.cache, cfg.window,
                cfg.symmetric)
        rows, cols, x = cooccurrences
        if rows.size == 0:
            raise ValueError("no co-occurrences")

        if initial_weights is not None:
            # jnp.array (copy), NOT asarray: _glove_step donates its state
            # argument, so a no-copy view of the caller's arrays would be
            # deleted by donation on the first step, corrupting the state
            # tuple the caller warm-started from
            state = tuple(jnp.array(t) for t in initial_weights)
            if state[0].shape != (V, D):
                raise ValueError(
                    f"initial weights shaped {state[0].shape}, "
                    f"vocab expects {(V, D)}")
        else:
            key = jax.random.key(cfg.seed)
            k1, k2 = jax.random.split(key)
            init = lambda k: (jax.random.uniform(k, (V, D)) - 0.5) / D
            state = (init(k1), init(k2), jnp.zeros(V), jnp.zeros(V),
                     jnp.full((V, D), 1e-8), jnp.full((V, D), 1e-8),
                     jnp.full(V, 1e-8), jnp.full(V, 1e-8))

        B = min(cfg.batch_size, max(64, rows.size))
        rng = np.random.RandomState(cfg.seed)
        alpha = jnp.float32(cfg.alpha)
        for _ in range(cfg.epochs):
            perm = rng.permutation(rows.size)
            r, c, v = rows[perm], cols[perm], x[perm]
            for lo in range(0, r.size, B):
                rb, cb, vb = r[lo:lo + B], c[lo:lo + B], v[lo:lo + B]
                n_real = rb.size
                if n_real < B:
                    pad = B - n_real
                    rb = np.concatenate([rb, np.zeros(pad, np.int32)])
                    cb = np.concatenate([cb, np.zeros(pad, np.int32)])
                    vb = np.concatenate([vb, np.ones(pad, np.float32)])
                m = jnp.asarray(np.arange(B) < n_real, jnp.float32)
                state, loss = _glove_step(
                    state, jnp.asarray(rb), jnp.asarray(cb),
                    jnp.asarray(vb), m, alpha, cfg.x_max, cfg.weight_power)
            self.losses.append(float(loss))
        self.state = state
        w, wt = state[0], state[1]
        self._wv = WordVectors(self.cache, w + wt)
        return self._wv

    @property
    def word_vectors(self) -> WordVectors:
        if self._wv is None:
            raise RuntimeError("call fit() first")
        return self._wv

    def similarity(self, a: str, b: str) -> float:
        return self.word_vectors.similarity(a, b)

    def words_nearest(self, word: str, top_n: int = 10):
        return self.word_vectors.words_nearest(word, top_n)
