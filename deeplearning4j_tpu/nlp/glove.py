"""GloVe — co-occurrence counting + AdaGrad weighted-least-squares fit.

Reference parity: ``models/glove/Glove.java:57`` (fit:106, parallel
minibatch loop :172-212), ``GloveWeightLookupTable.iterateSample`` (the
f(X) = (X/xMax)^0.75-weighted WLS update with per-row AdaGrad), and
``CoOccurrences.java`` (actor-parallel, disk-buffered counting).

TPU-native redesign:
- co-occurrence counting is a host-side hash accumulation (string work),
  emitted as COO triples (i, j, X_ij);
- training runs ONE dispatch per epoch: an on-device shuffle of the
  triples + a ``lax.scan`` over fixed-size chunks, each doing gathers of
  w/w~/b/b~ rows, the weighted-squared-error gradient, AdaGrad accumulator
  updates, and count-normalized scatter-adds (same stability treatment —
  and the same dispatch-latency restructure — as word2vec).
- the final embedding is w + w~ (standard GloVe practice).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from functools import partial
from typing import Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word_vectors import WordVectors

Array = jax.Array


@dataclasses.dataclass
class GloveConfig:
    vector_size: int = 100
    window: int = 5
    min_word_frequency: int = 1
    alpha: float = 0.05          # AdaGrad master step
    x_max: float = 100.0
    weight_power: float = 0.75
    epochs: int = 5
    batch_size: int = 4096
    symmetric: bool = True
    seed: int = 13


def count_cooccurrences(sentences: Iterable[str], tokenizer,
                        cache: VocabCache, window: int = 5,
                        symmetric: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COO triples (rows, cols, counts); weight 1/d by distance d
    (standard GloVe counting; CoOccurrences.java equivalent)."""
    counts: Dict[Tuple[int, int], float] = defaultdict(float)
    for sent in sentences:
        idx = [cache.index_of(t) for t in tokenizer(sent)]
        idx = [i for i in idx if i >= 0]
        n = len(idx)
        for pos in range(n):
            for off in range(1, window + 1):
                j = pos + off
                if j >= n:
                    break
                w = 1.0 / off
                counts[(idx[pos], idx[j])] += w
                if symmetric:
                    counts[(idx[j], idx[pos])] += w
    if not counts:
        return (np.empty(0, np.int32),) * 2 + (np.empty(0, np.float32),)
    keys = np.asarray(list(counts.keys()), np.int32)
    vals = np.asarray(list(counts.values()), np.float32)
    return keys[:, 0], keys[:, 1], vals


def _glove_update(state, rows: Array, cols: Array, x: Array, mask: Array,
                  alpha: Array, x_max: float, power: float):
    """One batched AdaGrad WLS step on COO triples (plain function)."""
    w, wt, b, bt, gw, gwt, gb, gbt = state
    wi, wj = w[rows], wt[cols]                        # [B, D]
    diff = (jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bt[cols]
            - jnp.log(jnp.maximum(x, 1e-12)))
    fx = jnp.minimum((x / x_max) ** power, 1.0)
    g = fx * diff * mask                              # [B]

    dwi = g[:, None] * wj
    dwj = g[:, None] * wi
    db = g

    def adagrad_scatter(table, gsq, idx, grad, hit):
        # count-normalized scatter (stability under duplicate rows)
        cnt = jnp.zeros(table.shape[0]).at[idx].add(hit, mode="drop")
        norm = jnp.maximum(cnt, 1.0)[idx]
        if grad.ndim == 2:
            norm = norm[:, None]
        grad = grad / norm
        gsq = gsq.at[idx].add(grad * grad, mode="drop")
        step = alpha * grad / jnp.sqrt(gsq[idx] + 1e-8)
        table = table.at[idx].add(-step, mode="drop")
        return table, gsq

    w, gw = adagrad_scatter(w, gw, rows, dwi, mask)
    wt, gwt = adagrad_scatter(wt, gwt, cols, dwj, mask)
    b, gb = adagrad_scatter(b, gb, rows, db, mask)
    bt, gbt = adagrad_scatter(bt, gbt, cols, db, mask)
    loss = 0.5 * jnp.sum(fx * diff * diff * mask) / jnp.maximum(
        jnp.sum(mask), 1.0)
    return (w, wt, b, bt, gw, gwt, gb, gbt), loss


@partial(jax.jit, donate_argnums=(0,),
         static_argnames=("x_max", "power", "n_chunks", "batch"))
def _glove_scan_epoch(state, rows: Array, cols: Array, x: Array,
                      mask: Array, key: Array, epoch: Array, alpha: Array,
                      *, x_max: float, power: float, n_chunks: int,
                      batch: int):
    """One dispatch per EPOCH: on-device shuffle of the COO triples
    (Glove.java's per-epoch example shuffle) + ``lax.scan`` over fixed
    [batch] chunks.  The eager per-chunk loop paid one 15-20 ms tunnel
    dispatch per 4k triples; the scan removes that entirely (same
    restructure as word2vec's _scan_slab).  Returns (state, mean loss)."""
    perm = jax.random.permutation(jax.random.fold_in(key, epoch),
                                  rows.shape[0])

    def body(st, i):
        idx = jax.lax.dynamic_slice(perm, (i * batch,), (batch,))
        return _glove_update(st, rows[idx], cols[idx], x[idx], mask[idx],
                             alpha, x_max, power)

    state, losses = jax.lax.scan(body, state, jnp.arange(n_chunks))
    return state, jnp.mean(losses)


class Glove:
    def __init__(self, sentences: Iterable[str],
                 config: Optional[GloveConfig] = None,
                 tokenizer=None, cache: Optional[VocabCache] = None):
        self.config = config or GloveConfig()
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.sentences = sentences
        self.cache = cache
        self._wv: Optional[WordVectors] = None
        self.state: Optional[Tuple] = None
        self.losses: list = []

    def fit(self, initial_weights: Optional[Tuple] = None,
            cooccurrences: Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]] = None
            ) -> WordVectors:
        """Train; ``initial_weights`` (an 8-tuple of w/w~/b/b~ tables plus
        their AdaGrad accumulators, as produced in ``self.state``) warm-
        starts from a previous or globally-averaged state — the hook the
        distributed GloVe performer uses (GlovePerformer.java parity).
        ``cooccurrences`` = precomputed (rows, cols, counts) COO triples;
        when given, the counting pass is skipped."""
        cfg = self.config
        if self.cache is None:
            self.cache = build_vocab(self.sentences, self.tokenizer,
                                     cfg.min_word_frequency)
        V, D = len(self.cache), cfg.vector_size
        if V == 0:
            raise ValueError("empty vocabulary")
        if cooccurrences is None:
            cooccurrences = count_cooccurrences(
                self.sentences, self.tokenizer, self.cache, cfg.window,
                cfg.symmetric)
        rows, cols, x = cooccurrences
        if rows.size == 0:
            raise ValueError("no co-occurrences")

        if initial_weights is not None:
            # jnp.array (copy), NOT asarray: _glove_scan_epoch donates its
            # state argument, so a no-copy view of the caller's arrays
            # would be deleted by donation on the first epoch, corrupting
            # the state tuple the caller warm-started from
            state = tuple(jnp.array(t) for t in initial_weights)
            if state[0].shape != (V, D):
                raise ValueError(
                    f"initial weights shaped {state[0].shape}, "
                    f"vocab expects {(V, D)}")
        else:
            key = jax.random.key(cfg.seed)
            k1, k2 = jax.random.split(key)
            init = lambda k: (jax.random.uniform(k, (V, D)) - 0.5) / D
            state = (init(k1), init(k2), jnp.zeros(V), jnp.zeros(V),
                     jnp.full((V, D), 1e-8), jnp.full((V, D), 1e-8),
                     jnp.full(V, 1e-8), jnp.full(V, 1e-8))

        B = min(cfg.batch_size, max(64, rows.size))
        P = rows.size
        NC = -(-P // B)
        pad = NC * B - P
        if pad:
            rows = np.concatenate([rows, np.zeros(pad, np.int32)])
            cols = np.concatenate([cols, np.zeros(pad, np.int32)])
            x = np.concatenate([x, np.ones(pad, np.float32)])
        rows_d, cols_d = jnp.asarray(rows), jnp.asarray(cols)
        x_d = jnp.asarray(x)
        mask_d = jnp.asarray(np.arange(NC * B) < P, jnp.float32)
        key = jax.random.key(cfg.seed)
        alpha = jnp.float32(cfg.alpha)
        for epoch in range(cfg.epochs):
            state, loss = _glove_scan_epoch(
                state, rows_d, cols_d, x_d, mask_d, key,
                jnp.int32(epoch), alpha, x_max=cfg.x_max,
                power=cfg.weight_power, n_chunks=NC, batch=B)
            self.losses.append(float(loss))
        self.state = state
        w, wt = state[0], state[1]
        self._wv = WordVectors(self.cache, w + wt)
        return self._wv

    @property
    def word_vectors(self) -> WordVectors:
        if self._wv is None:
            raise RuntimeError("call fit() first")
        return self._wv

    def similarity(self, a: str, b: str) -> float:
        return self.word_vectors.similarity(a, b)

    def words_nearest(self, word: str, top_n: int = 10):
        return self.word_vectors.words_nearest(word, top_n)
