"""GloVe — co-occurrence counting + AdaGrad weighted-least-squares fit.

Reference parity: ``models/glove/Glove.java:57`` (fit:106, parallel
minibatch loop :172-212), ``GloveWeightLookupTable.iterateSample`` (the
f(X) = (X/xMax)^0.75-weighted WLS update with per-row AdaGrad), and
``CoOccurrences.java`` (actor-parallel, disk-buffered counting).

TPU-native redesign:
- co-occurrence counting is a host-side hash accumulation (string work),
  emitted as COO triples (i, j, X_ij);
- training runs ONE dispatch per epoch: an on-device shuffle of the
  triples + a ``lax.scan`` over fixed-size chunks, each doing gathers of
  w/w~/b/b~ rows, the weighted-squared-error gradient, AdaGrad accumulator
  updates, and count-normalized scatter-adds (same stability treatment —
  and the same dispatch-latency restructure — as word2vec).
- the final embedding is w + w~ (standard GloVe practice).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word_vectors import WordVectors

Array = jax.Array


@dataclasses.dataclass
class GloveConfig:
    vector_size: int = 100
    window: int = 5
    min_word_frequency: int = 1
    alpha: float = 0.05          # AdaGrad master step
    x_max: float = 100.0
    weight_power: float = 0.75
    epochs: int = 5
    batch_size: int = 4096
    symmetric: bool = True
    seed: int = 13
    #: "auto" uses the VMEM-resident Pallas kernel on TPU when the
    #: tables fit (ops/pallas_glove); "pallas"/"xla" force a path
    #: ("pallas" off-TPU runs through the interpreter — tests)
    kernel: str = "auto"


def count_cooccurrences(sentences: Iterable[str], tokenizer,
                        cache: VocabCache, window: int = 5,
                        symmetric: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COO triples (rows, cols, counts); weight 1/d by distance d
    (standard GloVe counting; CoOccurrences.java equivalent).

    Vectorized: the per-(position, offset) python loop topped out around
    300k tokens/s; here each sentence contributes [n, W] index matrices
    and the (i, j) pairs are merged with one np.unique pass over packed
    i*V+j keys — the same host-throughput treatment as
    ``word2vec.corpus_pairs``."""
    V = max(1, len(cache))
    deltas = np.arange(1, window + 1)
    weights_d = (1.0 / deltas).astype(np.float32)
    merged_k = np.empty(0, np.int64)
    merged_v = np.empty(0, np.float32)
    keys_parts: list = []
    w_parts: list = []
    buffered = 0

    def collapse():
        """Fold the raw pair buffer into the running unique set — peak
        memory stays O(unique pairs + buffer cap), not O(total pairs)."""
        nonlocal merged_k, merged_v, keys_parts, w_parts, buffered
        keys = np.concatenate([merged_k] + keys_parts)
        ws = np.concatenate([merged_v] + w_parts)
        merged_k, inv = np.unique(keys, return_inverse=True)
        merged_v = np.zeros(merged_k.size, np.float32)
        np.add.at(merged_v, inv, ws)
        keys_parts, w_parts, buffered = [], [], 0

    for sent in sentences:
        idx = [cache.index_of(t) for t in tokenizer(sent)]
        idx = np.asarray([i for i in idx if i >= 0], np.int64)
        n = idx.size
        if n < 2:
            continue
        j = np.arange(n)[:, None] + deltas[None, :]          # [n, W]
        valid = j < n
        pi, di = np.nonzero(valid)
        a, b = idx[pi], idx[j[pi, di]]
        keys_parts.append(a * V + b)
        w_parts.append(weights_d[di])
        if symmetric:
            keys_parts.append(b * V + a)
            w_parts.append(weights_d[di])
        buffered += a.size * (2 if symmetric else 1)
        if buffered >= 4_000_000:
            collapse()
    if buffered or keys_parts:
        collapse()
    if merged_k.size == 0:
        return (np.empty(0, np.int32),) * 2 + (np.empty(0, np.float32),)
    return ((merged_k // V).astype(np.int32),
            (merged_k % V).astype(np.int32), merged_v)


def _glove_update(state, rows: Array, cols: Array, x: Array, mask: Array,
                  alpha: Array, x_max: float, power: float):
    """One batched AdaGrad WLS step on COO triples (plain function)."""
    w, wt, b, bt, gw, gwt, gb, gbt = state
    wi, wj = w[rows], wt[cols]                        # [B, D]
    diff = (jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bt[cols]
            - jnp.log(jnp.maximum(x, 1e-12)))
    fx = jnp.minimum((x / x_max) ** power, 1.0)
    g = fx * diff * mask                              # [B]

    dwi = g[:, None] * wj
    dwj = g[:, None] * wi
    db = g

    def adagrad_scatter(table, gsq, idx, grad, hit):
        # count-normalized scatter (stability under duplicate rows)
        cnt = jnp.zeros(table.shape[0]).at[idx].add(hit, mode="drop")
        norm = jnp.maximum(cnt, 1.0)[idx]
        if grad.ndim == 2:
            norm = norm[:, None]
        grad = grad / norm
        gsq = gsq.at[idx].add(grad * grad, mode="drop")
        step = alpha * grad / jnp.sqrt(gsq[idx] + 1e-8)
        table = table.at[idx].add(-step, mode="drop")
        return table, gsq

    w, gw = adagrad_scatter(w, gw, rows, dwi, mask)
    wt, gwt = adagrad_scatter(wt, gwt, cols, dwj, mask)
    b, gb = adagrad_scatter(b, gb, rows, db, mask)
    bt, gbt = adagrad_scatter(bt, gbt, cols, db, mask)
    loss = 0.5 * jnp.sum(fx * diff * diff * mask) / jnp.maximum(
        jnp.sum(mask), 1.0)
    return (w, wt, b, bt, gw, gwt, gb, gbt), loss


def _glove_epoch_body(state, rows: Array, cols: Array, x: Array,
                      mask: Array, key: Array, epoch: Array, alpha: Array,
                      chunk0, *, x_max: float, power: float,
                      n_chunks: int, batch: int, pallas_block: int = 0,
                      pallas_interpret: bool = False):
    """Epoch core shared by the single-device jit and the dp shard_map:
    on-device shuffle of the COO triples (Glove.java's per-epoch example
    shuffle) + ``lax.scan`` over ``n_chunks`` fixed [batch] chunks
    STARTING at chunk ``chunk0`` of the permuted order (a dp shard
    passes its stripe offset; single-device passes 0).  Returns
    (state, (weighted loss sum, count sum)) so callers — or a psum
    across shards — can form the global mean."""
    perm = jax.random.permutation(jax.random.fold_in(key, epoch),
                                  rows.shape[0])

    if pallas_block > 0:
        from deeplearning4j_tpu.ops.pallas_glove import (apply_chunk,
                                                         fused_glove_chunk)
        # carry the EXTENDED layout across the epoch: wext = (w|b|1),
        # wtext = (wt|1|bt), gsq packed (gw|gb)/(gwt|gbt) — built once
        # here and split back once after the scan, not per chunk
        w, wt, b, bt, gw, gwt, gb, gbt = state
        V, D = w.shape
        ones = jnp.ones((V, 1), jnp.float32)
        ext = (jnp.concatenate([w, b[:, None], ones], axis=1),
               jnp.concatenate([wt, ones, bt[:, None]], axis=1),
               jnp.concatenate([gw, gb[:, None]], axis=1),
               jnp.concatenate([gwt, gbt[:, None]], axis=1))

        def body(st, i):
            wext, wtext, gext, gtext = st
            idx = jax.lax.dynamic_slice(perm, ((chunk0 + i) * batch,),
                                        (batch,))
            m = mask[idx]
            accw, accwt, ls = fused_glove_chunk(
                wext, wtext, rows[idx], cols[idx], x[idx], m,
                x_max=x_max, power=power, block=pallas_block,
                interpret=pallas_interpret)
            wb, gext = apply_chunk(wext[:, :D + 1], gext, accw, alpha)
            wtb, gtext = apply_chunk(
                jnp.concatenate([wtext[:, :D], wtext[:, D + 1:]],
                                axis=1), gtext, accwt, alpha)
            wext = jnp.concatenate([wb, ones], axis=1)
            wtext = jnp.concatenate([wtb[:, :D], ones, wtb[:, D:]],
                                    axis=1)
            loss = ls[0, 0] / jnp.maximum(ls[0, 1], 1.0)
            return (wext, wtext, gext, gtext), (loss, ls[0, 1])

        ext, (losses, cnts) = jax.lax.scan(body, ext,
                                           jnp.arange(n_chunks))
        wext, wtext, gext, gtext = ext
        state = (wext[:, :D], wtext[:, :D], wext[:, D], wtext[:, D + 1],
                 gext[:, :D], gtext[:, :D], gext[:, D], gtext[:, D])
    else:
        def body(st, i):
            idx = jax.lax.dynamic_slice(perm, ((chunk0 + i) * batch,),
                                        (batch,))
            m = mask[idx]
            st, loss = _glove_update(st, rows[idx], cols[idx], x[idx],
                                     m, alpha, x_max, power)
            return st, (loss, jnp.sum(m))

        state, (losses, cnts) = jax.lax.scan(body, state,
                                             jnp.arange(n_chunks))
    # weighted sums: chunk counts vary under the shuffle (and whole
    # chunks can be padding when n_chunks is bucketed up)
    return state, (jnp.sum(losses * cnts), jnp.sum(cnts))


@partial(jax.jit, donate_argnums=(0,),
         static_argnames=("x_max", "power", "n_chunks", "batch",
                          "pallas_block", "pallas_interpret"))
def _glove_scan_epoch(state, rows: Array, cols: Array, x: Array,
                      mask: Array, key: Array, epoch: Array, alpha: Array,
                      *, x_max: float, power: float, n_chunks: int,
                      batch: int, pallas_block: int = 0,
                      pallas_interpret: bool = False):
    """One dispatch per EPOCH (single-device path).  The eager per-chunk
    loop paid one 15-20 ms tunnel dispatch per 4k triples; the scan
    removes that entirely (same restructure as word2vec's _scan_slab).
    Returns (state, mean loss)."""
    state, (ls, cs) = _glove_epoch_body(
        state, rows, cols, x, mask, key, epoch, alpha, jnp.int32(0),
        x_max=x_max, power=power, n_chunks=n_chunks, batch=batch,
        pallas_block=pallas_block, pallas_interpret=pallas_interpret)
    return state, ls / jnp.maximum(cs, 1.0)


def make_dp_glove_epoch(mesh, axis: str, n_shards: int, per: int, *,
                        x_max: float, power: float, batch: int,
                        pallas_block: int = 0,
                        pallas_interpret: bool = False,
                        average: bool = True):
    """Data-parallel GloVe epoch over a mesh ``axis``: every shard
    shuffles the SAME replicated COO triples (identical key -> identical
    permutation), trains its contiguous stripe of ``per`` chunks on its
    own table replica, and replicas are parameter-AVERAGED per epoch —
    the same Spark each-iteration-averaging semantics as word2vec's
    ``make_dp_stream_epoch`` (reference role: the spark glove job,
    models/embeddings/glove/Glove.java distributed fit).  AdaGrad
    accumulators average too (they are part of the replicated state).
    Loss is the count-weighted GLOBAL mean via psum.

    ``average=False`` skips the pmean — timing diagnostics only."""
    from deeplearning4j_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    rep = P()

    def shard_fn(state, rows, cols, x, mask, key, epoch, alpha):
        c0 = jax.lax.axis_index(axis) * per
        state, (ls, cs) = _glove_epoch_body(
            state, rows, cols, x, mask, key, epoch, alpha, c0,
            x_max=x_max, power=power, n_chunks=per, batch=batch,
            pallas_block=pallas_block, pallas_interpret=pallas_interpret)
        ls = jax.lax.psum(ls, axis)
        cs = jax.lax.psum(cs, axis)
        if average:
            state = tuple(jax.lax.pmean(t, axis) for t in state)
        return state, ls / jnp.maximum(cs, 1.0)

    f = shard_map(shard_fn, mesh=mesh, in_specs=(rep,) * 8,
                  out_specs=(rep, rep), check_vma=False)
    return jax.jit(f, donate_argnums=(0,))


class Glove:
    def __init__(self, sentences: Iterable[str],
                 config: Optional[GloveConfig] = None,
                 tokenizer=None, cache: Optional[VocabCache] = None):
        self.config = config or GloveConfig()
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.sentences = sentences
        self.cache = cache
        self._wv: Optional[WordVectors] = None
        self.state: Optional[Tuple] = None
        self.losses: list = []

    def fit(self, initial_weights: Optional[Tuple] = None,
            cooccurrences: Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]] = None,
            mesh=None, data_axis: str = "data") -> WordVectors:
        """Train; ``initial_weights`` (an 8-tuple of w/w~/b/b~ tables plus
        their AdaGrad accumulators, as produced in ``self.state``) warm-
        starts from a previous or globally-averaged state — the hook the
        distributed GloVe performer uses (GlovePerformer.java parity).
        ``cooccurrences`` = precomputed (rows, cols, counts) COO triples;
        when given, the counting pass is skipped.

        With ``mesh`` (and >1 devices on ``data_axis``), each device
        trains a stripe of the shuffled triples on its own table replica
        and replicas are parameter-averaged per epoch
        (``make_dp_glove_epoch`` — the spark glove job's role)."""
        cfg = self.config
        if self.cache is None:
            self.cache = build_vocab(self.sentences, self.tokenizer,
                                     cfg.min_word_frequency)
        V, D = len(self.cache), cfg.vector_size
        if V == 0:
            raise ValueError("empty vocabulary")
        if cooccurrences is None:
            cooccurrences = count_cooccurrences(
                self.sentences, self.tokenizer, self.cache, cfg.window,
                cfg.symmetric)
        rows, cols, x = cooccurrences
        if rows.size == 0:
            raise ValueError("no co-occurrences")

        if initial_weights is not None:
            # jnp.array (copy), NOT asarray: _glove_scan_epoch donates its
            # state argument, so a no-copy view of the caller's arrays
            # would be deleted by donation on the first epoch, corrupting
            # the state tuple the caller warm-started from
            state = tuple(jnp.array(t) for t in initial_weights)
            if state[0].shape != (V, D):
                raise ValueError(
                    f"initial weights shaped {state[0].shape}, "
                    f"vocab expects {(V, D)}")
        else:
            key = jax.random.key(cfg.seed)
            k1, k2 = jax.random.split(key)
            init = lambda k: (jax.random.uniform(k, (V, D)) - 0.5) / D
            state = (init(k1), init(k2), jnp.zeros(V), jnp.zeros(V),
                     jnp.full((V, D), 1e-8), jnp.full((V, D), 1e-8),
                     jnp.full(V, 1e-8), jnp.full(V, 1e-8))

        # FIXED batch width + power-of-two chunk counts: the scanned
        # epoch specializes on (n_chunks, batch), and the distributed
        # performers re-fit shards of many different sizes — bucketing
        # bounds the distinct compilations at log2(P) instead of one per
        # shard size.
        B = cfg.batch_size
        P = rows.size
        n_shards = int(mesh.shape[data_axis]) if mesh is not None else 1
        NC = max(1, 1 << (-(-P // B) - 1).bit_length())
        # a dp mesh needs a chunk count divisible by the shard count
        # (word2vec.py's run_stream_training does the same): extra
        # chunks are fully-masked padding the weighted loss ignores
        NC = max(n_shards, -(-NC // n_shards) * n_shards)
        pad = NC * B - P
        if pad:
            rows = np.concatenate([rows, np.zeros(pad, np.int32)])
            cols = np.concatenate([cols, np.zeros(pad, np.int32)])
            x = np.concatenate([x, np.ones(pad, np.float32)])
        rows_d, cols_d = jnp.asarray(rows), jnp.asarray(cols)
        x_d = jnp.asarray(x)
        mask_d = jnp.asarray(np.arange(NC * B) < P, jnp.float32)
        from deeplearning4j_tpu.ops.kernel_select import resolve_kernel
        from deeplearning4j_tpu.ops.pallas_glove import (choose_block,
                                                         probe_compile)
        platform = jax.devices()[0].platform
        pallas_block, pallas_interpret = resolve_kernel(
            cfg.kernel,
            choose_block(V, D, B, interpret=platform != "tpu"),
            f"glove vocab {V} x dim {D} (batch {B})")
        if (pallas_block and not pallas_interpret
                and cfg.kernel == "auto"
                and not probe_compile(pallas_block, V, D)):
            # Mosaic rejected the kernel on this hardware: silently use
            # the XLA path for auto (an explicit kernel="pallas" would
            # have surfaced the compile error instead)
            pallas_block = 0
        #: resolved dispatch for this fit — benches/tools report it so a
        #: round artifact records the Mosaic accept/reject verdict
        from deeplearning4j_tpu.ops.kernel_select import kernel_name
        self.kernel_used = kernel_name(pallas_block, pallas_interpret)
        key = jax.random.key(cfg.seed)
        alpha = jnp.float32(cfg.alpha)
        if n_shards > 1:
            mesh_key = (tuple(d.id for d in mesh.devices.flat),
                        data_axis, n_shards, NC // n_shards, B)
            self._dp_fns = getattr(self, "_dp_fns", {})
            epoch_fn = self._dp_fns.get(mesh_key)
            if epoch_fn is None:
                epoch_fn = make_dp_glove_epoch(
                    mesh, data_axis, n_shards, NC // n_shards,
                    x_max=cfg.x_max, power=cfg.weight_power, batch=B,
                    pallas_block=pallas_block,
                    pallas_interpret=pallas_interpret)
                self._dp_fns[mesh_key] = epoch_fn
            for epoch in range(cfg.epochs):
                state, loss = epoch_fn(state, rows_d, cols_d, x_d,
                                       mask_d, key, jnp.int32(epoch),
                                       alpha)
                self.losses.append(float(loss))
        else:
            for epoch in range(cfg.epochs):
                state, loss = _glove_scan_epoch(
                    state, rows_d, cols_d, x_d, mask_d, key,
                    jnp.int32(epoch), alpha, x_max=cfg.x_max,
                    power=cfg.weight_power, n_chunks=NC, batch=B,
                    pallas_block=pallas_block,
                    pallas_interpret=pallas_interpret)
                self.losses.append(float(loss))
        self.state = state
        w, wt = state[0], state[1]
        self._wv = WordVectors(self.cache, w + wt)
        return self._wv

    @property
    def word_vectors(self) -> WordVectors:
        if self._wv is None:
            raise RuntimeError("call fit() first")
        return self._wv

    def similarity(self, a: str, b: str) -> float:
        return self.word_vectors.similarity(a, b)

    def words_nearest(self, word: str, top_n: int = 10):
        return self.word_vectors.words_nearest(word, top_n)
