"""NLP suite: embeddings (Word2Vec/GloVe/ParagraphVectors), vocab/Huffman,
tokenization SPIs, bag-of-words/TF-IDF vectorizers, similarity queries.

Reference parity: deeplearning4j-nlp (SURVEY.md §2.6), redesigned TPU-first
(batched device kernels instead of per-word BLAS-1; see word2vec.py).
"""

from deeplearning4j_tpu.nlp.text import (  # noqa: F401
    CollectionSentenceIterator, DefaultTokenizerFactory, DocumentIterator,
    FileSentenceIterator, LabelAwareSentenceIterator, LineSentenceIterator,
    NGramTokenizerFactory, SentenceIterator, common_preprocessor,
)
from deeplearning4j_tpu.nlp.vocab import (  # noqa: F401
    VocabCache, VocabWord, build_huffman, build_vocab, encode_hs_tables,
    unigram_table,
)
from deeplearning4j_tpu.nlp.word_vectors import (  # noqa: F401
    WordVectors, load_word_vectors, write_word_vectors,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, Word2VecConfig  # noqa: F401
from deeplearning4j_tpu.nlp.glove import Glove, GloveConfig  # noqa: F401
from deeplearning4j_tpu.nlp.paragraph_vectors import (  # noqa: F401
    ParagraphVectors, ParagraphVectorsConfig,
)
from deeplearning4j_tpu.nlp.vectorizers import (  # noqa: F401
    BagOfWordsVectorizer, InvertedIndex, TfidfVectorizer,
)
