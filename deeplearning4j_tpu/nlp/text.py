"""Text infrastructure: tokenizer / sentence / document SPIs.

Reference parity (SURVEY.md §2.6 "Text infra"):
- ``Tokenizer``/``TokenizerFactory`` (text/tokenization/) — here a factory is
  any callable ``str -> List[str]``; `DefaultTokenizerFactory` mirrors the
  default behavior (whitespace split after punctuation stripping +
  lowercase), `NGramTokenizerFactory` the n-gram variant.
- ``SentenceIterator`` SPI + File/Line/Collection impls and label-aware
  variants (text/sentenceiterator/).
- ``DocumentIterator`` (text/documentiterator/).

UIMA/Lucene engines are external services in the reference; their roles
(PoS-gated tokenization, inverted index) are covered by the pure-Python
tokenizers here and nlp/vectorizers.InvertedIndex.
"""

from __future__ import annotations

import os
import re
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

TokenPreProcess = Callable[[str], str]
Tokenizer = Callable[[str], List[str]]

_PUNCT = re.compile(r"[\.,:;!\?\"'\(\)\[\]\{\}<>]")
_WS = re.compile(r"\s+")

#: word / number / single-punctuation tokenization, shared by the
#: annotator pipeline and the tree parser so both produce the same token
#: stream for the same text
WORD_PUNCT = re.compile(r"[a-zA-Z']+|[0-9]+|[^\sa-zA-Z0-9]")


def word_punct_tokenize(text: str) -> List[str]:
    return WORD_PUNCT.findall(text)


def common_preprocessor(token: str) -> str:
    """CommonPreprocessor parity: lowercase + strip punctuation."""
    return _PUNCT.sub("", token.lower())


class DefaultTokenizerFactory:
    """Whitespace tokenizer with optional per-token preprocessing."""

    def __init__(self, pre: Optional[TokenPreProcess] = common_preprocessor):
        self.pre = pre

    def create(self, text: str) -> List[str]:
        toks = [t for t in _WS.split(text.strip()) if t]
        if self.pre:
            toks = [self.pre(t) for t in toks]
        return [t for t in toks if t]

    __call__ = create


class NGramTokenizerFactory:
    """NGramTokenizerFactory parity: emits n-grams joined by spaces."""

    def __init__(self, n_min: int = 1, n_max: int = 2,
                 pre: Optional[TokenPreProcess] = common_preprocessor):
        self.base = DefaultTokenizerFactory(pre)
        self.n_min, self.n_max = n_min, n_max

    def create(self, text: str) -> List[str]:
        toks = self.base.create(text)
        out: List[str] = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(toks) - n + 1):
                out.append(" ".join(toks[i:i + n]))
        return out

    __call__ = create


# -- sentence iterators -----------------------------------------------------

class SentenceIterator:
    """SPI: iterate sentences (strings), resettable; optional preprocessor."""

    def __init__(self, pre: Optional[Callable[[str], str]] = None):
        self.pre = pre

    def _sentences(self) -> Iterator[str]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        for s in self._sentences():
            yield self.pre(s) if self.pre else s

    def reset(self) -> None:  # stateless impls: nothing to do
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str], pre=None):
        super().__init__(pre)
        self.sentences = list(sentences)

    def _sentences(self):
        return iter(self.sentences)


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file."""

    def __init__(self, path: str, pre=None):
        super().__init__(pre)
        self.path = path

    def _sentences(self):
        with open(self.path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All files under a directory, line by line."""

    def __init__(self, root: str, pre=None):
        super().__init__(pre)
        self.root = root

    def _sentences(self):
        for dirpath, _, files in sorted(os.walk(self.root)):
            for name in sorted(files):
                with open(os.path.join(dirpath, name), encoding="utf-8",
                          errors="replace") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield line


class BasicLineIterator(LineSentenceIterator):
    pass


class LabelAwareSentenceIterator(SentenceIterator):
    """Yields sentences while exposing ``current_label`` — the contract
    ParagraphVectors trains against (labelled documents)."""

    def __init__(self, labelled: Sequence[Tuple[str, str]], pre=None):
        """labelled: sequence of (label, sentence)."""
        super().__init__(pre)
        self.labelled = list(labelled)
        self.current_label: Optional[str] = None

    def _sentences(self):
        for label, sent in self.labelled:
            self.current_label = label
            yield sent

    def labels(self) -> List[str]:
        return sorted({l for l, _ in self.labelled})


class DocumentIterator:
    """SPI: iterate whole documents (lists of sentences)."""

    def __init__(self, docs: Sequence[Sequence[str]]):
        self.docs = [list(d) for d in docs]

    def __iter__(self) -> Iterator[List[str]]:
        return iter(self.docs)
