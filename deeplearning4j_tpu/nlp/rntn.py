"""RNTN — Recursive Neural Tensor Network (Socher 2013) over parse trees.

Reference parity: ``models/rntn/RNTN.java:66`` — per-node tensor
composition (``forwardPropagateTree:359``), manual tree backprop
(``backpropDerivativesAndError:574``), AdaGrad updates; trees come from
PTB-style s-expressions (text/corpora/treeparser).

TPU-native design: the reference recurses host-side per node.  Here a tree
compiles ONCE to flat arrays (post-order node list with child indices) and
the whole forward pass is a ``lax.scan`` writing a node-activation buffer —
so arbitrary tree shapes run as one fixed-shape XLA program, trees batch by
padding to max_nodes, and the backward pass is ``jax.grad`` of the scan
(no hand-rolled tree backprop).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array
PyTree = Any


# ---------------------------------------------------------------------------
# trees (Tree.java + treeparser parity, minimal)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Tree:
    label: int
    word: Optional[str] = None            # leaves only
    left: Optional["Tree"] = None
    right: Optional["Tree"] = None

    @property
    def is_leaf(self) -> bool:
        return self.word is not None

    def leaves(self) -> List[str]:
        if self.is_leaf:
            return [self.word]
        return self.left.leaves() + self.right.leaves()

    def size(self) -> int:
        return 1 if self.is_leaf else 1 + self.left.size() + self.right.size()


_TOKEN_RE = re.compile(r"\(|\)|[^\s()]+")


def parse_tree(s: str) -> Tree:
    """PTB-style s-expression: ``(3 (2 nice) (3 movie))`` — label then
    either a word (leaf) or exactly two subtrees."""
    tokens = _TOKEN_RE.findall(s)
    pos = 0

    def parse() -> Tree:
        nonlocal pos
        if tokens[pos] != "(":
            raise ValueError(f"expected '(' at token {pos}")
        pos += 1
        label = int(tokens[pos]); pos += 1
        if tokens[pos] != "(":                       # leaf: (label word)
            word = tokens[pos]; pos += 1
            if tokens[pos] != ")":
                raise ValueError("leaf must close after its word")
            pos += 1
            return Tree(label=label, word=word)
        left = parse()
        right = parse()
        if tokens[pos] != ")":
            raise ValueError("internal node must have exactly 2 children")
        pos += 1
        return Tree(label=label, left=left, right=right)

    t = parse()
    if pos != len(tokens):
        raise ValueError("trailing tokens after tree")
    return t


def compile_tree(tree: Tree, vocab: Dict[str, int], max_nodes: int
                 ) -> Dict[str, np.ndarray]:
    """Post-order flattening: children always precede parents, so a single
    forward scan over node indices sees resolved child activations."""
    n = tree.size()
    if n > max_nodes:
        raise ValueError(f"tree has {n} nodes > max_nodes={max_nodes}")
    word = np.zeros(max_nodes, np.int32)
    left = np.zeros(max_nodes, np.int32)
    right = np.zeros(max_nodes, np.int32)
    is_leaf = np.zeros(max_nodes, np.float32)
    label = np.zeros(max_nodes, np.int32)
    mask = np.zeros(max_nodes, np.float32)
    idx = 0

    def walk(t: Tree) -> int:
        nonlocal idx
        if t.is_leaf:
            me = idx; idx += 1
            word[me] = vocab.get(t.word, 0)
            is_leaf[me] = 1.0
        else:
            l = walk(t.left)
            r = walk(t.right)
            me = idx; idx += 1
            left[me], right[me] = l, r
        label[me] = t.label
        mask[me] = 1.0
        return me

    walk(tree)
    return {"word": word, "left": left, "right": right, "is_leaf": is_leaf,
            "label": label, "mask": mask}


def build_vocab(trees: Sequence[Tree]) -> Dict[str, int]:
    vocab: Dict[str, int] = {"<UNK>": 0}
    for t in trees:
        for w in t.leaves():
            vocab.setdefault(w, len(vocab))
    return vocab


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RNTNConfig:
    vocab_size: int = 1000
    dim: int = 25                 # reference default numHidden=25
    n_classes: int = 5            # sentiment treebank granularity
    max_nodes: int = 64
    adagrad_lr: float = 0.01      # reference trains with AdaGrad
    reg: float = 1e-4


def init_params(key: Array, cfg: RNTNConfig) -> PyTree:
    d, k = cfg.dim, cfg.n_classes
    ke, kw, kv, ku = jax.random.split(key, 4)
    return {
        "embed": jax.random.normal(ke, (cfg.vocab_size, d)) * 0.1,
        "W": jax.random.normal(kw, (2 * d, d)) * (1.0 / np.sqrt(2 * d)),
        "b": jnp.zeros((d,)),
        # the tensor: output dim k gets cᵀ V[k] c
        "V": jax.random.normal(kv, (d, 2 * d, 2 * d)) * (1.0 / (2 * d)),
        "U": jax.random.normal(ku, (d, k)) * (1.0 / np.sqrt(d)),
        "bc": jnp.zeros((k,)),
    }


def _compose(params: PyTree, hl: Array, hr: Array) -> Array:
    """tanh(Wc + b + cᵀVc) — the tensor composition (RNTN.java:359)."""
    c = jnp.concatenate([hl, hr])                        # [2d]
    linear = c @ params["W"] + params["b"]               # [d]
    tensor = jnp.einsum("i,kij,j->k", c, params["V"], c)
    return jnp.tanh(linear + tensor)


def forward_tree(params: PyTree, tree_arrays: Dict[str, Array]) -> Array:
    """Node activations H [max_nodes, d] via one scan (children precede
    parents in the post-order layout, so H is resolved when read)."""
    d = params["b"].shape[0]
    max_nodes = tree_arrays["word"].shape[0]
    H0 = jnp.zeros((max_nodes, d))

    def step(H, inputs):
        i, word, l, r, leaf = inputs
        h_leaf = params["embed"][word]
        h_int = _compose(params, H[l], H[r])
        h = leaf * h_leaf + (1.0 - leaf) * h_int
        return H.at[i].set(h), None

    idxs = jnp.arange(max_nodes)
    H, _ = lax.scan(step, H0, (idxs, tree_arrays["word"],
                               tree_arrays["left"], tree_arrays["right"],
                               tree_arrays["is_leaf"]))
    return H


def tree_loss(params: PyTree, tree_arrays: Dict[str, Array],
              cfg: RNTNConfig) -> Array:
    """Summed per-node softmax cross-entropy (every node is labeled —
    RNTN trains sentiment at all constituents), masked over padding."""
    H = forward_tree(params, tree_arrays)
    logits = H @ params["U"] + params["bc"]              # [N, K]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, tree_arrays["label"][:, None],
                             axis=-1)[:, 0]
    mask = tree_arrays["mask"]
    return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def batch_loss(params: PyTree, batch: Dict[str, Array],
               cfg: RNTNConfig) -> Array:
    per_tree = jax.vmap(lambda t: tree_loss(params, t, cfg))(batch)
    reg = sum(jnp.sum(p ** 2) for name, p in params.items()
              if name in ("W", "V", "U"))
    return jnp.mean(per_tree) + cfg.reg * reg


def predict_root(params: PyTree, tree_arrays: Dict[str, Array]) -> Array:
    """Root sentiment: the root is the LAST real node in post-order."""
    H = forward_tree(params, tree_arrays)
    root = jnp.sum(tree_arrays["mask"]).astype(jnp.int32) - 1
    logits = H[root] @ params["U"] + params["bc"]
    return jnp.argmax(logits)


class RNTN:
    """Trainer facade (RNTN.java API shape): fit(trees), predict(tree)."""

    def __init__(self, cfg: Optional[RNTNConfig] = None,
                 trees: Optional[Sequence[Tree]] = None, seed: int = 0):
        trees = list(trees or [])
        self.vocab = build_vocab(trees) if trees else {"<UNK>": 0}
        self.cfg = cfg or RNTNConfig(vocab_size=max(len(self.vocab), 2))
        if self.cfg.vocab_size < len(self.vocab):
            raise ValueError("vocab_size smaller than actual vocabulary")
        self.trees = trees
        self.params = init_params(jax.random.key(seed), self.cfg)
        self._accum = jax.tree.map(jnp.zeros_like, self.params)  # AdaGrad

        cfg_ = self.cfg

        @jax.jit
        def step(params, accum, batch):
            loss, grads = jax.value_and_grad(batch_loss)(params, batch, cfg_)
            accum = jax.tree.map(lambda a, g: a + g * g, accum, grads)
            params = jax.tree.map(
                lambda p, g, a: p - cfg_.adagrad_lr * g /
                (jnp.sqrt(a) + 1e-8),
                params, grads, accum)
            return params, accum, loss

        self._step = step

    def _batch_arrays(self, trees: Sequence[Tree]) -> Dict[str, Array]:
        if not trees:
            raise ValueError("no training trees provided")
        compiled = [compile_tree(t, self.vocab, self.cfg.max_nodes)
                    for t in trees]
        return {k: jnp.asarray(np.stack([c[k] for c in compiled]))
                for k in compiled[0]}

    def fit(self, epochs: int = 30,
            trees: Optional[Sequence[Tree]] = None) -> List[float]:
        batch = self._batch_arrays(trees or self.trees)
        losses = []
        for _ in range(epochs):
            self.params, self._accum, loss = self._step(
                self.params, self._accum, batch)
            losses.append(float(loss))
        return losses

    def predict(self, tree: Tree) -> int:
        arrays = {k: jnp.asarray(v) for k, v in
                  compile_tree(tree, self.vocab, self.cfg.max_nodes).items()}
        return int(predict_root(self.params, arrays))


# ---------------------------------------------------------------------------
# evaluation (RNTNEval.java parity)
# ---------------------------------------------------------------------------

def predict_nodes(params: PyTree, tree_arrays: Dict[str, Array]) -> Array:
    """Per-node argmax sentiment labels [max_nodes] (padding included;
    filter with mask/is_leaf on the host side)."""
    H = forward_tree(params, tree_arrays)
    logits = H @ params["U"] + params["bc"]
    return jnp.argmax(logits, axis=-1)


class RNTNEval:
    """Per-node sentiment evaluation over labeled trees.

    Reference parity: ``models/rntn/RNTNEval.java`` — walks each evaluated
    tree and adds (gold label, argmax prediction) for every NON-LEAF node
    to a confusion matrix; ``stats()`` prints the non-zero confusion
    cells.  Here the whole batch of trees is evaluated in one vmapped
    device program (scan forward + argmax) instead of per-node host
    recursion, and per-ROOT accuracy is tracked too (the headline
    sentiment-treebank metric the reference never reports).
    """

    def __init__(self, n_classes: Optional[int] = None):
        self._n = n_classes
        self._node_counts: Optional[np.ndarray] = None   # [K, K] gold x pred
        self._root_counts: Optional[np.ndarray] = None

    def _ensure(self, k: int) -> None:
        if self._node_counts is None:
            k = max(k, self._n or 0)
            self._node_counts = np.zeros((k, k), np.int64)
            self._root_counts = np.zeros((k, k), np.int64)

    def eval(self, rntn: RNTN, trees: Sequence[Tree]) -> None:
        """Accumulate confusion counts for every internal node (and every
        root) of ``trees`` under ``rntn``'s current parameters."""
        if not trees:
            return
        self._ensure(rntn.cfg.n_classes)
        batch = rntn._batch_arrays(trees)
        preds = np.asarray(jax.vmap(
            lambda t: predict_nodes(rntn.params, t))(batch))   # [B, N]
        mask = np.asarray(batch["mask"]) > 0
        internal = mask & (np.asarray(batch["is_leaf"]) == 0)
        gold = np.asarray(batch["label"])
        k = self._node_counts.shape[0]
        np.add.at(self._node_counts, (gold[internal], preds[internal]), 1)
        # root = last real node in post-order
        n_real = mask.sum(axis=1).astype(int)
        rows = np.arange(len(trees))
        roots = n_real - 1
        np.add.at(self._root_counts, (gold[rows, roots], preds[rows, roots]),
                  1)

    @property
    def confusion(self) -> np.ndarray:
        """[gold, pred] counts over internal nodes."""
        if self._node_counts is None:
            raise ValueError("eval() has not been called")
        return self._node_counts

    def accuracy(self) -> float:
        """Per-internal-node accuracy (the metric RNTNEval.java's counts
        support)."""
        c = self.confusion
        total = c.sum()
        return float(np.trace(c) / total) if total else 0.0

    def root_accuracy(self) -> float:
        c = self._root_counts
        if c is None:
            raise ValueError("eval() has not been called")
        total = c.sum()
        return float(np.trace(c) / total) if total else 0.0

    def stats(self) -> str:
        """Reference-format summary (non-zero confusion cells) plus the
        accuracy lines."""
        lines = []
        c = self.confusion
        for g in range(c.shape[0]):
            for p in range(c.shape[1]):
                if c[g, p]:
                    lines.append(f"Actual Class {g} was predicted with "
                                 f"Predicted {p} with count {c[g, p]} times")
        lines.append(f"Node accuracy: {self.accuracy():.4f}")
        lines.append(f"Root accuracy: {self.root_accuracy():.4f}")
        return "\n".join(lines)
