"""Distributed NLP jobs over the scaleout runner.

Reference parity: the Akka-runtime word2vec workload
(``scaleout/perform/models/word2vec/{Word2VecPerformer,Word2VecWork,
Word2VecResult,Word2VecJobAggregator}.java`` — per-job word-vector tables
shipped, trained on a sentence shard, averaged back), exercised end-to-end
by ``DistributedWord2VecTest``.  The same pattern serves GloVe.

The vocab is built ONCE up front (the reference's VocabActor phase) and
shared by every performer; each job is a sentence shard; the aggregator
parameter-averages the (syn0, syn1, syn1neg) tables.
"""

from __future__ import annotations

import logging
from typing import Iterable, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

import jax
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_vocab
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, Word2VecConfig
from deeplearning4j_tpu.nlp.word_vectors import WordVectors
from deeplearning4j_tpu.parallel import scaleout as so
from deeplearning4j_tpu.parallel.coordinator import Job


def shard_sentences(sentences: Sequence[str], n_shards: int
                    ) -> List[List[str]]:
    """Round-robin the corpus into at most ``n_shards`` non-empty shards
    (the BatchActor partitioning step, shared by every distributed NLP
    job)."""
    shards: List[List[str]] = [[] for _ in range(n_shards)]
    for i, s in enumerate(sentences):
        shards[i % n_shards].append(s)
    return [s for s in shards if s]


class Word2VecPerformer(so.WorkerPerformer):
    """Trains the shared-vocab model on a job's sentence shard, starting
    from the current global tables; ships the trained tables back."""

    def __init__(self, cache: VocabCache, config: Word2VecConfig,
                 tokenizer=None):
        self.cache = cache
        self.config = config
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self._current: Optional[Tuple] = None

    def perform(self, job: Job) -> None:
        w2v = Word2Vec(job.work, self.config, self.tokenizer,
                       cache=self.cache)
        w2v.fit(initial_weights=self._current)
        job.result = (np.asarray(w2v.syn0), np.asarray(w2v.syn1),
                      None if w2v.syn1neg is None
                      else np.asarray(w2v.syn1neg))

    def update(self, current) -> None:
        self._current = current


class Word2VecJobAggregator(so.JobAggregator):
    """Running average of the weight-table tuples
    (Word2VecJobAggregator.java parity)."""

    def __init__(self):
        self._sum = None
        self._n = 0

    def accumulate(self, job: Job) -> None:
        if job.result is None:
            return
        self._n += 1
        if self._sum is None:
            self._sum = [None if t is None else t.copy()
                         for t in job.result]
        else:
            self._sum = [a if b is None else
                         (b.copy() if a is None else a + b)
                         for a, b in zip(self._sum, job.result)]

    def aggregate(self):
        if self._sum is None:
            return None
        return tuple(None if t is None else t / self._n for t in self._sum)

    def reset(self) -> None:
        self._sum = None
        self._n = 0


def train_word2vec_distributed(sentences: Sequence[str],
                               config: Optional[Word2VecConfig] = None,
                               n_workers: int = 2,
                               n_shards: Optional[int] = None,
                               tokenizer=None,
                               timeout_s: float = 300.0) -> WordVectors:
    """DistributedWord2VecTest parity: shard sentences, run the in-process
    runner with Word2Vec performers, return the averaged vectors."""
    import jax.numpy as jnp

    config = config or Word2VecConfig()
    tokenizer = tokenizer or DefaultTokenizerFactory()
    cache = build_vocab(sentences, tokenizer, config.min_word_frequency)

    shards = shard_sentences(sentences, n_shards or n_workers)
    runner = so.DistributedRunner(
        so.CollectionJobIterator(shards),
        lambda: Word2VecPerformer(cache, config, tokenizer),
        Word2VecJobAggregator(), n_workers=n_workers)
    result = runner.run(timeout_s=timeout_s)
    _warn_dropped(runner)
    if result is None:
        raise ValueError("no worker produced trained tables — every shard "
                         "was empty of trainable pairs or every job was "
                         "dropped after repeated failures")
    syn0, syn1, syn1neg = result
    return WordVectors(cache, jnp.asarray(syn0))


def _warn_dropped(runner: "so.DistributedRunner") -> None:
    """Partial results are a quality change, not just a counter: say so."""
    dropped = runner.tracker.count("jobs_dropped")
    if dropped:
        log.warning("%d shard job(s) were dropped after repeated failures; "
                    "the returned vectors exclude that data", dropped)


class WordCountPerformer(so.WorkerPerformer):
    """Distributed word counting (scaleout/perform/text/
    WordCountWorkPerformer.java parity): each job is a sentence (or
    sentence list); the result is its token-count dict."""

    def __init__(self, tokenizer=None):
        self.tokenizer = tokenizer or DefaultTokenizerFactory()

    def perform(self, job: Job) -> None:
        from collections import Counter
        from itertools import chain

        sentences = [job.work] if isinstance(job.work, str) else job.work
        job.result = dict(Counter(
            chain.from_iterable(self.tokenizer(s) for s in sentences)))


class WordCountAggregator(so.JobAggregator):
    """Merge per-shard count dicts (the WordCountTest reduction)."""

    def __init__(self):
        from collections import Counter
        self.total = Counter()

    def accumulate(self, job: Job) -> None:
        self.total.update(job.result or {})

    def aggregate(self):
        return dict(self.total)

    def reset(self) -> None:
        pass                      # counts accumulate across rounds


def word_count_distributed(sentences: Sequence[str], n_workers: int = 2,
                           tokenizer=None, timeout_s: float = 60.0) -> dict:
    """WordCountTest parity: corpus → merged token counts via the runner."""
    runner = so.DistributedRunner(
        so.CollectionJobIterator(list(sentences)),
        lambda: WordCountPerformer(tokenizer),
        WordCountAggregator(), n_workers=n_workers,
        router_cls=so.HogWildWorkRouter)
    counts = runner.run(timeout_s=timeout_s)
    _warn_dropped(runner)
    return counts if counts is not None else {}


class GlovePerformer(so.WorkerPerformer):
    """Distributed GloVe workload (scaleout/perform/models/glove/
    GlovePerformer.java parity): each job is a sentence shard; the
    performer counts the shard's co-occurrences and runs the AdaGrad WLS
    fit starting from the current globally-averaged tables, then ships the
    full (w, w~, b, b~, AdaGrad accumulators) state back."""

    def __init__(self, cache: VocabCache, config: "GloveConfig",
                 tokenizer=None):
        self.cache = cache
        self.config = config
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self._current: Optional[Tuple] = None

    def perform(self, job: Job) -> None:
        from deeplearning4j_tpu.nlp.glove import Glove, count_cooccurrences

        glove = Glove(job.work, self.config, self.tokenizer,
                      cache=self.cache)
        cooc = count_cooccurrences(job.work, self.tokenizer, self.cache,
                                   self.config.window,
                                   self.config.symmetric)
        if cooc[0].size == 0:
            # a shard can legitimately produce no co-occurrences (all its
            # tokens below min frequency / single-token sentences); report
            # an empty result instead of failing — a deterministic raise
            # here would requeue forever and sink the whole run
            job.result = None
            return
        glove.fit(initial_weights=self._current, cooccurrences=cooc)
        job.result = tuple(np.asarray(t) for t in glove.state)

    def update(self, current) -> None:
        self._current = current


class GloveJobAggregator(Word2VecJobAggregator):
    """Running average of the 8-tuple GloVe state (GloveJobAggregator
    .java parity).  The math is the word2vec aggregator's elementwise
    table average — only the tuple arity differs."""


def train_glove_distributed(sentences: Sequence[str],
                            config=None,
                            n_workers: int = 2,
                            n_shards: Optional[int] = None,
                            tokenizer=None,
                            timeout_s: float = 300.0) -> WordVectors:
    """DistributedGloveTest parity: shard sentences, run the runner with
    GloVe performers, return vectors from the averaged tables."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nlp.glove import GloveConfig

    config = config or GloveConfig()
    tokenizer = tokenizer or DefaultTokenizerFactory()
    cache = build_vocab(sentences, tokenizer, config.min_word_frequency)

    shards = shard_sentences(sentences, n_shards or n_workers)
    runner = so.DistributedRunner(
        so.CollectionJobIterator(shards),
        lambda: GlovePerformer(cache, config, tokenizer),
        GloveJobAggregator(), n_workers=n_workers)
    state = runner.run(timeout_s=timeout_s)
    _warn_dropped(runner)
    if state is None:
        raise ValueError("no worker produced trained tables — every shard "
                         "had zero co-occurrences or every job was dropped "
                         "after repeated failures")
    return WordVectors(cache, jnp.asarray(state[0]) + jnp.asarray(state[1]))


class VocabCountPerformer(so.WorkerPerformer):
    """Distributed vocab counting (spark TextPipeline parity:
    dl4j-spark-nlp/.../text/TextPipeline.java:37 — RDD tokenize ->
    per-partition term/doc counts).  Each job is a sentence shard; the
    result is (term_counts, doc_counts, n_docs)."""

    def __init__(self, tokenizer=None):
        self.tokenizer = tokenizer or DefaultTokenizerFactory()

    def perform(self, job: Job) -> None:
        from collections import Counter

        sentences = [job.work] if isinstance(job.work, str) else job.work
        terms: "Counter[str]" = Counter()
        docs: "Counter[str]" = Counter()
        for s in sentences:
            toks = self.tokenizer(s)
            terms.update(toks)
            docs.update(set(toks))
        job.result = (dict(terms), dict(docs), len(sentences))


class VocabCountAggregator(so.JobAggregator):
    """Merge partition counts into one (terms, docs, n_docs) triple —
    TextPipeline's reduceByKey stage."""

    def __init__(self):
        from collections import Counter
        self.terms = Counter()
        self.docs = Counter()
        self.n_docs = 0

    def accumulate(self, job: Job) -> None:
        t, d, n = job.result or ({}, {}, 0)
        self.terms.update(t)
        self.docs.update(d)
        self.n_docs += n

    def aggregate(self):
        return dict(self.terms), dict(self.docs), self.n_docs

    def reset(self) -> None:
        pass                      # counts accumulate across rounds


def build_vocab_distributed(sentences: Sequence[str],
                            min_word_frequency: int = 1,
                            n_workers: int = 2,
                            n_shards: Optional[int] = None,
                            tokenizer=None,
                            timeout_s: float = 60.0) -> VocabCache:
    """TextPipeline parity: the VOCABULARY itself is built from
    distributed counts (the reference's spark pipeline tokenizes and
    counts on executors, then builds the VocabCache from the reduced
    counts), equivalent to the sequential ``build_vocab`` on the same
    corpus."""
    runner = so.DistributedRunner(
        so.CollectionJobIterator(
            shard_sentences(sentences, n_shards or n_workers)),
        lambda: VocabCountPerformer(tokenizer),
        VocabCountAggregator(), n_workers=n_workers,
        router_cls=so.HogWildWorkRouter)
    out = runner.run(timeout_s=timeout_s)
    _warn_dropped(runner)
    if out is None:
        raise ValueError(
            "no worker produced vocabulary counts — every shard job was "
            "dropped after repeated failures")
    terms, docs, n_docs = out
    cache = VocabCache()
    for w, c in terms.items():
        cache.add_token(w, count=float(c))
    for w, c in docs.items():
        cache.doc_freq[w] = int(c)
    cache.num_docs = n_docs
    cache.trim(min_word_frequency)
    return cache
