"""SentiWordNet-style sentiment scoring.

Reference parity: ``text/corpora/sentiwordnet/SWN3.java`` — parses the
SentiWordNet 3.0 tab-separated format (POS, id, PosScore, NegScore,
``word#rank`` synset terms, gloss), folds per-sense polarity
(pos − neg) into one score per ``word#pos`` with 1/rank weighting
normalized by the harmonic sum (SWN3.java:80-118), scores token lists by
summing word polarities with a whole-sentence sign flip when a negation
word occurs (scoreTokens:174-190), and maps scores to the seven
sentiment classes.

Differences from the reference, on purpose:
- ``class_for_score`` uses monotone, non-overlapping buckets; the
  reference's branch chain (SWN3.java:150-164) has overlapping and
  unreachable conditions (e.g. ``score > 0 && score >= 0.25`` labeled
  "weak_positive") that we do not reproduce.
- the bundled lexicon is a small hand-authored file in the same format
  (data/sentiwordnet_mini.txt); pass ``path`` to load the real
  SentiWordNet 3.0 distribution.
"""

from __future__ import annotations

import math
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence

_DEFAULT_LEXICON = os.path.join(os.path.dirname(__file__), "data",
                                "sentiwordnet_mini.txt")

#: SWN3.java:50 negation set (could/would/should/not/…n't)
NEGATION_WORDS = frozenset({
    "could", "would", "should", "not", "no", "never", "isn't", "aren't",
    "wasn't", "weren't", "haven't", "doesn't", "didn't", "don't", "won't",
    "can't", "cannot",
})

_SENT_SPLIT = re.compile(r"(?<=[.!?])\s+")
_TOKEN = re.compile(r"[a-zA-Z']+")


class SentiWordNet:
    """Polarity dictionary + scorer (SWN3 parity)."""

    POS_TAGS = ("a", "n", "v", "r")

    def __init__(self, path: Optional[str] = None,
                 negation_words: Optional[Iterable[str]] = None):
        self.path = path or _DEFAULT_LEXICON
        self.negation_words = frozenset(
            negation_words if negation_words is not None else NEGATION_WORDS)
        self._dict: Dict[str, float] = {}
        self._load(self.path)

    # -- lexicon ------------------------------------------------------------
    def _load(self, path: str) -> None:
        senses: Dict[str, Dict[int, float]] = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                cols = line.split("\t")
                if len(cols) < 5 or not cols[2] or not cols[3]:
                    continue
                pos, _, pos_score, neg_score, terms = cols[:5]
                polarity = float(pos_score) - float(neg_score)
                for term in terms.split():
                    if "#" not in term:
                        continue
                    word, _, rank = term.rpartition("#")
                    key = f"{word.lower()}#{pos}"
                    senses.setdefault(key, {})[int(rank)] = polarity
        # 1/rank weighting over senses, normalized by the harmonic sum —
        # the reference's fold (SWN3.java:107-117)
        for key, by_rank in senses.items():
            score = sum(s / rank for rank, s in by_rank.items())
            norm = sum(1.0 / rank for rank in by_rank)
            self._dict[key] = score / norm if norm else 0.0

    def __len__(self) -> int:
        return len(self._dict)

    # -- scoring ------------------------------------------------------------
    def score_word(self, word: str, pos: Optional[str] = None) -> float:
        """Polarity in [-1, 1].  With ``pos`` (one of a/n/v/r) look up
        that entry; otherwise average the entries present across POS
        (the reference's ``extract`` probes each suffix)."""
        word = word.lower()
        if pos is not None:
            return self._dict.get(f"{word}#{pos}", 0.0)
        found = [self._dict[k] for k in (f"{word}#{p}"
                                         for p in self.POS_TAGS)
                 if k in self._dict]
        return sum(found) / len(found) if found else 0.0

    def score_tokens(self, tokens: Sequence[str]) -> float:
        """Sum of token polarities; the whole sentence flips sign when a
        negation word occurs (scoreTokens:185-188)."""
        total = 0.0
        negated = False
        for tok in tokens:
            low = tok.lower()
            total += self.score_word(low)
            if low in self.negation_words:
                negated = True
        return -total if negated else total

    def score(self, text: str) -> float:
        """Sentence-split, tokenize, sum per-sentence scores."""
        return sum(self.score_tokens(_TOKEN.findall(sent))
                   for sent in _SENT_SPLIT.split(text) if sent.strip())

    # -- classification -----------------------------------------------------
    @staticmethod
    def class_for_score(score: float) -> str:
        if score >= 0.75:
            return "strong_positive"
        if score > 0.25:
            return "positive"
        if score > 0.0:
            return "weak_positive"
        if score == 0.0:
            return "neutral"
        if score >= -0.25:
            return "weak_negative"
        if score > -0.75:
            return "negative"
        return "strong_negative"

    def classify(self, text: str) -> str:
        return self.class_for_score(self.score(text))


def harmonic_number(n: int) -> float:
    """H(n); exposed for tests documenting the sense-weighting fold."""
    return sum(1.0 / k for k in range(1, n + 1))
