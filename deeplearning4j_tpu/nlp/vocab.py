"""Vocabulary: VocabWord, VocabCache, Huffman coding.

Reference parity:
- ``VocabWord`` (models/word2vec/VocabWord.java) — word + frequency +
  Huffman ``codes``/``points`` filled by the Huffman pass.
- ``VocabCache`` (models/word2vec/wordstore/VocabCache.java,
  inmemory/InMemoryLookupCache.java) — term/doc frequencies + index.
- ``Huffman`` (models/word2vec/Huffman.java:27-35) — builds the binary tree
  over frequencies and assigns each word its code path (for hierarchical
  softmax) and inner-node indices (``points``).

TPU-native addition: ``encode_hs_tables`` packs codes/points into dense
padded int32 arrays [V, max_code_len] so the whole hierarchical-softmax
walk becomes batched gathers/scatter-adds on device (no per-word Python in
the training loop).
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class VocabWord:
    word: str
    count: float = 1.0
    index: int = -1
    codes: List[int] = dataclasses.field(default_factory=list)
    points: List[int] = dataclasses.field(default_factory=list)


class VocabCache:
    """Term/doc-frequency store + word<->index mapping."""

    def __init__(self):
        self.vocab: Dict[str, VocabWord] = {}
        self.index: List[str] = []
        self.doc_freq: Counter = Counter()
        self.total_words: float = 0.0
        self.num_docs: int = 0

    # -- building ----------------------------------------------------------
    def add_token(self, word: str, count: float = 1.0) -> VocabWord:
        vw = self.vocab.get(word)
        if vw is None:
            vw = VocabWord(word, 0.0)
            self.vocab[word] = vw
        vw.count += count
        self.total_words += count
        return vw

    def add_document(self, tokens: Iterable[str]) -> None:
        toks = list(tokens)
        for t in toks:
            self.add_token(t)
        for t in set(toks):
            self.doc_freq[t] += 1
        self.num_docs += 1

    def trim(self, min_word_frequency: int = 1) -> None:
        """Drop rare words and (re)build the index ordered by frequency
        descending (the layout Huffman + the unigram table expect)."""
        kept = {w: vw for w, vw in self.vocab.items()
                if vw.count >= min_word_frequency}
        self.vocab = kept
        self.index = sorted(kept, key=lambda w: (-kept[w].count, w))
        for i, w in enumerate(self.index):
            kept[w].index = i

    # -- queries -----------------------------------------------------------
    def __len__(self) -> int:
        return len(self.index)

    def __contains__(self, word: str) -> bool:
        return word in self.vocab

    def word_for(self, index: int) -> str:
        return self.index[index]

    def index_of(self, word: str) -> int:
        vw = self.vocab.get(word)
        return vw.index if vw else -1

    def word_frequency(self, word: str) -> float:
        vw = self.vocab.get(word)
        return vw.count if vw else 0.0

    def doc_frequency(self, word: str) -> int:
        return self.doc_freq.get(word, 0)

    def words(self) -> List[str]:
        return list(self.index)


def build_vocab(sentences: Iterable[str], tokenizer,
                min_word_frequency: int = 1) -> VocabCache:
    """The reference's VocabActor pipeline, sequentially: tokenize ->
    count -> trim -> index (Word2Vec.buildVocab:257)."""
    cache = VocabCache()
    for sent in sentences:
        cache.add_document(tokenizer(sent))
    cache.trim(min_word_frequency)
    return cache


# -- Huffman ----------------------------------------------------------------

def build_huffman(cache: VocabCache) -> None:
    """Assign codes/points to every VocabWord (Huffman.java:27-35).

    points[d] = index of the d-th inner node on the root->leaf path
    (inner nodes numbered 0..V-2); codes[d] = branch taken (0/1)."""
    V = len(cache)
    if V == 0:
        return
    if V == 1:
        vw = cache.vocab[cache.index[0]]
        vw.codes, vw.points = [0], [0]
        return

    # heap of (count, tiebreak, node_id); leaves are 0..V-1, inner V..2V-2
    heap: List[Tuple[float, int, int]] = [
        (cache.vocab[w].count, i, i) for i, w in enumerate(cache.index)]
    heapq.heapify(heap)
    parent = np.zeros(2 * V - 1, dtype=np.int64)
    binary = np.zeros(2 * V - 1, dtype=np.int64)
    next_id = V
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        parent[n1] = next_id
        parent[n2] = next_id
        binary[n2] = 1
        heapq.heappush(heap, (c1 + c2, next_id, next_id))
        next_id += 1
    root = next_id - 1

    for i, w in enumerate(cache.index):
        codes: List[int] = []
        path: List[int] = []
        node = i
        while node != root:
            codes.append(int(binary[node]))
            node = int(parent[node])
            path.append(node)
        codes.reverse()
        path.reverse()
        vw = cache.vocab[w]
        vw.codes = codes
        # inner node id -> 0-based "syn1 row": node - V
        vw.points = [p - V for p in path]


def encode_hs_tables(cache: VocabCache
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dense padded hierarchical-softmax tables for device-side training.

    Returns (codes [V, L] int32, points [V, L] int32, lengths [V] int32)
    where L = max code length; padding uses point=0/code=0 with
    mask from lengths."""
    V = len(cache)
    L = max((len(cache.vocab[w].codes) for w in cache.index), default=1)
    codes = np.zeros((V, L), np.int32)
    points = np.zeros((V, L), np.int32)
    lengths = np.zeros((V,), np.int32)
    for i, w in enumerate(cache.index):
        vw = cache.vocab[w]
        n = len(vw.codes)
        codes[i, :n] = vw.codes
        points[i, :n] = vw.points
        lengths[i] = n
    return codes, points, lengths


def unigram_table(cache: VocabCache, table_size: int = 100_000,
                  power: float = 0.75) -> np.ndarray:
    """Negative-sampling table (InMemoryLookupTable parity): word i occupies
    a slice proportional to count^0.75."""
    V = len(cache)
    counts = np.array([cache.vocab[w].count for w in cache.index])
    probs = counts ** power
    probs /= probs.sum()
    return np.repeat(np.arange(V), np.maximum(
        1, np.round(probs * table_size).astype(np.int64))).astype(np.int32)
