"""WordVectors query API + serialization.

Reference parity: ``wordvectors/WordVectors.java``/``WordVectorsImpl.java``
(``wordsNearest``, ``similarity``) and ``loader/WordVectorSerializer.java``
(word2vec text format round-trip).

TPU-native: similarity queries are one normalized matmul over the whole
embedding table — batched, MXU-shaped — instead of per-word BLAS dots.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class WordVectors:
    """Embedding table + vocab with similarity queries."""

    def __init__(self, cache: VocabCache, vectors: jax.Array):
        assert vectors.shape[0] == len(cache), (vectors.shape, len(cache))
        self.cache = cache
        self.vectors = vectors
        self._normed: Optional[jax.Array] = None

    @property
    def dim(self) -> int:
        return int(self.vectors.shape[1])

    def has_word(self, word: str) -> bool:
        return word in self.cache

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.cache.index_of(word)
        if i < 0:
            return None
        return np.asarray(self.vectors[i])

    def _norm_table(self) -> jax.Array:
        if self._normed is None:
            v = self.vectors
            self._normed = v / jnp.maximum(
                jnp.linalg.norm(v, axis=1, keepdims=True), 1e-12)
        return self._normed

    def similarity(self, w1: str, w2: str) -> float:
        i, j = self.cache.index_of(w1), self.cache.index_of(w2)
        if i < 0 or j < 0:
            return float("nan")
        t = self._norm_table()
        return float(jnp.dot(t[i], t[j]))

    def words_nearest(self, word_or_vec, top_n: int = 10,
                      exclude: Sequence[str] = ()) -> List[Tuple[str, float]]:
        if isinstance(word_or_vec, str):
            i = self.cache.index_of(word_or_vec)
            if i < 0:
                return []
            q = self._norm_table()[i]
            exclude = tuple(exclude) + (word_or_vec,)
        else:
            q = jnp.asarray(word_or_vec)
            q = q / jnp.maximum(jnp.linalg.norm(q), 1e-12)
        sims = self._norm_table() @ q
        order = np.asarray(jnp.argsort(-sims))
        out = []
        for idx in order:
            w = self.cache.word_for(int(idx))
            if w in exclude:
                continue
            out.append((w, float(sims[int(idx)])))
            if len(out) >= top_n:
                break
        return out

    def analogy(self, a: str, b: str, c: str, top_n: int = 5):
        """king - man + woman style query."""
        va, vb, vc = (self.word_vector(w) for w in (a, b, c))
        if va is None or vb is None or vc is None:
            return []
        return self.words_nearest(vb - va + vc, top_n, exclude=(a, b, c))


# -- serialization (WordVectorSerializer parity) ----------------------------

def write_word_vectors(wv: WordVectors, path: str) -> None:
    """word2vec C text format: header 'V dim', then 'word v0 v1 ...'."""
    vecs = np.asarray(wv.vectors)
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"{vecs.shape[0]} {vecs.shape[1]}\n")
        for i in range(vecs.shape[0]):
            vals = " ".join(f"{x:.6f}" for x in vecs[i])
            f.write(f"{wv.cache.word_for(i)} {vals}\n")


def load_word_vectors(path: str) -> WordVectors:
    cache = VocabCache()
    rows: List[np.ndarray] = []
    with open(path, encoding="utf-8") as f:
        header = f.readline().split()
        v, dim = int(header[0]), int(header[1])
        for line in f:
            parts = line.rstrip("\n").split(" ")
            # parse from the END: the last `dim` fields are floats, the
            # word is everything before (n-gram vocab entries contain
            # spaces)
            word = " ".join(parts[:-dim])
            vec = np.asarray([float(x) for x in parts[-dim:]], np.float32)
            cache.add_token(word)
            rows.append(vec)
    # preserve file order as the index
    cache.index = [w for w in cache.vocab]
    for i, w in enumerate(cache.index):
        cache.vocab[w].index = i
    assert len(rows) == v, f"expected {v} rows, got {len(rows)}"
    return WordVectors(cache, jnp.asarray(np.stack(rows)))


def write_word_vectors_binary(wv: WordVectors, path: str) -> None:
    """word2vec C BINARY format (WordVectorSerializer's other half):
    ascii header 'V dim\\n', then per word: 'word ' + dim float32 LE."""
    vecs = np.asarray(wv.vectors, dtype=np.float32)
    with open(path, "wb") as f:
        f.write(f"{vecs.shape[0]} {vecs.shape[1]}\n".encode())
        for i in range(vecs.shape[0]):
            word = wv.cache.word_for(i)
            if " " in word:
                # the C binary layout delimits the word with the FIRST
                # space, so spaced vocab entries (n-grams) cannot
                # round-trip — the text format handles those
                raise ValueError(
                    f"binary format cannot store spaced word {word!r}; "
                    f"use write_word_vectors (text) instead")
            f.write(word.encode("utf-8") + b" ")
            f.write(vecs[i].astype("<f4").tobytes())
            f.write(b"\n")


def load_word_vectors_binary(path: str) -> WordVectors:
    import jax.numpy as jnp

    cache = VocabCache()
    rows: List[np.ndarray] = []
    with open(path, "rb") as f:
        header = f.readline().split()
        v, dim = int(header[0]), int(header[1])
        for _ in range(v):
            word = bytearray()
            while True:
                c = f.read(1)
                if not c:
                    break
                if c in (b" ", b"\t", b"\n", b"\r"):
                    # skip record-separator whitespace BEFORE the word (the
                    # word2vec C writer emits '\n' after each vector; gensim
                    # emits none) instead of consuming a fixed byte after —
                    # the robust-loader convention, so both layouts parse
                    if word:
                        break
                    continue
                word.extend(c)
            vec = np.frombuffer(f.read(4 * dim), dtype="<f4").copy()
            cache.add_token(word.decode("utf-8"))
            rows.append(vec)
    # preserve file order as the index (rows align with words)
    cache.index = [w for w in cache.vocab]
    for i, w in enumerate(cache.index):
        cache.vocab[w].index = i
    return WordVectors(cache, jnp.asarray(np.stack(rows)))
