"""Moving-window featurization for sequence labeling.

Reference parity: ``text/movingwindow/{Window,Windows,WindowConverter,
WordConverter}.java`` — slide a fixed window over a token sequence, embed
each window as the concatenation of its word vectors, classify the center
token, then decode the label sequence with ``utils/viterbi``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory

PAD = "<PAD>"


@dataclasses.dataclass
class Window:
    """One window (Window.java parity): tokens with the focus word in the
    middle, padded at sequence edges."""
    words: List[str]
    focus_index: int
    begin: int
    end: int

    @property
    def focus(self) -> str:
        return self.words[self.focus_index]


def windows(tokens_or_text, window_size: int = 5,
            tokenizer=None) -> List[Window]:
    """All center-aligned windows over a sentence (Windows.java parity).
    ``window_size`` must be odd (a center word needs symmetric context)."""
    if window_size % 2 == 0:
        raise ValueError(f"window_size must be odd, got {window_size}")
    if isinstance(tokens_or_text, str):
        tokenizer = tokenizer or DefaultTokenizerFactory()
        tokens = tokenizer.create(tokens_or_text)
    else:
        tokens = list(tokens_or_text)
    half = window_size // 2
    out = []
    for i in range(len(tokens)):
        ws = []
        for j in range(i - half, i + half + 1):
            ws.append(tokens[j] if 0 <= j < len(tokens) else PAD)
        out.append(Window(words=ws, focus_index=half,
                          begin=max(i - half, 0),
                          end=min(i + half, len(tokens) - 1)))
    return out


class WindowConverter:
    """Window -> concatenated word-vector features (WindowConverter.java).

    Uses a WordVectors-like object (``word_vector(w)`` + ``dim``); unknown
    words and PAD map to zeros.
    """

    def __init__(self, word_vectors):
        self.wv = word_vectors

    def to_features(self, window: Window) -> np.ndarray:
        d = self.wv.dim
        parts = []
        for w in window.words:
            vec = None if w == PAD else self.wv.word_vector(w)
            parts.append(np.zeros(d, np.float32) if vec is None
                         else np.asarray(vec, np.float32))
        return np.concatenate(parts)

    def to_matrix(self, wins: Sequence[Window]) -> np.ndarray:
        return np.stack([self.to_features(w) for w in wins])


def sentence_features(text_or_tokens, word_vectors, window_size: int = 5,
                      tokenizer=None) -> np.ndarray:
    """[T, window_size*dim] feature matrix for a whole sentence — the input
    to a per-position classifier whose outputs feed utils/viterbi.decode."""
    wins = windows(text_or_tokens, window_size, tokenizer)
    return WindowConverter(word_vectors).to_matrix(wins)
