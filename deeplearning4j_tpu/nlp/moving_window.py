"""Moving-window featurization for sequence labeling.

Reference parity: ``text/movingwindow/{Window,Windows,WindowConverter,
WordConverter}.java`` — slide a fixed window over a token sequence, embed
each window as the concatenation of its word vectors, classify the center
token, then decode the label sequence with ``utils/viterbi``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory

PAD = "<PAD>"


@dataclasses.dataclass
class Window:
    """One window (Window.java parity): tokens with the focus word in the
    middle, padded at sequence edges."""
    words: List[str]
    focus_index: int
    begin: int
    end: int

    @property
    def focus(self) -> str:
        return self.words[self.focus_index]


def windows(tokens_or_text, window_size: int = 5,
            tokenizer=None) -> List[Window]:
    """All center-aligned windows over a sentence (Windows.java parity).
    ``window_size`` must be odd (a center word needs symmetric context)."""
    if window_size % 2 == 0:
        raise ValueError(f"window_size must be odd, got {window_size}")
    if isinstance(tokens_or_text, str):
        tokenizer = tokenizer or DefaultTokenizerFactory()
        tokens = tokenizer.create(tokens_or_text)
    else:
        tokens = list(tokens_or_text)
    half = window_size // 2
    out = []
    for i in range(len(tokens)):
        ws = []
        for j in range(i - half, i + half + 1):
            ws.append(tokens[j] if 0 <= j < len(tokens) else PAD)
        out.append(Window(words=ws, focus_index=half,
                          begin=max(i - half, 0),
                          end=min(i + half, len(tokens) - 1)))
    return out


class WindowConverter:
    """Window -> concatenated word-vector features (WindowConverter.java).

    Uses a WordVectors-like object (``word_vector(w)`` + ``dim``); unknown
    words and PAD map to zeros.
    """

    def __init__(self, word_vectors):
        self.wv = word_vectors

    def to_features(self, window: Window) -> np.ndarray:
        d = self.wv.dim
        parts = []
        for w in window.words:
            vec = None if w == PAD else self.wv.word_vector(w)
            parts.append(np.zeros(d, np.float32) if vec is None
                         else np.asarray(vec, np.float32))
        return np.concatenate(parts)

    def to_matrix(self, wins: Sequence[Window]) -> np.ndarray:
        return np.stack([self.to_features(w) for w in wins])


def sentence_features(text_or_tokens, word_vectors, window_size: int = 5,
                      tokenizer=None) -> np.ndarray:
    """[T, window_size*dim] feature matrix for a whole sentence — the input
    to a per-position classifier whose outputs feed utils/viterbi.decode."""
    wins = windows(text_or_tokens, window_size, tokenizer)
    return WindowConverter(word_vectors).to_matrix(wins)


class Word2VecDataSetIterator:
    """Labeled windows -> DataSets (models/word2vec/iterator/
    Word2VecDataSetIterator.java parity): each window of a labeled
    sentence becomes (concatenated word vectors, one-hot label of the
    focus token) — the featurization feeding a per-position classifier
    (+ utils/viterbi for decoding).
    """

    def __init__(self, word_vectors, labeled_sentences, labels: Sequence[str],
                 batch_size: int = 32, window_size: int = 5,
                 tokenizer=None):
        """labeled_sentences: iterable of (tokens_or_text, token_labels)."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.datasets.dataset import DataSet

        self.labels = list(labels)
        label_ix = {l: i for i, l in enumerate(self.labels)}
        conv = WindowConverter(word_vectors)
        feats, ys = [], []
        for sent, sent_labels in labeled_sentences:
            wins = windows(sent, window_size, tokenizer)
            if len(wins) != len(sent_labels):
                raise ValueError(
                    f"{len(sent_labels)} labels for {len(wins)} tokens")
            for w, lab in zip(wins, sent_labels):
                feats.append(conv.to_features(w))
                ys.append(label_ix[lab])
        x = np.stack(feats)
        y = np.eye(len(self.labels), dtype=np.float32)[np.asarray(ys)]
        self._batches = [
            DataSet(jnp.asarray(x[i:i + batch_size]),
                    jnp.asarray(y[i:i + batch_size]))
            for i in range(0, len(x), batch_size)]
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < len(self._batches)

    def next(self):
        ds = self._batches[self._cursor]
        self._cursor += 1
        return ds

    def reset(self) -> None:
        self._cursor = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()
