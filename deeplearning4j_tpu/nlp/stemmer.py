"""Porter stemmer — pure-Python implementation of Porter (1980).

Reference parity: ``text/annotator/StemmerAnnotator.java`` and
``text/tokenization/tokenizer/preprocessor/EndingPreProcessor`` give the
reference its stemming capability (via the snowball library).  This
module implements the classic Porter algorithm from its published rule
tables — no third-party dependency, suitable as a tokenizer
pre-processor or an annotator stage (see nlp/annotators.py).
"""

from __future__ import annotations

_VOWELS = "aeiou"


def _is_consonant(word: str, i: int) -> bool:
    ch = word[i]
    if ch in _VOWELS:
        return False
    if ch == "y":
        return i == 0 or not _is_consonant(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """m = number of VC sequences in [C](VC)^m[V]."""
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        vowel = not _is_consonant(stem, i)
        if prev_vowel and not vowel:
            m += 1
        prev_vowel = vowel
    return m


def _contains_vowel(stem: str) -> bool:
    return any(not _is_consonant(stem, i) for i in range(len(stem)))


def _ends_double_consonant(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2]
            and _is_consonant(word, len(word) - 1))


def _ends_cvc(word: str) -> bool:
    """*o: stem ends cvc where the final c is not w, x or y."""
    if len(word) < 3:
        return False
    return (_is_consonant(word, len(word) - 3)
            and not _is_consonant(word, len(word) - 2)
            and _is_consonant(word, len(word) - 1)
            and word[-1] not in "wxy")


def _replace(word: str, suffix: str, repl: str, m_min: int) -> str | None:
    """If word ends with suffix and measure(stem) > m_min, replace."""
    if not word.endswith(suffix):
        return None
    stem = word[: len(word) - len(suffix)]
    if _measure(stem) > m_min:
        return stem + repl
    return word                                # matched but condition failed


class PorterStemmer:
    """``stem("relational") == "relat"`` etc.; stateless and reusable."""

    def stem(self, word: str) -> str:
        word = word.lower()
        if len(word) <= 2:
            return word
        word = self._step1a(word)
        word = self._step1b(word)
        word = self._step1c(word)
        word = self._step2(word)
        word = self._step3(word)
        word = self._step4(word)
        word = self._step5a(word)
        word = self._step5b(word)
        return word

    def __call__(self, word: str) -> str:
        return self.stem(word)

    # -- step 1: plurals and -ed/-ing ----------------------------------------
    @staticmethod
    def _step1a(w: str) -> str:
        if w.endswith("sses"):
            return w[:-2]
        if w.endswith("ies"):
            return w[:-2]
        if w.endswith("ss"):
            return w
        if w.endswith("s"):
            return w[:-1]
        return w

    def _step1b(self, w: str) -> str:
        if w.endswith("eed"):
            stem = w[:-3]
            return stem + "ee" if _measure(stem) > 0 else w
        flag = False
        if w.endswith("ed") and _contains_vowel(w[:-2]):
            w, flag = w[:-2], True
        elif w.endswith("ing") and _contains_vowel(w[:-3]):
            w, flag = w[:-3], True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                return w + "e"
            if _ends_double_consonant(w) and w[-1] not in "lsz":
                return w[:-1]
            if _measure(w) == 1 and _ends_cvc(w):
                return w + "e"
        return w

    @staticmethod
    def _step1c(w: str) -> str:
        if w.endswith("y") and _contains_vowel(w[:-1]):
            return w[:-1] + "i"
        return w

    # -- step 2/3: derivational suffixes -------------------------------------
    _STEP2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
              ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
              ("alli", "al"), ("entli", "ent"), ("eli", "e"),
              ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
              ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
              ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
              ("iviti", "ive"), ("biliti", "ble")]

    _STEP3 = [("icate", "ic"), ("ative", ""), ("alize", "al"),
              ("iciti", "ic"), ("ical", "ic"), ("ful", ""), ("ness", "")]

    def _step2(self, w: str) -> str:
        for suf, repl in self._STEP2:
            out = _replace(w, suf, repl, 0)
            if out is not None:
                return out
        return w

    def _step3(self, w: str) -> str:
        for suf, repl in self._STEP3:
            out = _replace(w, suf, repl, 0)
            if out is not None:
                return out
        return w

    # -- step 4: strip residual suffixes when m > 1 --------------------------
    _STEP4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant",
              "ement", "ment", "ent", "ou", "ism", "ate", "iti", "ous",
              "ive", "ize"]

    def _step4(self, w: str) -> str:
        for suf in self._STEP4:
            if w.endswith(suf):
                stem = w[: len(w) - len(suf)]
                if _measure(stem) > 1:
                    return stem
                return w
        if w.endswith("ion"):
            stem = w[:-3]
            if _measure(stem) > 1 and stem and stem[-1] in "st":
                return stem
        return w

    # -- step 5: tidy final e / double l -------------------------------------
    @staticmethod
    def _step5a(w: str) -> str:
        if w.endswith("e"):
            stem = w[:-1]
            m = _measure(stem)
            if m > 1 or (m == 1 and not _ends_cvc(stem)):
                return stem
        return w

    @staticmethod
    def _step5b(w: str) -> str:
        if _measure(w) > 1 and _ends_double_consonant(w) and w.endswith("l"):
            return w[:-1]
        return w


_DEFAULT = PorterStemmer()


def stem(word: str) -> str:
    """Module-level convenience using a shared stateless stemmer."""
    return _DEFAULT.stem(word)
