"""Raw-sentence → binary tree front-end for the RNTN.

Reference parity: ``text/corpora/treeparser/TreeParser.java`` (+ its
``transformer/{BinarizeTreeTransformer,CollapseUnaries}.java``) — the
reference turns plain sentences into binarized constituency trees the
RNTN can train on, via a CoreNLP/UIMA parser.  Zero-egress equivalent:
a TRAINED transition chunker (nlp/chunker.py, averaged perceptron over
B/I/O chunk actions — the trained-parse-model role) over the bundled
perceptron tagger (nlp/pos.py), followed by deterministic binarization,
producing :class:`deeplearning4j_tpu.nlp.rntn.Tree` nodes directly —
already binary, so no separate binarize/collapse-unaries passes are
needed.  The round-4 tag-rule chunker remains as ``mode="rules"``.

Labels: constituency parsing gives structure, not sentiment; interior
nodes get ``neutral_label`` and the root gets the caller's sentence
label — exactly how the reference pipelines raw text into RNTN training
(tree structure from the parser, labels from the dataset).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nlp.pos import AveragedPerceptronTagger, default_tagger
from deeplearning4j_tpu.nlp.rntn import Tree
from deeplearning4j_tpu.nlp.text import word_punct_tokenize

# chunk grammar over PTB tags: maximal runs joined into one phrase
_NP_START = {"DT", "PRP$", "JJ", "JJR", "JJS", "CD"}
_NP_HEAD = {"NN", "NNS", "NNP", "NNPS", "PRP"}
_VP_START = {"MD", "RB", "RBR", "RBS"}
_VP_HEAD = {"VB", "VBD", "VBG", "VBN", "VBP", "VBZ"}


#: shared word/punct tokenizer (see nlp/text.py)
tokenize = word_punct_tokenize


def _chunk(tagged: Sequence[Tuple[str, str]]) -> List[List[str]]:
    """Greedy shallow chunking: [DT/JJ/... NN+] noun phrases and
    [MD/RB/VB...] verb groups; everything else is its own chunk."""
    chunks: List[List[str]] = []
    i = 0
    n = len(tagged)
    while i < n:
        word, tag = tagged[i]
        if tag in _NP_START or tag in _NP_HEAD:
            j = i
            saw_head = False
            while j < n:
                t = tagged[j][1]
                if t in _NP_HEAD:
                    saw_head = True
                    j += 1
                elif t in _NP_START and not saw_head:
                    j += 1
                else:
                    break
            if j > i:
                chunks.append([w for w, _ in tagged[i:j]])
                i = j
                continue
        if tag in _VP_START or tag in _VP_HEAD:
            j = i
            saw_verb = False
            while j < n:
                t = tagged[j][1]
                if t in _VP_HEAD:
                    saw_verb = True
                    j += 1
                elif t in _VP_START:
                    j += 1
                else:
                    break
            if saw_verb:
                chunks.append([w for w, _ in tagged[i:j]])
                i = j
                continue
        chunks.append([word])
        i += 1
    return chunks


def _binarize_right(nodes: List[Tree], label: int) -> Tree:
    """Right-branching binarization (head-final combination, the shape
    BinarizeTreeTransformer produces for flat constituents)."""
    node = nodes[-1]
    for left in reversed(nodes[:-1]):
        node = Tree(label=label, left=left, right=node)
    return node


class TreeParser:
    """``parse(sentence, label)`` → binary :class:`rntn.Tree`.

    ``mode="model"`` (default) chunks with the TRAINED transition
    chunker (nlp/chunker.py — the reference's trained-parse-model role,
    TreeParser.java:57); ``mode="rules"`` keeps the round-4 tag-rule
    heuristic as the zero-cost fallback.

    ``neutral_label`` fills interior/leaf nodes (class 2 of the 5-class
    sentiment scheme); the sentence-level ``label`` lands on the root.
    """

    def __init__(self, tagger: Optional[AveragedPerceptronTagger] = None,
                 neutral_label: int = 2, propagate_label: bool = True,
                 mode: str = "model", chunker=None):
        if mode not in ("model", "rules"):
            raise ValueError(f"mode must be 'model' or 'rules': {mode!r}")
        self._tagger = tagger
        self.neutral_label = neutral_label
        #: with only a sentence-level label available, propagate it to
        #: interior phrase nodes (leaves stay neutral) — the RNTN loss is
        #: per-node, so root-only labeling would drown in neutral targets
        self.propagate_label = propagate_label
        self.mode = mode
        self._chunker = chunker

    @property
    def tagger(self) -> AveragedPerceptronTagger:
        if self._tagger is None:
            self._tagger = default_tagger()
        return self._tagger

    @property
    def chunker(self):
        if self._chunker is None:
            if self._tagger is None:
                from deeplearning4j_tpu.nlp.chunker import default_chunker
                self._chunker = default_chunker()
            else:
                # a custom tagger's tag distribution differs from the
                # bundled one the default chunker was trained on — train
                # a chunker on THIS tagger's output so the 't:'/'t2:'
                # features match what parse() will feed it
                from deeplearning4j_tpu.nlp.chunker import (
                    ChunkPerceptron, annotated_corpus)
                self._chunker = ChunkPerceptron().train(
                    annotated_corpus(self._tagger))
        return self._chunker

    def _chunks(self, tagged) -> List[List[str]]:
        if self.mode == "model":
            return self.chunker.chunk(tagged)
        return _chunk(tagged)

    def parse(self, sentence: str, label: Optional[int] = None) -> Tree:
        tokens = tokenize(sentence)
        if not tokens:
            raise ValueError("empty sentence")
        neutral = self.neutral_label
        interior = (label if (label is not None and self.propagate_label)
                    else neutral)
        tagged = self.tagger.tag(tokens)
        phrase_trees: List[Tree] = []
        for chunk in self._chunks(tagged):
            leaves = [Tree(label=neutral, word=w) for w in chunk]
            phrase_trees.append(_binarize_right(leaves, interior))
        root = _binarize_right(phrase_trees, interior)
        root.label = neutral if label is None else label
        return root

    def parse_labeled(self, labeled: Sequence[Tuple[str, int]]) -> List[Tree]:
        """[(sentence, label)] → trees ready for ``RNTN.fit`` — the
        raw-text training path TreeParser.java enables."""
        return [self.parse(s, lab) for s, lab in labeled]


def trees_from_raw(labeled: Sequence[Tuple[str, int]],
                   tagger: Optional[AveragedPerceptronTagger] = None,
                   mode: str = "model") -> List[Tree]:
    """Module-level convenience: raw labeled sentences → RNTN trees
    (model-chunked by default; ``mode="rules"`` for the heuristic)."""
    return TreeParser(tagger, mode=mode).parse_labeled(labeled)
