"""ParagraphVectors — PV-DBOW/PV-DM document embeddings.

Reference parity: ``models/paragraphvectors/ParagraphVectors.java:53``
(``dbow:188``, ``trainSentence:165``) — label words are injected into the
same embedding space as vocabulary words and trained alongside them.

TPU-native: the label "word" is just an extra row of syn0 trained against
every center word of its document (PV-DBOW), or averaged into the context
(PV-DM simplified to the DBOW-style update the reference actually performs
in ``dbow``).  Label pairs ride the word2vec scanned-epoch machinery
(``_scan_slab`` — one dispatch per epoch, Pallas VMEM kernel on TPU) by
encoding them as candidate pairs with ``delta = 0``: the on-device dynamic
window shrink ``|delta| <= window - b`` always passes for them, so they
train every epoch exactly like the reference's dbow loop, while real word
pairs keep their shrink semantics.  Inference for an unseen document
trains ONLY its new label row with the rest of the space frozen.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (VocabCache, build_huffman,
                                          encode_hs_tables)
from deeplearning4j_tpu.nlp.word2vec import (Word2VecConfig,
                                             corpus_pairs, hs_mask_table,
                                             run_pair_training)
from deeplearning4j_tpu.nlp.word_vectors import WordVectors


@dataclasses.dataclass
class ParagraphVectorsConfig(Word2VecConfig):
    train_words: bool = True     # PV-DBOW + word training (dbow+w2v)


class ParagraphVectors:
    """fit() over labelled documents [(label, text), ...]."""

    def __init__(self, labelled_docs: Sequence[Tuple[str, str]],
                 config: Optional[ParagraphVectorsConfig] = None,
                 tokenizer=None):
        self.config = config or ParagraphVectorsConfig()
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.docs = list(labelled_docs)
        self.cache: Optional[VocabCache] = None
        self.labels: List[str] = []
        self.syn0 = None
        self.syn1 = None
        self._hs_tables = None
        self._wv: Optional[WordVectors] = None

    def fit(self) -> WordVectors:
        cfg = self.config
        # vocab over words AND label tokens (label words live in the space)
        cache = VocabCache()
        for label, text in self.docs:
            cache.add_document(self.tokenizer(text))
        cache.trim(cfg.min_word_frequency)
        self.labels = sorted({l for l, _ in self.docs})
        for l in self.labels:
            cache.add_token(l, count=1.0)
        # labels not already in the word index are appended after it
        # (a label sharing a word's surface form shares its row)
        existing = set(cache.index)
        cache.index += [l for l in self.labels if l not in existing]
        for i, w in enumerate(cache.index):
            cache.vocab[w].index = i
        build_huffman(cache)
        self.cache = cache

        V, D = len(cache), cfg.vector_size
        key = jax.random.key(cfg.seed)
        self.syn0 = (jax.random.uniform(key, (V, D)) - 0.5) / D
        self.syn1 = jnp.zeros((V, D))

        codes_np, points_np, lengths_t = encode_hs_tables(cache)
        mask_full = hs_mask_table(codes_np, lengths_t)
        codes_t = jnp.asarray(codes_np)
        points_t = jnp.asarray(points_np)
        # cached for infer_vector (rebuilding iterates the whole vocab)
        self._hs_tables = (codes_np, points_np, np.asarray(mask_full))

        # Assemble ONE candidate pair list for the whole corpus, then run
        # the word2vec scanned-epoch engine on it.  Label pairs (PV-DBOW:
        # label row predicts every doc word) get delta = 0 so the
        # on-device window-shrink mask always keeps them; word pairs come
        # from corpus_pairs with real deltas.
        indexed: List[np.ndarray] = []
        label_rows: List[int] = []
        for label, text in self.docs:
            idx = np.asarray(
                [i for i in (cache.index_of(t)
                             for t in self.tokenizer(text)) if i >= 0],
                np.int32)
            if idx.size:
                indexed.append(idx)
                label_rows.append(cache.index_of(label))
        if not indexed:
            self._wv = WordVectors(cache, self.syn0)
            return self._wv

        lens = np.asarray([a.size for a in indexed])
        starts = np.concatenate([[0], np.cumsum(lens)[:-1]])
        # int64 like corpus_pairs' word_offset: the lr clock stays exact
        # however large the corpus (float happens at ratio time)
        seen_before = starts.astype(np.int64)
        # label pairs: (center=word, input=label row, pos=token position)
        lb_cen = np.concatenate(indexed)
        lb_ctx = np.repeat(np.asarray(label_rows, np.int32), lens)
        lb_pos = np.arange(lb_cen.size, dtype=np.int32)
        lb_dlt = np.zeros(lb_cen.size, np.int32)
        lb_off = np.repeat(seen_before, lens)
        if cfg.train_words:
            w_cen, w_ctx, w_pos, w_dlt, w_off = corpus_pairs(
                indexed, cfg.window)
            cen = np.concatenate([lb_cen, w_cen])
            ctx = np.concatenate([lb_ctx, w_ctx])
            pos = np.concatenate([lb_pos, w_pos])
            dlt = np.concatenate([lb_dlt, w_dlt])
            off = np.concatenate([lb_off, w_off])
        else:
            cen, ctx, pos, dlt, off = (lb_cen, lb_ctx, lb_pos, lb_dlt,
                                       lb_off)

        total_words = int(lens.sum())
        self.syn0, self.syn1, _, _, self.kernel_used = run_pair_training(
            self.syn0, self.syn1, None, (cen, ctx, pos, dlt, off),
            vocab_size=V, dim=D, epochs=cfg.epochs,
            total_words=total_words, codes_t=codes_t, points_t=points_t,
            mask_t=mask_full, table=jnp.zeros((1,), jnp.int32),
            window=cfg.window, alpha=cfg.alpha, min_alpha=cfg.min_alpha,
            use_hs=True, negative=0, batch_size=cfg.batch_size,
            kernel=cfg.kernel, seed=cfg.seed)

        self._wv = WordVectors(cache, self.syn0)
        return self._wv

    # -- queries ------------------------------------------------------------
    @property
    def word_vectors(self) -> WordVectors:
        if self._wv is None:
            raise RuntimeError("call fit() first")
        return self._wv

    def doc_vector(self, label: str) -> Optional[np.ndarray]:
        return self.word_vectors.word_vector(label)

    def similarity(self, a: str, b: str) -> float:
        return self.word_vectors.similarity(a, b)

    def infer_vector(self, text: str, epochs: int = 25,
                     alpha: Optional[float] = None) -> np.ndarray:
        """Embed an UNSEEN document: train a fresh syn0-style row against
        the document's words' Huffman paths with the rest of the space
        frozen (the PV inference step; the reference retrains through the
        same dbow update with only the new label row unfrozen)."""
        cfg = self.config
        if self.cache is None or self.syn1 is None:
            raise RuntimeError("call fit() first")
        idx = [self.cache.index_of(t) for t in self.tokenizer(text)]
        idx = np.asarray([i for i in idx if i >= 0], np.int32)
        if idx.size == 0:
            return np.zeros(cfg.vector_size, np.float32)
        codes_np, points_np, mask_np = self._hs_tables
        mask = mask_np[idx]
        codes = codes_np[idx].astype(np.float32)         # [n, L]
        points = points_np[idx]                          # [n, L]
        # on-device gather: syn1 stays put, only [n, L, D] rows move
        s1 = jnp.take(self.syn1, jnp.asarray(points), axis=0)  # frozen
        codes_j, mask_j = jnp.asarray(codes), jnp.asarray(mask)
        a = jnp.float32(alpha if alpha is not None else cfg.alpha)
        key = jax.random.key(cfg.seed + 7)
        v0 = (jax.random.uniform(key, (cfg.vector_size,)) - 0.5) \
            / cfg.vector_size

        def epoch_step(v, _):
            f = jax.nn.sigmoid(jnp.einsum("d,nld->nl", v, s1))
            g = (1.0 - codes_j - f) * a * mask_j
            return v + jnp.einsum("nl,nld->d", g, s1) / idx.size, None

        v, _ = jax.lax.scan(epoch_step, v0, None, length=epochs)
        return np.asarray(v)

    def nearest_labels(self, text: str, top_n: int = 3):
        """Infer by averaging word vectors of the text, rank labels."""
        idx = [self.cache.index_of(t) for t in self.tokenizer(text)]
        idx = [i for i in idx if i >= 0]
        if not idx:
            return []
        v = np.asarray(self.syn0)[idx].mean(axis=0)
        sims = self.word_vectors.words_nearest(v, top_n=len(self.cache))
        return [(w, s) for w, s in sims if w in set(self.labels)][:top_n]
