"""ParagraphVectors — PV-DBOW/PV-DM document embeddings.

Reference parity: ``models/paragraphvectors/ParagraphVectors.java:53``
(``dbow:188``, ``trainSentence:165``) — label words are injected into the
same embedding space as vocabulary words and trained alongside them.

TPU-native: reuses the word2vec batched kernels (_hs_step) — the label
"word" is just an extra row of syn0 trained against every center word of
its document (PV-DBOW), or averaged into the context (PV-DM simplified to
the DBOW-style update the reference actually performs in ``dbow``).
Inference for an unseen document trains ONLY its new label row with the
rest of the space frozen.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (VocabCache, build_huffman,
                                          encode_hs_tables)
from deeplearning4j_tpu.nlp.word2vec import (Word2VecConfig, _hs_step,
                                             sentence_pairs)
from deeplearning4j_tpu.nlp.word_vectors import WordVectors


@dataclasses.dataclass
class ParagraphVectorsConfig(Word2VecConfig):
    train_words: bool = True     # PV-DBOW + word training (dbow+w2v)


class ParagraphVectors:
    """fit() over labelled documents [(label, text), ...]."""

    def __init__(self, labelled_docs: Sequence[Tuple[str, str]],
                 config: Optional[ParagraphVectorsConfig] = None,
                 tokenizer=None):
        self.config = config or ParagraphVectorsConfig()
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.docs = list(labelled_docs)
        self.cache: Optional[VocabCache] = None
        self.labels: List[str] = []
        self.syn0 = None
        self.syn1 = None
        self._wv: Optional[WordVectors] = None

    def fit(self) -> WordVectors:
        cfg = self.config
        # vocab over words AND label tokens (label words live in the space)
        cache = VocabCache()
        for label, text in self.docs:
            cache.add_document(self.tokenizer(text))
        cache.trim(cfg.min_word_frequency)
        self.labels = sorted({l for l, _ in self.docs})
        for l in self.labels:
            cache.add_token(l, count=1.0)
        # labels not already in the word index are appended after it
        # (a label sharing a word's surface form shares its row)
        existing = set(cache.index)
        cache.index += [l for l in self.labels if l not in existing]
        for i, w in enumerate(cache.index):
            cache.vocab[w].index = i
        build_huffman(cache)
        self.cache = cache

        V, D = len(cache), cfg.vector_size
        key = jax.random.key(cfg.seed)
        self.syn0 = (jax.random.uniform(key, (V, D)) - 0.5) / D
        self.syn1 = jnp.zeros((V, D))

        codes_t, points_t, lengths_t = encode_hs_tables(cache)
        codes_t = jnp.asarray(codes_t)
        points_t = jnp.asarray(points_t)
        mask_full = jnp.asarray(
            (np.arange(codes_t.shape[1])[None, :] <
             np.asarray(lengths_t)[:, None]).astype(np.float32))

        rng = np.random.RandomState(cfg.seed)
        B = cfg.batch_size

        def train_pairs(inputs_np, centers_np):
            """inputs: syn0 rows to move; centers: HS target words."""
            for lo in range(0, inputs_np.size, B):
                ib = inputs_np[lo:lo + B]
                cb = centers_np[lo:lo + B]
                n_real = ib.size
                if n_real < B:
                    pad = B - n_real
                    ib = np.concatenate([ib, np.zeros(pad, np.int32)])
                    cb = np.concatenate([cb, np.zeros(pad, np.int32)])
                pmask = jnp.asarray(np.arange(B) < n_real, jnp.float32)
                centers = jnp.asarray(cb)
                self.syn0, self.syn1 = _hs_step(
                    self.syn0, self.syn1, jnp.asarray(ib),
                    codes_t[centers], points_t[centers],
                    mask_full[centers] * pmask[:, None],
                    jnp.float32(cfg.alpha))

        for _ in range(cfg.epochs):
            for label, text in self.docs:
                li = cache.index_of(label)
                idx = np.asarray(
                    [i for i in (cache.index_of(t)
                                 for t in self.tokenizer(text)) if i >= 0],
                    np.int32)
                if idx.size == 0:
                    continue
                # PV-DBOW: the label row is trained to predict every word
                lbl_in = np.full(idx.size, li, np.int32)
                train_pairs(lbl_in, idx)
                if cfg.train_words:
                    c, x = sentence_pairs(idx, cfg.window, rng)
                    if c.size:
                        train_pairs(x, c)

        self._wv = WordVectors(cache, self.syn0)
        return self._wv

    # -- queries ------------------------------------------------------------
    @property
    def word_vectors(self) -> WordVectors:
        if self._wv is None:
            raise RuntimeError("call fit() first")
        return self._wv

    def doc_vector(self, label: str) -> Optional[np.ndarray]:
        return self.word_vectors.word_vector(label)

    def similarity(self, a: str, b: str) -> float:
        return self.word_vectors.similarity(a, b)

    def nearest_labels(self, text: str, top_n: int = 3):
        """Infer by averaging word vectors of the text, rank labels."""
        idx = [self.cache.index_of(t) for t in self.tokenizer(text)]
        idx = [i for i in idx if i >= 0]
        if not idx:
            return []
        v = np.asarray(self.syn0)[idx].mean(axis=0)
        sims = self.word_vectors.words_nearest(v, top_n=len(self.cache))
        return [(w, s) for w, s in sims if w in set(self.labels)][:top_n]
