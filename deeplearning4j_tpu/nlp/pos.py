"""Part-of-speech tagging — averaged perceptron, trainable and bundled.

Reference parity: ``text/annotator/PoStagger.java`` (UIMA wrapper around
a pretrained OpenNLP maxent model) and
``text/tokenization/tokenizer/PosUimaTokenizer.java`` (keeps only tokens
whose tag is in an allow-list).  This environment is zero-egress, so
instead of shipping a 10 MB pretrained model the tagger is a compact
averaged perceptron (Collins 2002) trained on a bundled seed corpus at
first use — the same Penn-Treebank tag inventory, trainable on any
user-supplied tagged corpus, serializable to JSON.

Tags follow the PTB convention (NN, NNS, VB, VBD, JJ, DT, IN, ...).
"""

from __future__ import annotations

import json
import random
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

TaggedSentence = Sequence[Tuple[str, str]]


def _normalize(word: str) -> str:
    if any(c.isdigit() for c in word):
        return "!DIGITS" if word.isdigit() else "!MIXEDDIGITS"
    return word.lower()


def _features(i: int, word: str, context: List[str],
              prev: str, prev2: str) -> List[str]:
    """Feature templates: word identity, affixes, shape, neighbors, and
    the two previous predicted tags (the classic Collins set)."""
    w = context[i]
    feats = [
        "bias",
        f"w={w}",
        f"suf3={word[-3:]}",
        f"suf2={word[-2:]}",
        f"pre1={word[:1]}",
        f"p1={prev}",
        f"p2={prev2}",
        f"p1p2={prev}|{prev2}",
        f"p1w={prev}|{w}",
        f"w-1={context[i - 1]}",
        f"w-1suf3={context[i - 1][-3:]}",
        f"w-2={context[i - 2]}",
        f"w+1={context[i + 1]}",
        f"w+1suf3={context[i + 1][-3:]}",
        f"w+2={context[i + 2]}",
    ]
    if word and word[0].isupper():
        feats.append("shape=cap")
    if "-" in word:
        feats.append("shape=hyphen")
    return feats


class AveragedPerceptronTagger:
    """Greedy left-to-right tagger with averaged-perceptron weights.

    ``train`` on (word, tag) sentences; ``tag`` a token list.  Words seen
    unambiguously in training short-circuit through a tag dictionary
    (standard speedup + accuracy trick).
    """

    START = ["-START2-", "-START-"]
    END = ["-END-", "-END2-"]

    def __init__(self):
        self.weights: Dict[str, Dict[str, float]] = {}
        self.tagdict: Dict[str, str] = {}
        self.classes: List[str] = []

    # -- inference ----------------------------------------------------------
    def _score(self, feats: Sequence[str]) -> Dict[str, float]:
        scores: Dict[str, float] = defaultdict(float)
        for f in feats:
            for tag, w in self.weights.get(f, {}).items():
                scores[tag] += w
        return scores

    def _predict(self, feats: Sequence[str]) -> str:
        scores = self._score(feats)
        if not scores:
            return self.classes[0] if self.classes else "NN"
        # deterministic tie-break by tag name
        return max(self.classes, key=lambda t: (scores.get(t, 0.0), t))

    def tag(self, tokens: Sequence[str]) -> List[Tuple[str, str]]:
        prev, prev2 = self.START
        context = (self.START + [_normalize(t) for t in tokens] + self.END)
        out: List[Tuple[str, str]] = []
        for i, word in enumerate(tokens):
            guess = self.tagdict.get(_normalize(word))
            if guess is None:
                feats = _features(i + 2, word, context, prev, prev2)
                guess = self._predict(feats)
            out.append((word, guess))
            prev2, prev = prev, guess
        return out

    # -- training -----------------------------------------------------------
    def train(self, sentences: Iterable[TaggedSentence],
              n_iter: int = 8, seed: int = 7) -> "AveragedPerceptronTagger":
        sentences = [list(s) for s in sentences]
        self._build_tagdict(sentences)
        self.classes = sorted({t for s in sentences for _, t in s})

        totals: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        stamps: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int))
        weights: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        self.weights = weights
        instant = 0
        rng = random.Random(seed)

        def upd(f: str, tag: str, delta: float) -> None:
            # lazily-averaged update: fold in elapsed time before changing
            totals[f][tag] += (instant - stamps[f][tag]) * weights[f][tag]
            stamps[f][tag] = instant
            weights[f][tag] += delta

        for _ in range(n_iter):
            rng.shuffle(sentences)
            for sent in sentences:
                tokens = [w for w, _ in sent]
                context = (self.START + [_normalize(t) for t in tokens]
                           + self.END)
                prev, prev2 = self.START
                for i, (word, gold) in enumerate(sent):
                    instant += 1
                    guess = self.tagdict.get(_normalize(word))
                    if guess is None:
                        feats = _features(i + 2, word, context, prev, prev2)
                        guess = self._predict(feats)
                        if guess != gold:
                            for f in feats:
                                upd(f, gold, +1.0)
                                upd(f, guess, -1.0)
                    prev2, prev = prev, guess
        # final average
        averaged: Dict[str, Dict[str, float]] = {}
        for f, tags in weights.items():
            row = {}
            for tag, w in tags.items():
                total = totals[f][tag] + (instant - stamps[f][tag]) * w
                avg = total / max(instant, 1)
                if abs(avg) > 1e-9:
                    row[tag] = round(avg, 6)
            if row:
                averaged[f] = row
        self.weights = averaged
        return self

    def _build_tagdict(self, sentences: Sequence[TaggedSentence],
                       freq_min: int = 3, ambiguity: float = 0.99) -> None:
        counts: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int))
        for sent in sentences:
            for word, tag in sent:
                counts[_normalize(word)][tag] += 1
        self.tagdict = {}
        for word, tags in counts.items():
            tag, n = max(tags.items(), key=lambda kv: kv[1])
            total = sum(tags.values())
            if total >= freq_min and n / total >= ambiguity:
                self.tagdict[word] = tag
        # closed classes are enumerable: the lexicon always wins for them
        self.tagdict.update(CLOSED_CLASS)

    # -- persistence --------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({"weights": self.weights, "tagdict": self.tagdict,
                           "classes": self.classes})

    @classmethod
    def from_json(cls, blob: str) -> "AveragedPerceptronTagger":
        d = json.loads(blob)
        t = cls()
        t.weights = d["weights"]
        t.tagdict = d["tagdict"]
        t.classes = d["classes"]
        return t


# ---------------------------------------------------------------------------
# Closed-class lexicon: determiners, prepositions, pronouns, conjunctions,
# modals, auxiliaries, wh-words and punctuation are finite sets — listing
# them beats learning them from a small corpus.
# ---------------------------------------------------------------------------

CLOSED_CLASS: Dict[str, str] = {
    **{w: "DT" for w in ("the", "a", "an", "this", "that", "these",
                         "those", "each", "every", "some", "any", "no",
                         "all", "both", "either", "neither", "another")},
    **{w: "IN" for w in ("of", "in", "on", "at", "by", "for", "with",
                         "about", "against", "between", "into", "through",
                         "during", "before", "after", "above", "below",
                         "from", "up", "down", "under", "over", "near",
                         "across", "behind", "beyond", "within", "without",
                         "toward", "towards", "upon", "since", "until",
                         "although", "because", "while", "whether", "if",
                         "than", "per")},
    **{w: "PRP" for w in ("i", "you", "he", "she", "it", "we", "they",
                          "me", "him", "her", "us", "them", "myself",
                          "himself", "herself", "itself", "themselves")},
    **{w: "PRP$" for w in ("my", "your", "his", "its", "our", "their")},
    **{w: "CC" for w in ("and", "or", "but", "nor", "yet", "so")},
    **{w: "MD" for w in ("can", "could", "may", "might", "must", "shall",
                         "should", "will", "would")},
    **{w: "WRB" for w in ("when", "where", "why", "how")},
    **{w: "WDT" for w in ("which", "whatever", "whichever")},
    **{w: "WP" for w in ("who", "whom", "what")},
    **{w: "EX" for w in ("there",)},
    **{w: "TO" for w in ("to",)},
    **{w: "RB" for w in ("not", "n't", "never", "also", "just", "only",
                         "very", "too", "then", "now", "here", "again",
                         "always", "often", "already")},
    **{w: "." for w in (".", "!", "?")},
    **{w: "," for w in (",",)},
    **{w: ":" for w in (":", ";")},
    **{w: "CD" for w in ("one", "two", "three", "four", "five", "six",
                         "seven", "eight", "nine", "ten", "zero")},
}


# ---------------------------------------------------------------------------
# Bundled seed corpus (hand-tagged, PTB tags) — enough signal for the
# suffix/context features to generalize to everyday text; users with a
# real treebank should train on it instead.
# ---------------------------------------------------------------------------

def _t(s: str) -> List[Tuple[str, str]]:
    return [tuple(p.rsplit("/", 1)) for p in s.split()]


SEED_CORPUS: List[List[Tuple[str, str]]] = [_t(s) for s in [
    "the/DT quick/JJ brown/JJ fox/NN jumps/VBZ over/IN the/DT lazy/JJ dog/NN ./.",
    "a/DT cat/NN sat/VBD on/IN the/DT mat/NN ./.",
    "dogs/NNS and/CC cats/NNS are/VBP friendly/JJ animals/NNS ./.",
    "she/PRP quickly/RB opened/VBD the/DT old/JJ wooden/JJ door/NN ./.",
    "he/PRP is/VBZ running/VBG to/TO the/DT store/NN ./.",
    "they/PRP have/VBP finished/VBN the/DT long/JJ report/NN ./.",
    "we/PRP will/MD build/VB a/DT new/JJ model/NN tomorrow/NN ./.",
    "the/DT children/NNS played/VBD happily/RB in/IN the/DT park/NN ./.",
    "my/PRP$ older/JJR brother/NN drives/VBZ a/DT red/JJ car/NN ./.",
    "this/DT is/VBZ the/DT best/JJS result/NN of/IN all/DT ./.",
    "john/NNP gave/VBD mary/NNP a/DT beautiful/JJ gift/NN ./.",
    "the/DT company/NN reported/VBD strong/JJ earnings/NNS yesterday/NN ./.",
    "researchers/NNS trained/VBD the/DT network/NN on/IN large/JJ datasets/NNS ./.",
    "the/DT model/NN learns/VBZ useful/JJ representations/NNS from/IN text/NN ./.",
    "it/PRP was/VBD raining/VBG heavily/RB when/WRB we/PRP arrived/VBD ./.",
    "can/MD you/PRP open/VB the/DT window/NN ,/, please/UH ?/.",
    "the/DT very/RB tall/JJ man/NN walked/VBD slowly/RB ./.",
    "birds/NNS fly/VBP south/RB in/IN the/DT winter/NN ./.",
    "she/PRP wrote/VBD three/CD papers/NNS about/IN neural/JJ networks/NNS ./.",
    "the/DT students/NNS are/VBP studying/VBG for/IN their/PRP$ exams/NNS ./.",
    "i/PRP think/VBP that/IN he/PRP knows/VBZ the/DT answer/NN ./.",
    "a/DT small/JJ boat/NN sailed/VBD across/IN the/DT calm/JJ lake/NN ./.",
    "the/DT weather/NN was/VBD cold/JJ and/CC windy/JJ ./.",
    "computers/NNS process/VBP information/NN faster/RBR than/IN humans/NNS ./.",
    "the/DT old/JJ library/NN contains/VBZ thousands/NNS of/IN books/NNS ./.",
    "he/PRP carefully/RB examined/VBD the/DT broken/JJ machine/NN ./.",
    "the/DT team/NN won/VBD the/DT final/JJ game/NN easily/RB ./.",
    "new/JJ ideas/NNS often/RB come/VBP from/IN simple/JJ questions/NNS ./.",
    "the/DT train/NN arrives/VBZ at/IN noon/NN every/DT day/NN ./.",
    "farmers/NNS grow/VBP wheat/NN in/IN these/DT fields/NNS ./.",
    "she/PRP has/VBZ been/VBN working/VBG here/RB for/IN ten/CD years/NNS ./.",
    "the/DT bright/JJ sun/NN melted/VBD the/DT snow/NN quickly/RB ./.",
    "good/JJ teachers/NNS explain/VBP difficult/JJ concepts/NNS clearly/RB ./.",
    "the/DT river/NN flows/VBZ through/IN the/DT green/JJ valley/NN ./.",
    "we/PRP visited/VBD an/DT ancient/JJ castle/NN in/IN scotland/NNP ./.",
    "the/DT price/NN of/IN oil/NN rose/VBD sharply/RB last/JJ week/NN ./.",
    "young/JJ children/NNS learn/VBP languages/NNS very/RB quickly/RB ./.",
    "the/DT musician/NN played/VBD a/DT beautiful/JJ song/NN ./.",
    "scientists/NNS discovered/VBD a/DT new/JJ species/NN of/IN frog/NN ./.",
    "the/DT engine/NN stopped/VBD suddenly/RB near/IN the/DT bridge/NN ./.",
    "many/JJ people/NNS enjoy/VBP reading/VBG mystery/NN novels/NNS ./.",
    "the/DT chef/NN prepared/VBD a/DT delicious/JJ meal/NN for/IN us/PRP ./.",
    "strong/JJ winds/NNS damaged/VBD several/JJ houses/NNS last/JJ night/NN ./.",
    "the/DT doctor/NN examined/VBD the/DT patient/NN carefully/RB ./.",
    "these/DT flowers/NNS bloom/VBP early/RB in/IN the/DT spring/NN ./.",
    "the/DT lawyer/NN presented/VBD convincing/JJ evidence/NN today/NN ./.",
    "tall/JJ buildings/NNS dominate/VBP the/DT city/NN skyline/NN ./.",
    "the/DT baby/NN slept/VBD peacefully/RB through/IN the/DT storm/NN ./.",
    "workers/NNS repaired/VBD the/DT damaged/VBN road/NN quickly/RB ./.",
    "the/DT artist/NN painted/VBD a/DT stunning/JJ portrait/NN ./.",
    "fresh/JJ vegetables/NNS taste/VBP better/JJR than/IN frozen/JJ ones/NNS ./.",
    "the/DT committee/NN approved/VBD the/DT new/JJ budget/NN ./.",
    "heavy/JJ rain/NN flooded/VBD the/DT lower/JJR streets/NNS ./.",
    "the/DT pilot/NN landed/VBD the/DT plane/NN safely/RB ./.",
    "curious/JJ tourists/NNS photographed/VBD the/DT famous/JJ statue/NN ./.",
    "the/DT software/NN runs/VBZ smoothly/RB on/IN older/JJR machines/NNS ./.",
    "loud/JJ music/NN annoyed/VBD the/DT sleeping/VBG neighbors/NNS ./.",
    "the/DT gardener/NN watered/VBD the/DT thirsty/JJ plants/NNS ./.",
    "brave/JJ firefighters/NNS rescued/VBD the/DT trapped/VBN family/NN ./.",
    "the/DT economy/NN grew/VBD steadily/RB during/IN the/DT decade/NN ./.",
    # no-trailing-punctuation forms so -END- context is not welded to "."
    "a/DT happy/JJ child/NN held/VBD a/DT shiny/JJ red/JJ balloon/NN",
    "the/DT hungry/JJ wolves/NNS followed/VBD the/DT snowy/JJ trail/NN",
    "sleepy/JJ travelers/NNS waited/VBD near/IN the/DT busy/JJ gate/NN",
    "she/PRP read/VBD an/DT interesting/JJ book/NN",
    "he/PRP bought/VBD an/DT expensive/JJ watch/NN",
    "an/DT angry/JJ customer/NN returned/VBD the/DT faulty/JJ toaster/NN",
    "tiny/JJ insects/NNS crawled/VBD across/IN the/DT dusty/JJ window/NN",
    "the/DT funny/JJ clown/NN made/VBD everyone/NN laugh/VB",
    "noisy/JJ trucks/NNS passed/VBD the/DT quiet/JJ village/NN",
    "several/JJ heavy/JJ boxes/NNS blocked/VBD the/DT narrow/JJ hallway/NN",
    "modern/JJ systems/NNS require/VBP careful/JJ testing/NN",
    "large/JJ models/NNS need/VBP fast/JJ accelerators/NNS",
    "the/DT compiler/NN optimizes/VBZ the/DT generated/VBN code/NN",
    "distributed/VBN training/NN uses/VBZ many/JJ devices/NNS",
    "a/DT cloudy/JJ sky/NN promised/VBD rainy/JJ weather/NN",
]]


_default_tagger: Optional[AveragedPerceptronTagger] = None


def default_tagger() -> AveragedPerceptronTagger:
    """Shared tagger trained once on the bundled seed corpus."""
    global _default_tagger
    if _default_tagger is None:
        _default_tagger = AveragedPerceptronTagger().train(SEED_CORPUS)
    return _default_tagger


def pos_tag(tokens: Sequence[str]) -> List[Tuple[str, str]]:
    """Tag a token list with the default tagger (PoStagger.java role)."""
    return default_tagger().tag(list(tokens))
