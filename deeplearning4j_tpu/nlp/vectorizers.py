"""Bag-of-words / TF-IDF vectorization + inverted index.

Reference parity: ``bagofwords/vectorizer/{TfidfVectorizer,
BagOfWordsVectorizer}.java`` over ``InvertedIndex``
(text/invertedindex/LuceneInvertedIndex.java — Lucene replaced by a plain
in-memory posting-list index; the capability is the contract, not Lucene).

Output matrices are jnp arrays [n_docs, V] ready for model input (the
reference feeds these to MultiLayerNetwork classifiers).
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache


class InvertedIndex:
    """word -> posting list of (doc_id, positions)."""

    def __init__(self):
        self.postings: Dict[str, List[Tuple[int, List[int]]]] = defaultdict(list)
        self.docs: List[List[str]] = []

    def add_document(self, tokens: Sequence[str]) -> int:
        doc_id = len(self.docs)
        self.docs.append(list(tokens))
        pos: Dict[str, List[int]] = defaultdict(list)
        for i, t in enumerate(tokens):
            pos[t].append(i)
        for t, ps in pos.items():
            self.postings[t].append((doc_id, ps))
        return doc_id

    def documents_containing(self, word: str) -> List[int]:
        return [d for d, _ in self.postings.get(word, [])]

    def doc_frequency(self, word: str) -> int:
        return len(self.postings.get(word, []))

    def num_docs(self) -> int:
        return len(self.docs)


class BagOfWordsVectorizer:
    """Count-vectorizer: fit builds vocab + index, transform -> [N, V]."""

    def __init__(self, tokenizer=None, min_word_frequency: int = 1):
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.cache = VocabCache()
        self.index = InvertedIndex()

    def fit(self, texts: Iterable[str]) -> "BagOfWordsVectorizer":
        for t in texts:
            toks = self.tokenizer(t)
            self.cache.add_document(toks)
            self.index.add_document(toks)
        self.cache.trim(self.min_word_frequency)
        return self

    def _doc_counts(self, text: str) -> np.ndarray:
        v = np.zeros(len(self.cache), np.float32)
        for t in self.tokenizer(text):
            i = self.cache.index_of(t)
            if i >= 0:
                v[i] += 1.0
        return v

    def transform(self, texts: Sequence[str]) -> jnp.ndarray:
        return jnp.asarray(np.stack([self._doc_counts(t) for t in texts]))

    def fit_transform(self, texts: Sequence[str]) -> jnp.ndarray:
        texts = list(texts)
        self.fit(texts)
        return self.transform(texts)


class TfidfVectorizer(BagOfWordsVectorizer):
    """tf-idf with idf = log(N / df) (TfidfVectorizer.java semantics)."""

    def idf(self) -> np.ndarray:
        n = max(1, self.cache.num_docs)
        out = np.zeros(len(self.cache), np.float32)
        for i, w in enumerate(self.cache.index):
            df = max(1, self.cache.doc_frequency(w))
            out[i] = math.log(n / df)
        return out

    def transform(self, texts: Sequence[str]) -> jnp.ndarray:
        counts = np.stack([self._doc_counts(t) for t in texts])
        tf = counts / np.maximum(counts.sum(axis=1, keepdims=True), 1.0)
        return jnp.asarray(tf * self.idf()[None, :])
