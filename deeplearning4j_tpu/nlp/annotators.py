"""Annotation pipeline — the UIMA annotator suite as a plain SPI.

Reference parity: ``text/annotator/{SentenceAnnotator, TokenizerAnnotator,
PoStagger, StemmerAnnotator}.java`` — composable CAS annotators that
progressively enrich a document (sentences → tokens → PoS tags → stems),
plus the tokenizer factories that consume them
(``text/tokenization/tokenizer/PosUimaTokenizer.java`` keeps only tokens
whose tag is allowed, ``preprocessor/EndingPreProcessor`` normalizes
endings).  UIMA's CAS machinery is replaced by a plain ``Annotation``
dataclass threaded through ``Annotator.process`` stages.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nlp.pos import AveragedPerceptronTagger, default_tagger
from deeplearning4j_tpu.nlp.stemmer import PorterStemmer
from deeplearning4j_tpu.nlp.text import word_punct_tokenize

_SENT_SPLIT = re.compile(r"(?<=[.!?])\s+")


@dataclasses.dataclass
class Annotation:
    """The document being enriched (the CAS role): each annotator fills
    the fields it is responsible for."""
    text: str
    sentences: Optional[List[str]] = None
    tokens: Optional[List[List[str]]] = None           # per sentence
    pos_tags: Optional[List[List[Tuple[str, str]]]] = None
    stems: Optional[List[List[str]]] = None


class Annotator:
    """process(annotation) -> annotation (CasAnnotator_ImplBase role)."""

    def process(self, ann: Annotation) -> Annotation:
        raise NotImplementedError


class SentenceAnnotator(Annotator):
    """Regex sentence segmentation (SentenceAnnotator.java role)."""

    def process(self, ann: Annotation) -> Annotation:
        ann.sentences = [s.strip() for s in _SENT_SPLIT.split(ann.text)
                         if s.strip()]
        return ann


class TokenizerAnnotator(Annotator):
    """Per-sentence tokenization (TokenizerAnnotator.java role)."""

    def process(self, ann: Annotation) -> Annotation:
        if ann.sentences is None:
            SentenceAnnotator().process(ann)
        ann.tokens = [word_punct_tokenize(s) for s in ann.sentences]
        return ann


class PoSAnnotator(Annotator):
    """Tag each sentence's tokens (PoStagger.java role)."""

    def __init__(self, tagger: Optional[AveragedPerceptronTagger] = None):
        self._tagger = tagger

    def process(self, ann: Annotation) -> Annotation:
        if ann.tokens is None:
            TokenizerAnnotator().process(ann)
        tagger = self._tagger or default_tagger()
        ann.pos_tags = [tagger.tag(toks) for toks in ann.tokens]
        return ann


class StemmerAnnotator(Annotator):
    """Porter-stem each token (StemmerAnnotator.java role)."""

    def __init__(self, stemmer: Optional[PorterStemmer] = None):
        self.stemmer = stemmer or PorterStemmer()

    def process(self, ann: Annotation) -> Annotation:
        if ann.tokens is None:
            TokenizerAnnotator().process(ann)
        ann.stems = [[self.stemmer.stem(t) for t in toks]
                     for toks in ann.tokens]
        return ann


class AnalysisPipeline:
    """Ordered annotator chain (the aggregate AnalysisEngine role).

    ``AnalysisPipeline.default()`` = sentences → tokens → PoS → stems,
    the reference's standard engine
    (UimaTokenizerFactory.defaultAnalysisEngine)."""

    def __init__(self, annotators: Sequence[Annotator]):
        self.annotators = list(annotators)

    @classmethod
    def default(cls) -> "AnalysisPipeline":
        return cls([SentenceAnnotator(), TokenizerAnnotator(),
                    PoSAnnotator(), StemmerAnnotator()])

    def process(self, text: str) -> Annotation:
        ann = Annotation(text=text)
        for a in self.annotators:
            a.process(ann)
        return ann


# ---------------------------------------------------------------------------
# Tokenizer factories consuming the annotators (SPI-compatible with
# nlp/text.py factories: create(text) -> tokens)
# ---------------------------------------------------------------------------

class PosFilterTokenizerFactory:
    """Keep only tokens whose PoS tag is in ``allowed`` — the others are
    dropped (PosUimaTokenizer.java behavior of masking disallowed
    tokens).  ``allowed`` uses PTB tags, prefix-matched so "NN" admits
    NN/NNS/NNP/NNPS."""

    def __init__(self, allowed: Sequence[str],
                 tagger: Optional[AveragedPerceptronTagger] = None,
                 lowercase: bool = True):
        self.allowed = tuple(allowed)
        self._tagger = tagger
        self.lowercase = lowercase

    def create(self, text: str) -> List[str]:
        tagger = self._tagger or default_tagger()
        toks = word_punct_tokenize(text)
        out = []
        for word, tag in tagger.tag(toks):
            if any(tag.startswith(a) for a in self.allowed):
                out.append(word.lower() if self.lowercase else word)
        return out

    __call__ = create


class StemmingTokenizerFactory:
    """Tokenize then Porter-stem (EndingPreProcessor/StemmerAnnotator as
    a tokenizer stage)."""

    def __init__(self, stemmer: Optional[PorterStemmer] = None,
                 lowercase: bool = True):
        self.stemmer = stemmer or PorterStemmer()
        self.lowercase = lowercase

    def create(self, text: str) -> List[str]:
        toks = word_punct_tokenize(text.lower() if self.lowercase
                                   else text)
        return [self.stemmer.stem(t) if t.isalpha() else t for t in toks]

    __call__ = create
