"""Word2Vec — skip-gram with hierarchical softmax + negative sampling.

Reference parity: ``models/word2vec/Word2Vec.java:57`` (fit:101,
buildVocab:257, trainSentence:298, skipGram:314) and the inner kernel
``InMemoryLookupTable.iterateSample:195-303`` (HS tree walk: dot -> sigmoid
-> g=(1-code-f)*alpha -> axpy into syn0/syn1; negative-sampling loop over a
unigram table; lr decay by words seen).

TPU-native redesign — the reference's kernel is per-word BLAS-1 axpy on
small vectors, the worst possible TPU shape (SURVEY.md "hard parts": sparse
embedding updates).  Here the whole minibatch of (center, context) pairs is
trained in ONE jitted program:

- gather the padded Huffman tables (vocab.encode_hs_tables) for the batch:
  codes/points [B, L] + mask;
- one [B, D] x [B, L, D] einsum computes every HS dot in the batch on the
  MXU; sigmoid, g, and the two rank-1 update families become dense batched
  ops;
- parameter updates are scatter-adds (``.at[].add``) into syn0/syn1 —
  XLA lowers these to efficient TPU scatters;
- negative sampling draws [B, K] negatives on device from the unigram
  table and trains syn1neg the same way;
- the LR schedule (linear decay by words seen, min 1e-4 floor —
  Word2Vec.java trainSentence) is computed per batch and passed as a
  scalar.

Pair generation (dynamic window shrink b = rand % window, skipGram:314)
stays on host — it is string work — and batches are processed in FIXED-size
padded chunks so the jitted steps compile exactly once.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (VocabCache, build_huffman,
                                          build_vocab, encode_hs_tables,
                                          unigram_table)
from deeplearning4j_tpu.nlp.word_vectors import WordVectors

log = logging.getLogger(__name__)

Array = jax.Array


@dataclasses.dataclass
class Word2VecConfig:
    vector_size: int = 100
    window: int = 5
    min_word_frequency: int = 1
    alpha: float = 0.025
    min_alpha: float = 1e-4
    negative: int = 0           # 0 => hierarchical softmax only
    use_hs: bool = True
    epochs: int = 1
    batch_size: int = 2048
    seed: int = 42
    table_size: int = 100_000


# -- jitted training steps --------------------------------------------------

def _hs_update(syn0: Array, syn1: Array, inputs: Array, codes: Array,
               points: Array, mask: Array, alpha: Array):
    """One batched HS update (plain function; jitted wrappers below).

    inputs [B] — rows of syn0 to train (context words);
    codes/points/mask [B, L] — the center words' Huffman paths.
    Padded pairs carry mask == 0 everywhere, so they contribute nothing."""
    l1 = syn0[inputs]                                   # [B, D]
    s1 = syn1[points]                                   # [B, L, D]
    f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", l1, s1))
    g = (1.0 - codes.astype(jnp.float32) - f) * alpha * mask
    neu1e = jnp.einsum("bl,bld->bd", g, s1)             # dL/dl1
    dsyn1 = g[:, :, None] * l1[:, None, :]              # [B, L, D]
    B, L, D = dsyn1.shape
    # Rows hit many times in one batch would receive a SUM of updates all
    # computed at stale values (the reference applies them sequentially);
    # normalize to the per-row MEAN so the batched step stays stable at any
    # batch-size/vocab ratio.
    flat_pts = points.reshape(B * L)
    cnt1 = jnp.zeros(syn1.shape[0]).at[flat_pts].add(
        mask.reshape(B * L), mode="drop")
    syn1 = syn1.at[flat_pts].add(
        dsyn1.reshape(B * L, D)
        / jnp.maximum(cnt1, 1.0)[flat_pts][:, None], mode="drop")
    row_mask = (jnp.sum(mask, axis=1) > 0).astype(jnp.float32)
    cnt0 = jnp.zeros(syn0.shape[0]).at[inputs].add(row_mask, mode="drop")
    syn0 = syn0.at[inputs].add(
        neu1e / jnp.maximum(cnt0, 1.0)[inputs][:, None], mode="drop")
    return syn0, syn1


def _neg_update(syn0: Array, syn1neg: Array, inputs: Array, targets: Array,
                negatives: Array, pair_mask: Array, alpha: Array):
    """Negative sampling: target center word label 1, K negatives label 0.
    ``pair_mask`` [B] zeroes padded pairs."""
    l1 = syn0[inputs]                                    # [B, D]
    rows = jnp.concatenate([targets[:, None], negatives], axis=1)  # [B,K+1]
    labels = jnp.concatenate(
        [jnp.ones_like(targets[:, None], jnp.float32),
         jnp.zeros(negatives.shape, jnp.float32)], axis=1)
    sn = syn1neg[rows]                                   # [B, K+1, D]
    f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", l1, sn))
    # mask accidental collisions negative == target
    valid = jnp.concatenate(
        [jnp.ones_like(targets[:, None], jnp.float32),
         (negatives != targets[:, None]).astype(jnp.float32)], axis=1)
    g = (labels - f) * alpha * valid * pair_mask[:, None]
    neu1e = jnp.einsum("bk,bkd->bd", g, sn)
    dneg = g[:, :, None] * l1[:, None, :]
    B, K1, D = dneg.shape
    # per-row mean normalization (see _hs_step)
    flat_rows = rows.reshape(B * K1)
    hit = (valid * pair_mask[:, None]).reshape(B * K1)
    cntn = jnp.zeros(syn1neg.shape[0]).at[flat_rows].add(hit, mode="drop")
    syn1neg = syn1neg.at[flat_rows].add(
        dneg.reshape(B * K1, D)
        / jnp.maximum(cntn, 1.0)[flat_rows][:, None], mode="drop")
    cnt0 = jnp.zeros(syn0.shape[0]).at[inputs].add(pair_mask, mode="drop")
    syn0 = syn0.at[inputs].add(
        neu1e / jnp.maximum(cnt0, 1.0)[inputs][:, None], mode="drop")
    return syn0, syn1neg


#: jitted single-objective steps (kept for paragraph_vectors and tests)
_hs_step = partial(jax.jit, donate_argnums=(0, 1))(_hs_update)
_neg_step = partial(jax.jit, donate_argnums=(0, 1))(_neg_update)


@partial(jax.jit, donate_argnums=(0, 1, 2),
         static_argnames=("use_hs", "negative"))
def _chunk_step(syn0: Array, syn1: Array, syn1neg: Array,
                centers: Array, contexts: Array, n_real: Array,
                codes_t: Array, points_t: Array, mask_t: Array,
                table: Array, key: Array, chunk_id: Array, alpha: Array,
                *, use_hs: bool, negative: int):
    """One FUSED training chunk: Huffman-path gathers, negative-sample
    draws, and both objective updates in a single compiled program.

    The eager per-chunk version dispatched ~8 separate device ops
    (gathers, randint, two jitted steps); under a tunneled TPU that made
    training dispatch-latency-bound.  All device-resident inputs
    (codes_t/points_t/mask_t/table) are passed by buffer each call —
    constant, so nothing re-uploads.  The pad mask is derived on-device
    from ``n_real`` (one scalar) instead of shipping a [B] float vector
    per chunk."""
    pmask = (jnp.arange(centers.shape[0]) < n_real).astype(jnp.float32)
    if use_hs:
        syn0, syn1 = _hs_update(
            syn0, syn1, contexts, codes_t[centers], points_t[centers],
            mask_t[centers] * pmask[:, None], alpha)
    if negative > 0:
        sub = jax.random.fold_in(key, chunk_id)
        draws = jax.random.randint(
            sub, (centers.shape[0], negative), 0, table.shape[0])
        syn0, syn1neg = _neg_update(
            syn0, syn1neg, contexts, centers, table[draws], pmask, alpha)
    return syn0, syn1, syn1neg


# -- host-side pair generation ---------------------------------------------

def sentence_pairs(idx: np.ndarray, window: int,
                   rng: np.random.RandomState
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """(center, context) pairs with per-position dynamic window shrink
    (skipGram:314's b = rand % window).  Fully vectorized: the previous
    python double loop topped out around 450k words/s on host, below the
    device kernel's rate — pair generation must not be the pipeline's
    bottleneck."""
    n = idx.shape[0]
    if n < 2:
        return (np.empty(0, np.int32),) * 2
    b = rng.randint(0, window, size=n)
    deltas = np.concatenate([np.arange(-window, 0),
                             np.arange(1, window + 1)])      # [2W]
    pos = np.arange(n)
    j = pos[:, None] + deltas[None, :]                        # [n, 2W]
    valid = ((np.abs(deltas)[None, :] <= (window - b)[:, None])
             & (j >= 0) & (j < n))
    ci, di = np.nonzero(valid)            # row-major: same order as the
    return (idx[ci].astype(np.int32),     # reference's per-pos j sweep
            idx[j[ci, di]].astype(np.int32))


class Word2Vec:
    """fit() -> WordVectors.  API parity with Word2Vec.java's builder usage:
    Word2Vec(sentences, Word2VecConfig(...), tokenizer)."""

    def __init__(self, sentences: Iterable[str],
                 config: Optional[Word2VecConfig] = None,
                 tokenizer=None,
                 cache: Optional[VocabCache] = None):
        self.config = config or Word2VecConfig()
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.sentences = sentences
        self.cache = cache
        self.syn0: Optional[Array] = None
        self.syn1: Optional[Array] = None
        self.syn1neg: Optional[Array] = None
        self._wv: Optional[WordVectors] = None

    # -- vocab (buildVocab:257 parity) -------------------------------------
    def build_vocab(self) -> VocabCache:
        if self.cache is None:
            self.cache = build_vocab(self.sentences, self.tokenizer,
                                     self.config.min_word_frequency)
        if self.config.use_hs:
            build_huffman(self.cache)
        return self.cache

    def _reset_weights(self) -> None:
        """syn0 ~ U(-0.5, 0.5)/dim (InMemoryLookupTable:98-104)."""
        cfg = self.config
        V, D = len(self.cache), cfg.vector_size
        key = jax.random.key(cfg.seed)
        self.syn0 = (jax.random.uniform(key, (V, D)) - 0.5) / D
        self.syn1 = jnp.zeros((V, D))
        if cfg.negative > 0:
            self.syn1neg = jnp.zeros((V, D))

    def fit(self, initial_weights=None) -> WordVectors:
        """Train; ``initial_weights=(syn0, syn1, syn1neg|None)`` resumes
        from given tables instead of re-initializing — the hook the
        distributed performers use to absorb the current global state
        (scaleout word2vec job parity)."""
        cfg = self.config
        if not cfg.use_hs and cfg.negative <= 0:
            raise ValueError(
                "no training objective: enable use_hs and/or negative > 0")
        self.build_vocab()
        if len(self.cache) == 0:
            raise ValueError("empty vocabulary")
        if initial_weights is not None:
            # jnp.array (copy), NOT asarray: the jitted steps donate their
            # table arguments, so a no-copy view of the caller's arrays
            # would be deleted by donation on the first step, corrupting
            # the state the caller warm-started from
            self.syn0, self.syn1, self.syn1neg = (
                jnp.array(initial_weights[0]),
                jnp.array(initial_weights[1]),
                None if initial_weights[2] is None
                else jnp.array(initial_weights[2]))
        else:
            self._reset_weights()
        codes_t, points_t, lengths_t = encode_hs_tables(self.cache)
        codes_t = jnp.asarray(codes_t)
        points_t = jnp.asarray(points_t)
        mask_t = jnp.asarray(
            (np.arange(codes_t.shape[1])[None, :] <
             np.asarray(lengths_t)[:, None]).astype(np.float32))
        table = jnp.asarray(unigram_table(self.cache, cfg.table_size))
        rng = np.random.RandomState(cfg.seed)
        nkey = jax.random.key(cfg.seed + 1)

        # pre-index sentences once
        indexed: List[np.ndarray] = []
        total_words = 0
        for sent in self.sentences:
            idx = [self.cache.index_of(t) for t in self.tokenizer(sent)]
            arr = np.asarray([i for i in idx if i >= 0], np.int32)
            if arr.size:
                indexed.append(arr)
                total_words += arr.size
        total = max(1, total_words * cfg.epochs)

        words_seen = 0
        chunk_id = 0
        B = cfg.batch_size
        pend_c = np.empty(0, np.int32)
        pend_x = np.empty(0, np.int32)
        if cfg.negative > 0 and self.syn1neg is None:
            raise ValueError(
                "negative sampling enabled but no syn1neg table: pass "
                "initial_weights with a syn1neg entry (or None weights to "
                "initialize fresh)")
        # syn1neg placeholder so the fused step has a donatable buffer
        # when negative sampling is OFF (that static branch never reads
        # it); rethreaded through every call because donation consumes it
        dummy_neg = jnp.zeros((1, 1), jnp.float32)

        def run_chunk(centers_np: np.ndarray, contexts_np: np.ndarray,
                      n_real: int) -> None:
            """Train one FIXED-size [B] chunk (padded with masked zeros)
            via the single fused jitted step."""
            nonlocal chunk_id, dummy_neg
            pad = B - n_real
            if pad:
                centers_np = np.concatenate(
                    [centers_np, np.zeros(pad, np.int32)])
                contexts_np = np.concatenate(
                    [contexts_np, np.zeros(pad, np.int32)])
            alpha = max(cfg.min_alpha,
                        cfg.alpha * (1.0 - words_seen / total))
            neg_tab = (self.syn1neg if self.syn1neg is not None
                       else dummy_neg)
            self.syn0, self.syn1, neg_tab = _chunk_step(
                self.syn0, self.syn1, neg_tab,
                jnp.asarray(centers_np), jnp.asarray(contexts_np),
                n_real, codes_t, points_t, mask_t, table,
                nkey, chunk_id, jnp.float32(alpha),
                use_hs=cfg.use_hs, negative=cfg.negative)
            if self.syn1neg is not None:
                self.syn1neg = neg_tab
            else:
                dummy_neg = neg_tab          # keep a live (undonated) handle
            chunk_id += 1

        def drain(final: bool) -> None:
            nonlocal pend_c, pend_x
            while pend_c.size >= B:
                run_chunk(pend_c[:B], pend_x[:B], B)
                pend_c, pend_x = pend_c[B:], pend_x[B:]
            if final and pend_c.size:
                run_chunk(pend_c, pend_x, pend_c.size)
                pend_c = np.empty(0, np.int32)
                pend_x = np.empty(0, np.int32)

        for _ in range(cfg.epochs):
            for arr in indexed:
                c, x = sentence_pairs(arr, cfg.window, rng)
                words_seen += arr.size
                if c.size == 0:
                    continue
                pend_c = np.concatenate([pend_c, c])
                pend_x = np.concatenate([pend_x, x])
                drain(final=False)
        drain(final=True)
        self._wv = WordVectors(self.cache, self.syn0)
        return self._wv

    # -- query passthrough --------------------------------------------------
    @property
    def word_vectors(self) -> WordVectors:
        if self._wv is None:
            raise RuntimeError("call fit() first")
        return self._wv

    def similarity(self, a: str, b: str) -> float:
        return self.word_vectors.similarity(a, b)

    def words_nearest(self, word: str, top_n: int = 10):
        return self.word_vectors.words_nearest(word, top_n)
