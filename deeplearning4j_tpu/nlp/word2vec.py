"""Word2Vec — skip-gram with hierarchical softmax + negative sampling.

Reference parity: ``models/word2vec/Word2Vec.java:57`` (fit:101,
buildVocab:257, trainSentence:298, skipGram:314) and the inner kernel
``InMemoryLookupTable.iterateSample:195-303`` (HS tree walk: dot -> sigmoid
-> g=(1-code-f)*alpha -> axpy into syn0/syn1; negative-sampling loop over a
unigram table; lr decay by words seen).

TPU-native redesign — the reference's kernel is per-word BLAS-1 axpy on
small vectors, the worst possible TPU shape (SURVEY.md "hard parts": sparse
embedding updates).  Here whole [B]-pair chunks train inside one jitted
scan:

- the padded Huffman tables (vocab.encode_hs_tables) are gathered per
  chunk: codes/points [B, L] + mask; negative sampling draws [B, K]
  negatives on device from the unigram table;
- on TPU with a VMEM-sized vocabulary, the chunk update runs through the
  fused Pallas kernel (ops/pallas_word2vec): tables stay resident in
  VMEM and every row gather/scatter is a one-hot matmul on the MXU;
- otherwise the XLA path batches the math as einsums + count-normalized
  scatter-adds into syn0/syn1/syn1neg;
- the LR schedule (linear decay by words seen, min 1e-4 floor —
  Word2Vec.java trainSentence) is an on-device per-chunk clock, and
  ``depth_buckets`` optionally partitions pairs by center Huffman depth
  so frequent (shallow) centers skip padded levels.

Pair generation stays on host but runs ONCE per corpus: full-window
candidate pairs are built in slabs that STREAM into epoch 0's async
device dispatches (cold-fit wall time = max(host, device)), then cached
for later epochs/fits; the dynamic window shrink (b = rand % window,
skipGram:314) is applied ON DEVICE as a per-epoch mask, and each slab
trains as one ``lax.scan`` dispatch over fixed-size [B] chunks
(see _scan_slab / run_pair_training).
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (VocabCache, build_huffman,
                                          build_vocab, encode_hs_tables,
                                          unigram_table)
from deeplearning4j_tpu.nlp.word_vectors import WordVectors

log = logging.getLogger(__name__)

Array = jax.Array


@dataclasses.dataclass
class Word2VecConfig:
    vector_size: int = 100
    window: int = 5
    min_word_frequency: int = 1
    alpha: float = 0.025
    min_alpha: float = 1e-4
    negative: int = 0           # 0 => hierarchical softmax only
    use_hs: bool = True
    epochs: int = 1
    batch_size: int = 2048
    seed: int = 42
    table_size: int = 100_000
    #: "auto" picks the VMEM-resident Pallas kernel on TPU when the
    #: tables fit (ops/pallas_word2vec), else the XLA gather/scatter
    #: path; "pallas"/"xla" force a path ("pallas" off-TPU runs the
    #: kernel through the interpreter — test harness only)
    kernel: str = "auto"
    #: >1 partitions pairs by center Huffman depth into that many
    #: buckets with per-bucket sliced HS tables — shallow (frequent)
    #: pairs skip the deep padded levels.  Exact semantics (masked
    #: levels contribute nothing); costs one jit variant per bucket.
    depth_buckets: int = 1
    #: "masked" (default): candidate pairs at the full window are built
    #: once and the per-epoch dynamic window shrink masks on device —
    #: zero host pair work after epoch 0, but ~45% of pair compute is
    #: masked waste at window 5.  "exact": the shrink is applied host-
    #: side per epoch (the reference's actual algorithm) so the device
    #: trains only real pairs — fresh streaming every epoch (overlapped
    #: with dispatch), no replay cache.  "device": NO host pair work at
    #: all — the int32 token stream uploads once (~4 bytes/word vs
    #: ~16 bytes/PAIR for host-built slabs) and each epoch is ONE
    #: dispatch that gathers contexts, applies sentence-boundary and
    #: window-shrink masks, and trains, all on device (see
    #: _scan_stream_epoch).
    pair_mode: str = "masked"


# -- jitted training steps --------------------------------------------------

def _hs_update(syn0: Array, syn1: Array, inputs: Array, codes: Array,
               points: Array, mask: Array, alpha: Array):
    """One batched HS update (the XLA gather/scatter path).

    inputs [B] — rows of syn0 to train (context words);
    codes/points/mask [B, L] — the center words' Huffman paths.
    Padded pairs carry mask == 0 everywhere, so they contribute nothing."""
    l1 = syn0[inputs]                                   # [B, D]
    s1 = syn1[points]                                   # [B, L, D]
    f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", l1, s1))
    g = (1.0 - codes.astype(jnp.float32) - f) * alpha * mask
    neu1e = jnp.einsum("bl,bld->bd", g, s1)             # dL/dl1
    dsyn1 = g[:, :, None] * l1[:, None, :]              # [B, L, D]
    B, L, D = dsyn1.shape
    # Rows hit many times in one batch would receive a SUM of updates all
    # computed at stale values (the reference applies them sequentially);
    # normalize to the per-row MEAN so the batched step stays stable at any
    # batch-size/vocab ratio.
    flat_pts = points.reshape(B * L)
    cnt1 = jnp.zeros(syn1.shape[0]).at[flat_pts].add(
        mask.reshape(B * L), mode="drop")
    syn1 = syn1.at[flat_pts].add(
        dsyn1.reshape(B * L, D)
        / jnp.maximum(cnt1, 1.0)[flat_pts][:, None], mode="drop")
    row_mask = (jnp.sum(mask, axis=1) > 0).astype(jnp.float32)
    cnt0 = jnp.zeros(syn0.shape[0]).at[inputs].add(row_mask, mode="drop")
    syn0 = syn0.at[inputs].add(
        neu1e / jnp.maximum(cnt0, 1.0)[inputs][:, None], mode="drop")
    return syn0, syn1


def _neg_update(syn0: Array, syn1neg: Array, inputs: Array, targets: Array,
                negatives: Array, pair_mask: Array, alpha: Array):
    """Negative sampling: target center word label 1, K negatives label 0.
    ``pair_mask`` [B] zeroes padded pairs."""
    l1 = syn0[inputs]                                    # [B, D]
    rows = jnp.concatenate([targets[:, None], negatives], axis=1)  # [B,K+1]
    labels = jnp.concatenate(
        [jnp.ones_like(targets[:, None], jnp.float32),
         jnp.zeros(negatives.shape, jnp.float32)], axis=1)
    sn = syn1neg[rows]                                   # [B, K+1, D]
    f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", l1, sn))
    # mask accidental collisions negative == target
    valid = jnp.concatenate(
        [jnp.ones_like(targets[:, None], jnp.float32),
         (negatives != targets[:, None]).astype(jnp.float32)], axis=1)
    g = (labels - f) * alpha * valid * pair_mask[:, None]
    neu1e = jnp.einsum("bk,bkd->bd", g, sn)
    dneg = g[:, :, None] * l1[:, None, :]
    B, K1, D = dneg.shape
    # per-row mean normalization (see _hs_update)
    flat_rows = rows.reshape(B * K1)
    hit = (valid * pair_mask[:, None]).reshape(B * K1)
    cntn = jnp.zeros(syn1neg.shape[0]).at[flat_rows].add(hit, mode="drop")
    syn1neg = syn1neg.at[flat_rows].add(
        dneg.reshape(B * K1, D)
        / jnp.maximum(cntn, 1.0)[flat_rows][:, None], mode="drop")
    cnt0 = jnp.zeros(syn0.shape[0]).at[inputs].add(pair_mask, mode="drop")
    syn0 = syn0.at[inputs].add(
        neu1e / jnp.maximum(cnt0, 1.0)[inputs][:, None], mode="drop")
    return syn0, syn1neg


@partial(jax.jit, donate_argnums=(0, 1, 2),
         static_argnames=("use_hs", "negative", "window", "window_mask",
                          "pallas_block", "pallas_interpret"))
def _scan_slab(syn0: Array, syn1: Array, syn1neg: Array,
               centers: Array, contexts: Array, cpos: Array, deltas: Array,
               offsets: Array, chunk_ids: Array, n_real: Array,
               codes_t: Array, points_t: Array, mask_t: Array,
               table: Array, key: Array, epoch: Array,
               epoch_frac: Array, alpha0: Array,
               min_alpha: Array,
               *, use_hs: bool, negative: int, window: int,
               window_mask: bool = True,
               pallas_block: int = 0, pallas_interpret: bool = False):
    """One dispatch per SLAB of chunks: ``lax.scan`` over [NC, B] pair
    chunks so the whole epoch costs one host->device round trip.

    The per-chunk fused step still paid one tunnel dispatch (~15-20 ms)
    per 16k pairs, which made training dispatch-latency-bound: 33 chunks
    of the bench corpus spent ~0.6 s in dispatch for ~0.05 s of compute.
    Scanning the chunks inside one jitted program removes that entirely.

    The reference's dynamic window shrink (skipGram:314's
    ``b = rand % window``: position ``pos`` trains only context offsets
    ``|delta| <= window - b``) moves ON DEVICE: per epoch a fresh
    ``b[n_positions]`` is drawn and pairs are masked by
    ``|delta| <= window - b[cpos]``.  That lets the host build the
    candidate pair list (all offsets up to ``window``) exactly ONCE per
    corpus instead of re-running pair generation every epoch.

    ``offsets`` [NC] = each chunk's first-pair word offset as a FRACTION
    of the total decay span (formed in float64 on host from exact int64
    word counts), and ``epoch_frac`` = total_words/total, so the linear
    lr decay by words seen (trainSentence:298) stays exact:
    ``alpha = max(min_alpha, alpha0 * (1 - (epoch*epoch_frac +
    offsets[c])))``.  ``n_real`` [NC] = real
    (unpadded) pairs per chunk; ``chunk_ids`` stay globally unique across
    slabs so negative draws never repeat within an epoch.
    """
    ekey = jax.random.fold_in(key, epoch)
    seed32 = jax.random.randint(
        jax.random.fold_in(ekey, 0), (), 0, 2 ** 31 - 1, jnp.uint32)
    B = centers.shape[1]
    col = jnp.arange(B)

    def b_draw(pos):
        # the one shrink-draw implementation, shared with the "device"
        # stream path (_scan_stream_epoch) so the two modes can never
        # diverge on shrink semantics
        return _hash_shrink(pos, seed32, window)

    def body(carry, inp):
        syn0, syn1, syn1neg = carry
        cen, ctx, pos, dlt, off, cid, nr = inp
        pmask = (col < nr).astype(jnp.float32)
        if window_mask:
            shrink = window - b_draw(pos)                    # [B]
            m = (jnp.abs(dlt) <= shrink).astype(jnp.float32) * pmask
        else:
            # pairs arrive pre-shrunk from the host (pair_mode="exact"):
            # every real pair trains
            m = pmask
        frac = epoch.astype(jnp.float32) * epoch_frac + off
        alpha = jnp.maximum(min_alpha, alpha0 * (1.0 - frac))
        if negative > 0:
            draws = jax.random.randint(
                jax.random.fold_in(ekey, 1 + cid),
                (B, negative), 0, table.shape[0])
            negs = table[draws]
        else:
            negs = jnp.zeros((B, 1), jnp.int32)
        if pallas_block > 0:
            from deeplearning4j_tpu.ops.pallas_word2vec import \
                fused_chunk_update
            if use_hs:
                codes_b, points_b, mask_b = (codes_t[cen], points_t[cen],
                                             mask_t[cen])
            else:      # no Huffman tables exist; (B, 1) dummies keep the
                B_ = cen.shape[0]          # kernel's BlockSpecs non-empty
                codes_b = jnp.zeros((B_, 1), jnp.float32)
                points_b = jnp.zeros((B_, 1), jnp.int32)
                mask_b = jnp.zeros((B_, 1), jnp.float32)
            syn0, syn1, syn1neg = fused_chunk_update(
                syn0, syn1, syn1neg, ctx, cen, codes_b,
                points_b, mask_b, negs, m, alpha,
                use_hs=use_hs, negative=negative,
                block=pallas_block, interpret=pallas_interpret)
        else:
            # both objectives read CHUNK-START tables and their syn0
            # deltas are summed — the exact semantics of the fused
            # Pallas kernel, so kernel="xla" and kernel="pallas" agree
            # to bf16 precision (tests/test_nlp.py asserts this)
            syn0_in = syn0
            if use_hs:
                hs0, syn1 = _hs_update(
                    syn0_in, syn1, ctx, codes_t[cen], points_t[cen],
                    mask_t[cen] * m[:, None], alpha)
                syn0 = syn0 + (hs0 - syn0_in)
            if negative > 0:
                ng0, syn1neg = _neg_update(
                    syn0_in, syn1neg, ctx, cen, negs, m, alpha)
                syn0 = syn0 + (ng0 - syn0_in)
        return (syn0, syn1, syn1neg), None

    (syn0, syn1, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg),
        (centers, contexts, cpos, deltas, offsets, chunk_ids, n_real))
    return syn0, syn1, syn1neg


def _hash_shrink(pos: Array, seed32: Array, window: int) -> Array:
    """Stateless per-(epoch, position) window-shrink draw: a Wang-style
    integer hash of the position — every pair sharing a center position
    sees the same b, no O(corpus) array is materialized per dispatch,
    and epochs re-draw via ``seed32``.  (The reference's own randomness
    is an LCG stream, Word2Vec.java skipGram:314.)"""
    h = pos.astype(jnp.uint32) * jnp.uint32(2654435761) + seed32
    h = (h ^ (h >> 16)) * jnp.uint32(2246822519)
    h = (h ^ (h >> 13)) * jnp.uint32(3266489917)
    return ((h ^ (h >> 16)) % jnp.uint32(window)).astype(jnp.int32)


def _stream_epoch_scan(syn0: Array, syn1: Array, syn1neg: Array,
                       tok: Array, n_stream: Array, chunk0: Array,
                       codes_t: Array, points_t: Array, mask_t: Array,
                       table: Array, key: Array, epoch: Array,
                       n_epochs_f: Array, alpha0: Array, min_alpha: Array,
                       *, use_hs: bool, negative: int, window: int,
                       pos_chunk: int, n_chunks: int,
                       pallas_block: int = 0,
                       pallas_interpret: bool = False):
    """Core of the pair_mode="device" epoch: scan ``n_chunks`` position
    chunks starting at chunk index ``chunk0`` (traced — the dp path
    gives each mesh shard its own stripe).  See _scan_stream_epoch."""
    ekey = jax.random.fold_in(key, epoch)
    seed32 = jax.random.randint(
        jax.random.fold_in(ekey, 0), (), 0, 2 ** 31 - 1, jnp.uint32)
    deltas = jnp.concatenate([jnp.arange(-window, 0),
                              jnp.arange(1, window + 1)]).astype(jnp.int32)
    W2 = 2 * window
    B = pos_chunk * W2
    n_pad = tok.shape[0]
    sid = jnp.cumsum((tok < 0).astype(jnp.int32))
    nf = n_stream.astype(jnp.float32)

    def body(carry, i):
        syn0, syn1, syn1neg = carry
        p0 = i * pos_chunk
        pos = p0 + jnp.arange(pos_chunk, dtype=jnp.int32)
        cen = tok[pos]
        j = pos[:, None] + deltas[None, :]                  # [P, 2W]
        jc = jnp.clip(j, 0, n_pad - 1)
        ctx = tok[jc]
        valid = ((j >= 0) & (cen[:, None] >= 0) & (ctx >= 0)
                 & (sid[jc] == sid[pos][:, None]))
        shrink = window - _hash_shrink(pos, seed32, window)
        m = valid & (jnp.abs(deltas)[None, :] <= shrink[:, None])
        pm = m.reshape(B).astype(jnp.float32)
        inputs = jnp.maximum(ctx, 0).reshape(B)
        cen_s = jnp.maximum(cen, 0)
        targets = jnp.broadcast_to(cen_s[:, None],
                                   (pos_chunk, W2)).reshape(B)
        frac = (epoch.astype(jnp.float32) * nf + p0) \
            / jnp.maximum(nf * n_epochs_f, 1.0)
        alpha = jnp.maximum(min_alpha, alpha0 * (1.0 - frac))
        if negative > 0:
            draws = jax.random.randint(
                jax.random.fold_in(ekey, 1 + i), (B, negative), 0,
                table.shape[0])
            negs = table[draws]
        else:
            negs = jnp.zeros((B, 1), jnp.int32)
        if use_hs:
            codes_b = jnp.broadcast_to(
                codes_t[cen_s][:, None, :],
                (pos_chunk, W2, codes_t.shape[1])).reshape(B, -1)
            points_b = jnp.broadcast_to(
                points_t[cen_s][:, None, :],
                (pos_chunk, W2, points_t.shape[1])).reshape(B, -1)
            mask_b = jnp.broadcast_to(
                mask_t[cen_s][:, None, :],
                (pos_chunk, W2, mask_t.shape[1])).reshape(B, -1)
        else:
            codes_b = jnp.zeros((B, 1), jnp.float32)
            points_b = jnp.zeros((B, 1), jnp.int32)
            mask_b = jnp.zeros((B, 1), jnp.float32)
        if pallas_block > 0:
            from deeplearning4j_tpu.ops.pallas_word2vec import \
                fused_chunk_update
            syn0, syn1, syn1neg = fused_chunk_update(
                syn0, syn1, syn1neg, inputs, targets, codes_b,
                points_b, mask_b, negs, pm, alpha,
                use_hs=use_hs, negative=negative,
                block=pallas_block, interpret=pallas_interpret)
        else:
            syn0_in = syn0
            if use_hs:
                hs0, syn1 = _hs_update(
                    syn0_in, syn1, inputs, codes_b,
                    points_b, mask_b * pm[:, None], alpha)
                syn0 = syn0 + (hs0 - syn0_in)
            if negative > 0:
                ng0, syn1neg = _neg_update(
                    syn0_in, syn1neg, inputs, targets, negs, pm, alpha)
                syn0 = syn0 + (ng0 - syn0_in)
        return (syn0, syn1, syn1neg), None

    (syn0, syn1, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1, syn1neg),
        chunk0 + jnp.arange(n_chunks, dtype=jnp.int32))
    return syn0, syn1, syn1neg


@partial(jax.jit, donate_argnums=(0, 1, 2),
         static_argnames=("use_hs", "negative", "window", "pos_chunk",
                          "n_chunks", "pallas_block", "pallas_interpret"))
def _scan_stream_epoch(syn0: Array, syn1: Array, syn1neg: Array,
                       tok: Array, n_stream: Array,
                       codes_t: Array, points_t: Array, mask_t: Array,
                       table: Array, key: Array, epoch: Array,
                       n_epochs_f: Array, alpha0: Array, min_alpha: Array,
                       *, use_hs: bool, negative: int, window: int,
                       pos_chunk: int, n_chunks: int,
                       pallas_block: int = 0,
                       pallas_interpret: bool = False):
    """One dispatch per EPOCH with ZERO host pair work (pair_mode
    ="device"): ``tok`` is the int32 token stream with ``-1`` sentence
    separators, uploaded ONCE per corpus (~4 bytes/word, vs ~16 bytes
    per PAIR for host-built slabs riding the tunnel every fit).  Each
    scan step takes a [pos_chunk] window of positions and builds its
    pairs on device: contexts are ``tok`` gathers at the 2W signed
    offsets, sentence boundaries mask via a separator-count (cumsum)
    sentence id, and the reference's dynamic window shrink
    (skipGram:314) is the usual stateless hash mask.  The lr clock is
    the stream position (= words seen, separators included — within
    ~n_sentences/n_words of the reference's per-sentence clock)."""
    return _stream_epoch_scan(
        syn0, syn1, syn1neg, tok, n_stream, jnp.int32(0), codes_t,
        points_t, mask_t, table, key, epoch, n_epochs_f, alpha0,
        min_alpha, use_hs=use_hs, negative=negative, window=window,
        pos_chunk=pos_chunk, n_chunks=n_chunks,
        pallas_block=pallas_block, pallas_interpret=pallas_interpret)


def make_dp_stream_epoch(mesh, axis: str, n_shards: int, per: int, *,
                         use_hs: bool, negative: int, window: int,
                         pos_chunk: int, pallas_block: int,
                         pallas_interpret: bool, average: bool = True):
    """Data-parallel device-mode epoch over a mesh ``axis``: each shard
    trains its contiguous stripe of ``per`` position chunks on its OWN
    table replica, then replicas are parameter-AVERAGED (pmean) — the
    reference's Spark each-iteration averaging mode
    (SparkDl4jMultiLayer fitDataSet / ParameterAveragingTrainer role),
    per EPOCH at chip scale.  Returns a jitted epoch function with the
    _scan_stream_epoch signature.

    ``average=False`` skips the pmean (shard-local updates; replicas
    DIVERGE) — only for measuring the collective's share of epoch time
    (bench.py's w2v-dp row), never for training."""
    from deeplearning4j_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    rep = P()

    def shard_fn(syn0, syn1, syn1neg, tok, n_stream, codes_t, points_t,
                 mask_t, table, key, epoch, n_epochs_f, alpha0,
                 min_alpha):
        c0 = jax.lax.axis_index(axis) * per
        syn0, syn1, syn1neg = _stream_epoch_scan(
            syn0, syn1, syn1neg, tok, n_stream, c0, codes_t, points_t,
            mask_t, table, key, epoch, n_epochs_f, alpha0, min_alpha,
            use_hs=use_hs, negative=negative, window=window,
            pos_chunk=pos_chunk, n_chunks=per,
            pallas_block=pallas_block, pallas_interpret=pallas_interpret)
        if not average:
            return syn0, syn1, syn1neg
        pm = lambda x: jax.lax.pmean(x, axis)
        return pm(syn0), pm(syn1), pm(syn1neg)

    f = shard_map(shard_fn, mesh=mesh, in_specs=(rep,) * 14,
                  out_specs=(rep,) * 3, check_vma=False)
    return jax.jit(f, donate_argnums=(0, 1, 2))


def run_stream_training(syn0, syn1, syn1neg, indexed, *,
                        vocab_size, dim, epochs, codes_t, points_t,
                        mask_t, table, window, alpha, min_alpha, use_hs,
                        negative, batch_size, kernel, seed,
                        stream_cache=None, mesh=None, data_axis="data"):
    """pair_mode="device" engine: upload the separator-delimited token
    stream once, then one ``_scan_stream_epoch`` dispatch per epoch.
    With ``mesh`` (and >1 devices on ``data_axis``), each device trains
    a stripe of the stream on its own replica and replicas are
    parameter-averaged per epoch (``make_dp_stream_epoch``).
    Returns (syn0, syn1, syn1neg, stream_cache, kernel_used)."""
    from deeplearning4j_tpu.ops.kernel_select import (kernel_name,
                                                      resolve_kernel)
    from deeplearning4j_tpu.ops.pallas_word2vec import (choose_block,
                                                        probe_compile)
    W2 = 2 * window
    # pos_chunk: pairs-per-chunk ~= batch_size, with B = pos_chunk*2W a
    # multiple of every kernel block size (512 | lcm constraint below)
    import math
    step = 512 // math.gcd(W2, 512)
    pos_chunk = max(step, (batch_size // W2) // step * step)
    B = pos_chunk * W2

    platform = jax.devices()[0].platform
    pallas_block, pallas_interpret = resolve_kernel(
        kernel,
        choose_block(vocab_size, dim, negative, B,
                     interpret=platform != "tpu"),
        f"word2vec vocab {vocab_size} x dim {dim} (batch {B})")
    if (pallas_block and not pallas_interpret and kernel == "auto"
            and not probe_compile(pallas_block, use_hs, negative,
                                  vocab_size, dim,
                                  int(codes_t.shape[1]) if use_hs else 1)):
        pallas_block = 0
    # Honor the configured batch_size at the finest granularity the
    # selected kernel supports.  The 512-lcm floor above is only the
    # fused kernel's largest-BlockSpec preference — applied
    # unconditionally it rounded every small batch_size up to 256
    # POSITIONS (~1536 pair slots) per sequential update, which
    # collapsed convergence on small corpora to a handful of
    # mean-normalized steps per epoch.  That granularity cliff (not a
    # numeric issue) was the root cause of the device-mode quality
    # failures ROADMAP item 3 tracked.
    fine = max(8, (batch_size // W2) // 8 * 8)

    def _block_ok(blk):
        # a re-picked block must clear the same compile-probe gate the
        # original one did (block size changes the kernel signature);
        # on probe failure we keep the already-validated coarse block
        return (pallas_interpret or kernel != "auto"
                or probe_compile(blk, use_hs, negative, vocab_size, dim,
                                 int(codes_t.shape[1]) if use_hs else 1))

    if pallas_block == 0:
        pos_chunk = fine                    # XLA path: any chunk shape
    elif pos_chunk > fine:
        blk2 = choose_block(vocab_size, dim, negative, fine * W2,
                            interpret=platform != "tpu")
        if blk2 and fine * W2 % blk2 == 0 and _block_ok(blk2):
            pos_chunk, pallas_block = fine, blk2
        else:
            # compiled kernel grids need B % block == 0: fall back to
            # the finest 128-lane-aligned chunk covering batch_size
            step128 = 128 // math.gcd(W2, 128)
            cand = max(step128, (batch_size // W2) // step128 * step128)
            blk3 = choose_block(vocab_size, dim, negative, cand * W2,
                                interpret=platform != "tpu")
            if (blk3 and cand * W2 % blk3 == 0 and cand < pos_chunk
                    and _block_ok(blk3)):
                pos_chunk, pallas_block = cand, blk3
    B = pos_chunk * W2
    kernel_used = kernel_name(pallas_block, pallas_interpret)

    n_shards = int(mesh.shape[data_axis]) if mesh is not None else 1
    if stream_cache is None:
        # separator-delimited stream: sentence ids come from a cumsum on
        # device, so only ONE int32 array rides the link.  NC is padded
        # only to a multiple of n_shards (1 when unsharded) — a previous
        # next-power-of-two pad made up to ~2x of every epoch's scan
        # steps process fully-masked -1 filler.
        n_stream = int(sum(a.size + 1 for a in indexed))
        NC = -(-n_stream // pos_chunk)
        NC = max(n_shards, -(-NC // n_shards) * n_shards)
        stream = np.full(NC * pos_chunk, -1, np.int32)
        off = 0
        for a in indexed:
            stream[off:off + a.size] = a
            off += a.size + 1
        stream_cache = {"tok": jnp.asarray(stream), "n_stream": n_stream,
                        "n_chunks": NC, "pos_chunk": pos_chunk}
    if stream_cache["pos_chunk"] != pos_chunk:
        raise ValueError("stream cache built for a different batch "
                         "size; refit with a fresh instance")
    nkey = jax.random.key(seed + 1)
    had_neg = syn1neg is not None
    if not had_neg:
        syn1neg = jnp.zeros((1, 1), jnp.float32)
    NC = stream_cache["n_chunks"]
    if n_shards > 1 and NC % n_shards != 0:
        # Silently ignoring the mesh would train single-device while the
        # caller believes it is data-parallel; surface the mismatch.
        raise ValueError(
            f"stream cache has {NC} chunks, not divisible by the mesh's "
            f"{n_shards} '{data_axis}' shards; rebuild the cache (fit a "
            f"fresh instance with mesh=) instead of reusing this one")
    if n_shards > 1:
        # dp epoch fns are keyed by mesh layout: reusing a jitted
        # shard_map closed over a dead/different mesh trains on the
        # wrong layout or crashes (ADVICE r4, medium)
        mesh_key = (tuple(d.id for d in mesh.devices.flat), data_axis,
                    n_shards, NC // n_shards)
        dp_fns = stream_cache.setdefault("dp_epoch_fns", {})
        epoch_fn = dp_fns.get(mesh_key)
        if epoch_fn is None:
            epoch_fn = make_dp_stream_epoch(
                mesh, data_axis, n_shards, NC // n_shards,
                use_hs=use_hs, negative=negative, window=window,
                pos_chunk=pos_chunk, pallas_block=pallas_block,
                pallas_interpret=pallas_interpret)
            dp_fns[mesh_key] = epoch_fn
        for epoch in range(epochs):
            syn0, syn1, syn1neg = epoch_fn(
                syn0, syn1, syn1neg, stream_cache["tok"],
                jnp.int32(stream_cache["n_stream"]), codes_t, points_t,
                mask_t, table, nkey, jnp.int32(epoch),
                jnp.float32(max(epochs, 1)), jnp.float32(alpha),
                jnp.float32(min_alpha))
    else:
        for epoch in range(epochs):
            syn0, syn1, syn1neg = _scan_stream_epoch(
                syn0, syn1, syn1neg, stream_cache["tok"],
                jnp.int32(stream_cache["n_stream"]), codes_t, points_t,
                mask_t, table, nkey, jnp.int32(epoch),
                jnp.float32(max(epochs, 1)), jnp.float32(alpha),
                jnp.float32(min_alpha), use_hs=use_hs, negative=negative,
                window=window, pos_chunk=pos_chunk, n_chunks=NC,
                pallas_block=pallas_block,
                pallas_interpret=pallas_interpret)
    return (syn0, syn1, syn1neg if had_neg else None, stream_cache,
            kernel_used)


# -- host-side pair generation ---------------------------------------------

def sentence_pairs(idx: np.ndarray, window: int,
                   rng: np.random.RandomState
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """(center, context) pairs with per-position dynamic window shrink
    (skipGram:314's b = rand % window).  Fully vectorized: the previous
    python double loop topped out around 450k words/s on host, below the
    device kernel's rate — pair generation must not be the pipeline's
    bottleneck."""
    n = idx.shape[0]
    if n < 2:
        return (np.empty(0, np.int32),) * 2
    b = rng.randint(0, window, size=n)
    deltas = np.concatenate([np.arange(-window, 0),
                             np.arange(1, window + 1)])      # [2W]
    pos = np.arange(n)
    j = pos[:, None] + deltas[None, :]                        # [n, 2W]
    valid = ((np.abs(deltas)[None, :] <= (window - b)[:, None])
             & (j >= 0) & (j < n))
    ci, di = np.nonzero(valid)            # row-major: same order as the
    return (idx[ci].astype(np.int32),     # reference's per-pos j sweep
            idx[j[ci, di]].astype(np.int32))


def corpus_pairs(indexed: Sequence[np.ndarray], window: int,
                 slab: int = 1 << 20
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray,
                            np.ndarray]:
    """CANDIDATE (center, context) pairs for the whole corpus at the FULL
    window — built once; the per-epoch dynamic window shrink is applied
    on-device as a mask (see _scan_slab).

    Returns (centers, contexts, center_pos, delta, word_offset) where
    ``center_pos`` indexes the concatenated token stream (the key for the
    on-device ``b`` draw), ``delta`` is the signed context offset, and
    ``word_offset`` is the words-seen count at the pair's sentence — the
    lr-decay clock.  Vectorized over ``slab``-position blocks so the
    [n, 2W] candidate matrix never exceeds ~40 MB however large the
    corpus."""
    outs = list(_corpus_pair_blocks(indexed, window, slab))
    if not outs:
        return (np.empty(0, np.int32),) * 4 + (np.empty(0, np.int64),)
    return tuple(np.concatenate([o[k] for o in outs])        # type: ignore
                 for k in range(5))


def _corpus_pair_blocks(indexed: Sequence[np.ndarray], window: int,
                        slab: int = 1 << 20, shrink_rng=None):
    """Yield candidate-pair 5-tuples per position slab (corpus_pairs'
    loop body, exposed for the streaming trainer).

    ``shrink_rng`` applies the reference's dynamic window shrink HOST-side
    (skipGram:314's ``b = rand % window``: position trains offsets
    ``|delta| <= window - b``): only surviving pairs are emitted, so the
    device trains ~(window+1)/(2*window) as many pairs instead of masking
    them out on-chip (pair_mode="exact")."""
    if not indexed:
        return
    tok = np.concatenate(indexed).astype(np.int32)
    lens = np.asarray([a.size for a in indexed])
    sid = np.repeat(np.arange(len(indexed)), lens)
    # words seen AFTER each sentence is processed (trainSentence:298
    # increments per sentence) — broadcast to its positions.  Kept int64
    # through prep: float32 loses integer exactness past 2^24 (~16.7M)
    # corpus words, which would drift the linear lr decay; the offset only
    # becomes float when the alpha RATIO is formed (in float64, prep_slab)
    seen_after = np.cumsum(lens, dtype=np.int64)
    word_off = seen_after[sid] - lens[sid]
    n = tok.size
    deltas = np.concatenate([np.arange(-window, 0),
                             np.arange(1, window + 1)]).astype(np.int32)
    for s0 in range(0, n, slab):
        s1 = min(n, s0 + slab)
        pos = np.arange(s0, s1, dtype=np.int32)
        j = pos[:, None] + deltas[None, :]                   # [S, 2W] i32
        jc = np.clip(j, 0, n - 1)
        valid = (j >= 0) & (j < n) & (sid[jc] == sid[s0:s1, None])
        if shrink_rng is not None:
            b = shrink_rng.randint(0, window, size=s1 - s0)
            valid &= np.abs(deltas)[None, :] <= (window - b)[:, None]
        ci, di = np.nonzero(valid)
        p = pos[ci]
        yield (tok[p], tok[j[ci, di]], p.astype(np.int32),
               deltas[di], word_off[p])


def corpus_pairs_slabs(indexed: Sequence[np.ndarray], window: int,
                       pairs_per_slab: int, shrink_rng=None):
    """Yield ``corpus_pairs``-shaped blocks of ~``pairs_per_slab`` pairs.
    Streaming form: the scanned trainer dispatches each block (async)
    before the host builds the next, so cold-fit wall time is
    max(host pair generation, device training), not their sum."""
    bufs: List[Tuple[np.ndarray, ...]] = []
    n = 0
    # position-slab sized so each block stays well under the pair budget
    # (a position contributes up to 2*window candidate pairs)
    pos_slab = max(1024, pairs_per_slab // (8 * window))
    for arr_slab in _corpus_pair_blocks(indexed, window, pos_slab,
                                        shrink_rng):
        bufs.append(arr_slab)
        n += arr_slab[0].size
        while n >= pairs_per_slab:
            # emit EXACTLY pairs_per_slab (uniform [NC, B] shapes ->
            # one jit variant for all full slabs); remainder carries over
            cat = tuple(np.concatenate([b[k] for b in bufs])
                        for k in range(5))
            yield tuple(a[:pairs_per_slab] for a in cat)
            bufs = [tuple(a[pairs_per_slab:] for a in cat)]
            n -= pairs_per_slab
    if n:
        yield tuple(np.concatenate([b[k] for b in bufs]) for k in range(5))


#: pairs per dispatch — bounds device buffers and jit-cache variants
PAIRS_PER_SLAB = 1 << 22
#: total pairs kept device-resident across epochs (beyond: host numpy,
#: re-uploaded once per slab per epoch — bounded HBM for any corpus)
RESIDENT_PAIR_CAP = 32 * (1 << 20)


def run_pair_training(syn0, syn1, syn1neg,
                      pairs=None, *,
                      vocab_size, dim, epochs,
                      total_words, codes_t, points_t,
                      mask_t, table, window,
                      alpha, min_alpha, use_hs,
                      negative, batch_size, kernel,
                      seed, dev_cache=None, pairs_iter=None,
                      pairs_iter_factory=None, window_mask=True,
                      hs_lengths=None, hs_weights=None, depth_buckets=1):
    """The shared scanned-epoch training engine (Word2Vec AND
    ParagraphVectors fit through here).

    Input pairs (centers, contexts, center_pos, delta, word_offset — the
    ``corpus_pairs`` layout, plus any always-train pairs encoded with
    delta = 0) arrive either materialized (``pairs``) or as a STREAM of
    blocks (``pairs_iter``, e.g. ``corpus_pairs_slabs``).  In streaming
    form epoch 0 interleaves host pair generation with async device
    dispatch: cold-fit wall time is max(host, device), not their sum.

    ``pairs_iter_factory(epoch) -> blocks`` streams a FRESH pair set
    every epoch (pair_mode="exact": the host applies the window shrink,
    so pass ``window_mask=False`` — no on-device masking, ~45% fewer
    trained pairs at window 5); no replay cache is kept in this mode.

    Handles kernel validation/selection (VMEM-resident Pallas kernel on
    TPU when the tables fit; ``kernel='pallas'`` raises when they
    don't), per-slab chunking with the device-residency cap, and
    globally-unique chunk ids (negative-sample draws never repeat within
    an epoch).  Returns ``(syn0, syn1, syn1neg, dev_cache,
    kernel_used)`` — thread
    ``dev_cache`` back in to replay the prepared slabs on later fits."""
    B = batch_size
    neg_tab = (syn1neg if syn1neg is not None
               else jnp.zeros((1, 1), jnp.float32))

    # kernel selection: VMEM-resident Pallas kernel on TPU whenever the
    # tables fit (2.7x the XLA path on v5e at bench shapes);
    # kernel="pallas" forces it (via the interpreter off-TPU: tests)
    from deeplearning4j_tpu.ops.kernel_select import resolve_kernel
    from deeplearning4j_tpu.ops.pallas_word2vec import (choose_block,
                                                        probe_compile)
    platform = jax.devices()[0].platform
    pallas_block, pallas_interpret = resolve_kernel(
        kernel,
        choose_block(vocab_size, dim, negative, B,
                     interpret=platform != "tpu"),
        f"word2vec vocab {vocab_size} x dim {dim} (batch {B})")
    if (pallas_block and not pallas_interpret and kernel == "auto"
            and not probe_compile(pallas_block, use_hs, negative,
                                  vocab_size, dim,
                                  int(codes_t.shape[1]) if use_hs else 1)):
        pallas_block = 0        # Mosaic rejected: degrade to XLA
    # resolved dispatch — returned so benches record the Mosaic
    # accept/reject verdict per fit
    from deeplearning4j_tpu.ops.kernel_select import kernel_name
    kernel_used = kernel_name(pallas_block, pallas_interpret)

    if epochs <= 0:
        return syn0, syn1, syn1neg, dev_cache, kernel_used
    total = max(1, total_words * epochs)
    nkey = jax.random.key(seed + 1)

    # -- depth buckets (opt-in): the HS level loop is static in L, so
    # every pair pays the vocabulary's MAX Huffman depth even though
    # zipf makes most centers shallow.  Bucketing pairs by center depth
    # and slicing the HS tables per bucket trains shallow pairs with a
    # short loop — exactly (levels beyond a pair's depth are masked
    # zeros, so dropping them changes nothing but chunk grouping).
    n_buckets = max(1, depth_buckets) if (use_hs and hs_lengths is not None
                                          ) else 1
    if n_buckets > 1:
        hs_len = np.asarray(hs_lengths)
        full_l = int(codes_t.shape[1])
        # pair-weighted boundaries: word count is the center-frequency
        # proxy (pairs per center scale with its occurrences)
        w = (np.asarray(hs_weights, np.float64)
             if hs_weights is not None else np.ones_like(hs_len, float))
        order = np.argsort(hs_len)
        cw = np.cumsum(w[order])
        cw /= cw[-1]
        qs = [hs_len[order][np.searchsorted(cw, i / n_buckets)]
              for i in range(1, n_buckets)]
        bounds = sorted(set(int(q) for q in qs) | {full_l})
        bounds = [b for b in bounds if b > 0]
        bucket_l = bounds                       # max depth per bucket
        tables = [(codes_t, points_t, mask_t) if lb == full_l else
                  (codes_t[:, :lb], points_t[:, :lb], mask_t[:, :lb])
                  for lb in bucket_l]

        def bucket_of(cen):
            return np.searchsorted(np.asarray(bucket_l),
                                   hs_len[cen], side="left")
    else:
        bucket_l = [int(codes_t.shape[1])]
        tables = [(codes_t, points_t, mask_t)]
        bucket_of = None

    def prep_slab(blk, resident):
        cen, ctx, cpos, dlt, woff = blk
        P = cen.size
        NC = -(-P // B)
        pad = NC * B - P

        def ch(a, fill=0):
            if pad:
                a = np.concatenate([a, np.full(pad, fill, a.dtype)])
            a = a.reshape(NC, B)
            return jnp.asarray(a) if resident else a

        n_real = np.full(NC, B, np.int32)
        n_real[-1] = P - (NC - 1) * B
        # per-chunk lr clock = word offset at the chunk's first pair,
        # converted to a FRACTION of the total decay span in float64 on
        # host (int64 offsets stay exact however large the corpus)
        off_frac = (woff[::B].astype(np.float64) / float(total)
                    ).astype(np.float32)
        return (ch(cen), ch(ctx), ch(cpos), ch(dlt),
                jnp.asarray(off_frac), jnp.asarray(n_real))

    def dispatch(slab, cid0, bidx, epoch, state):
        syn0, syn1, neg_tab = state
        cen_d, ctx_d, cpos_d, dlt_d, woff_d, n_real = slab
        NC = n_real.shape[0]
        cids = jnp.arange(cid0, cid0 + NC, dtype=jnp.int32)
        c_t, p_t, m_t = tables[bidx]
        return _scan_slab(
            syn0, syn1, neg_tab, cen_d, ctx_d, cpos_d, dlt_d,
            woff_d, cids, n_real, c_t, p_t, m_t, table,
            nkey, jnp.int32(epoch), jnp.float32(total_words / total),
            jnp.float32(alpha), jnp.float32(min_alpha),
            use_hs=use_hs, negative=negative, window=window,
            window_mask=window_mask,
            pallas_block=pallas_block, pallas_interpret=pallas_interpret)

    state = (syn0, syn1, neg_tab)

    def stream(blocks, epoch, slabs):
        """Stream pair blocks through prep+dispatch for one epoch — host
        preps slab k+1 while the device (async dispatch) trains slab k.
        ``slabs`` (a list) caches the prepared slabs for replay; None
        streams without caching (fresh pairs every epoch)."""
        nonlocal state
        seen_pairs = 0
        cid0 = 0
        # per-bucket carry buffers so every bucket emits uniform
        # PAIRS_PER_SLAB slabs (one jit variant per bucket)
        bufs: List[List[Tuple[np.ndarray, ...]]] = \
            [[] for _ in range(len(bucket_l))]
        buf_n = [0] * len(bucket_l)

        def record(part, bidx):
            """Prep, dispatch and (optionally) cache one slab — the single
            accounting path for both the direct and bucketed branches."""
            nonlocal seen_pairs, cid0, state
            resident = (slabs is not None
                        and seen_pairs + part[0].size <= RESIDENT_PAIR_CAP)
            slab = prep_slab(part, resident)
            state = dispatch(slab, cid0, bidx, epoch, state)
            if slabs is not None:
                slabs.append((slab, cid0, bidx))
            seen_pairs += part[0].size
            cid0 += slab[5].shape[0]

        def emit(bidx, blk_b, final):
            # NOTE: bucketed mode re-buffers blocks that corpus_pairs_slabs
            # already sized — one extra host memcpy per slab, accepted for
            # the opt-in path (it overlaps the async device dispatches)
            bufs[bidx].append(blk_b)
            buf_n[bidx] += blk_b[0].size
            while buf_n[bidx] >= PAIRS_PER_SLAB or (final and buf_n[bidx]):
                cat = tuple(np.concatenate([b[k] for b in bufs[bidx]])
                            for k in range(5))
                take = min(PAIRS_PER_SLAB, cat[0].size)
                bufs[bidx] = [tuple(a[take:] for a in cat)]
                buf_n[bidx] -= take
                record(tuple(a[:take] for a in cat), bidx)
                if final and buf_n[bidx] == 0:
                    break

        empty = tuple(np.empty(0, np.int32) for _ in range(4)) + (
            np.empty(0, np.int64),)
        for blk in blocks:
            if blk[0].size == 0:
                continue
            if len(bucket_l) == 1:
                # already exact-size slabs: dispatch directly, no rebuffer
                record(blk, 0)
            else:
                which = bucket_of(blk[0])
                for bidx in range(len(bucket_l)):
                    sel = which == bidx
                    if sel.any():
                        emit(bidx, tuple(a[sel] for a in blk),
                             final=False)
        for bidx in range(len(bucket_l)):
            if buf_n[bidx]:
                emit(bidx, empty, final=True)

    if pairs_iter_factory is not None:
        # pair_mode="exact": the pair set changes per epoch (host-side
        # window shrink, like the reference's per-epoch b draws), so
        # every epoch streams fresh — no replay cache
        for epoch in range(epochs):
            stream(pairs_iter_factory(epoch), epoch, None)
        syn0, syn1, neg_tab = state
        return (syn0, syn1,
                neg_tab if syn1neg is not None else None, None,
                kernel_used)

    if dev_cache is not None and dev_cache["bucket_l"] != bucket_l:
        raise ValueError(
            f"cached pair slabs were built for depth buckets "
            f"{dev_cache['bucket_l']} but the config now implies "
            f"{bucket_l}; refit with a fresh instance (or keep "
            f"depth_buckets stable across fits)")
    if dev_cache is None:
        if pairs_iter is None:
            if pairs is None:
                raise ValueError("need pairs, pairs_iter or dev_cache")

            def _slices():
                P = pairs[0].size
                for lo in range(0, P, PAIRS_PER_SLAB):
                    yield tuple(a[lo:lo + PAIRS_PER_SLAB] for a in pairs)

            pairs_iter = _slices()
        # epoch 0 streams; prepared slabs are cached for replay
        dev_cache = {"bucket_l": bucket_l, "slabs": []}
        stream(pairs_iter, 0, dev_cache["slabs"])
        first_epoch = 1
    else:
        first_epoch = 0
    for epoch in range(first_epoch, epochs):
        for slab, cid0, bidx in dev_cache["slabs"]:
            state = dispatch(slab, cid0, bidx, epoch, state)
    syn0, syn1, neg_tab = state
    return (syn0, syn1,
            neg_tab if syn1neg is not None else None, dev_cache,
            kernel_used)


def prepare_train_tables(cache, table_size: int):
    """Device-ready training tables from a built vocab: (codes_t,
    points_t, mask_t, unigram table, hs code lengths) — the Huffman
    hierarchical-softmax encoding plus the negative-sampling
    distribution.  Shared by ``Word2Vec.fit`` and bench.py's w2v-dp row
    so the bench times the EXACT tables training uses
    (InMemoryLookupTable syn1/expTable/negative-table construction role,
    InMemoryLookupTable.java:98-180)."""
    codes_np, points_np, lengths_t = encode_hs_tables(cache)
    mask_t = hs_mask_table(codes_np, lengths_t)
    return (jnp.asarray(codes_np), jnp.asarray(points_np), mask_t,
            jnp.asarray(unigram_table(cache, table_size)), lengths_t)


def hs_mask_table(codes_t: np.ndarray, lengths_t: np.ndarray) -> Array:
    """[V, L] float mask from per-word Huffman path lengths."""
    return jnp.asarray(
        (np.arange(codes_t.shape[1])[None, :] <
         np.asarray(lengths_t)[:, None]).astype(np.float32))


class Word2Vec:
    """fit() -> WordVectors.  API parity with Word2Vec.java's builder usage:
    Word2Vec(sentences, Word2VecConfig(...), tokenizer)."""

    def __init__(self, sentences: Iterable[str],
                 config: Optional[Word2VecConfig] = None,
                 tokenizer=None,
                 cache: Optional[VocabCache] = None):
        self.config = config or Word2VecConfig()
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        self.sentences = sentences
        self.cache = cache
        self.syn0: Optional[Array] = None
        self.syn1: Optional[Array] = None
        self.syn1neg: Optional[Array] = None
        self._wv: Optional[WordVectors] = None
        self._n_positions = 0       # corpus words (the lr-decay clock)
        self._dev_cache = None      # prepared pair slabs (see engine)
        self._indexed = None        # indexed corpus (exact/device modes)
        self._stream_cache = None   # uploaded token stream ("device")

    # -- vocab (buildVocab:257 parity) -------------------------------------
    def build_vocab(self) -> VocabCache:
        if self.cache is None:
            self.cache = build_vocab(self.sentences, self.tokenizer,
                                     self.config.min_word_frequency)
        if self.config.use_hs:
            build_huffman(self.cache)
        return self.cache

    def _index_sentences(self) -> List[np.ndarray]:
        """Tokenize + vocab-index the corpus; sets the lr-decay clock.

        Hot path of a cold fit (the whole corpus flows through it): one
        local dict lookup per token via ``map`` instead of a bound-method
        call + VocabWord attribute chase per token (~35% faster at the
        1M-word bench scale, where indexing is the largest host cost
        left in pair_mode="device")."""
        d = {w: vw.index for w, vw in self.cache.vocab.items()}
        get = d.get
        tok = self.tokenizer
        indexed: List[np.ndarray] = []
        n = 0
        for sent in self.sentences:
            arr = np.fromiter(
                (i for i in map(get, tok(sent)) if i is not None),
                np.int32)
            if arr.size:
                indexed.append(arr)
                n += arr.size
        self._n_positions = n
        return indexed

    def _reset_weights(self) -> None:
        """syn0 ~ U(-0.5, 0.5)/dim (InMemoryLookupTable:98-104)."""
        cfg = self.config
        V, D = len(self.cache), cfg.vector_size
        key = jax.random.key(cfg.seed)
        self.syn0 = (jax.random.uniform(key, (V, D)) - 0.5) / D
        self.syn1 = jnp.zeros((V, D))
        if cfg.negative > 0:
            self.syn1neg = jnp.zeros((V, D))

    def fit(self, initial_weights=None, mesh=None) -> WordVectors:
        """Train; ``initial_weights=(syn0, syn1, syn1neg|None)`` resumes
        from given tables instead of re-initializing — the hook the
        distributed performers use to absorb the current global state
        (scaleout word2vec job parity).  ``mesh`` (pair_mode="device"
        only): data-parallel training over the mesh's ``data`` axis with
        per-epoch parameter averaging — the reference's parallel
        word2vec (Word2Vec.java's trainSentence actor fan-out / Spark
        averaging) at chip scale."""
        cfg = self.config
        if cfg.kernel not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"Word2VecConfig.kernel must be 'auto', 'pallas' or "
                f"'xla', got {cfg.kernel!r}")
        if cfg.pair_mode not in ("masked", "exact", "device"):
            raise ValueError(
                f"Word2VecConfig.pair_mode must be 'masked', 'exact' or "
                f"'device', got {cfg.pair_mode!r}")
        if not cfg.use_hs and cfg.negative <= 0:
            raise ValueError(
                "no training objective: enable use_hs and/or negative > 0")
        if mesh is not None and cfg.pair_mode != "device":
            raise ValueError(
                "fit(mesh=...) data-parallel training requires "
                f"pair_mode='device' (got {cfg.pair_mode!r})")
        self.build_vocab()
        if len(self.cache) == 0:
            raise ValueError("empty vocabulary")
        if initial_weights is not None:
            # jnp.array (copy), NOT asarray: the jitted steps donate their
            # table arguments, so a no-copy view of the caller's arrays
            # would be deleted by donation on the first step, corrupting
            # the state the caller warm-started from
            self.syn0, self.syn1, self.syn1neg = (
                jnp.array(initial_weights[0]),
                jnp.array(initial_weights[1]),
                None if initial_weights[2] is None
                else jnp.array(initial_weights[2]))
        else:
            self._reset_weights()
        codes_t, points_t, mask_t, table, lengths_t = prepare_train_tables(
            self.cache, cfg.table_size)
        counts = np.asarray([self.cache.vocab[w].count
                             for w in self.cache.index], np.float64)

        if cfg.negative > 0 and self.syn1neg is None:
            raise ValueError(
                "negative sampling enabled but no syn1neg table: pass "
                "initial_weights with a syn1neg entry (or None weights to "
                "initialize fresh)")
        # COLD fit: index sentences, then STREAM candidate-pair slabs —
        # epoch 0 trains each slab (async dispatch) while the host builds
        # the next.  pair_mode="masked" caches the prepared slabs so later
        # fits (and epochs 1+) replay them with zero host pair work;
        # pair_mode="exact" re-streams host-shrunk pairs every epoch.
        if cfg.pair_mode == "device":
            if self._indexed is None:
                self._indexed = self._index_sentences()
            (self.syn0, self.syn1, self.syn1neg, self._stream_cache,
             self.kernel_used) = run_stream_training(
                self.syn0, self.syn1, self.syn1neg, self._indexed,
                vocab_size=len(self.cache), dim=cfg.vector_size,
                epochs=cfg.epochs, codes_t=codes_t, points_t=points_t,
                mask_t=mask_t, table=table, window=cfg.window,
                alpha=cfg.alpha, min_alpha=cfg.min_alpha,
                use_hs=cfg.use_hs, negative=cfg.negative,
                batch_size=cfg.batch_size, kernel=cfg.kernel,
                seed=cfg.seed,
                stream_cache=getattr(self, "_stream_cache", None),
                mesh=mesh)
            self._wv = WordVectors(self.cache, self.syn0)
            return self._wv
        pairs_iter = factory = None
        if cfg.pair_mode == "exact":
            if self._indexed is None:
                self._indexed = self._index_sentences()
            indexed, w = self._indexed, cfg.window

            def factory(epoch):
                rng = np.random.RandomState(
                    (cfg.seed + 7919 * (epoch + 1)) % (2 ** 31 - 1))
                return corpus_pairs_slabs(indexed, w, PAIRS_PER_SLAB, rng)
        elif self._dev_cache is None:
            if self._indexed is None:
                self._indexed = self._index_sentences()
            pairs_iter = corpus_pairs_slabs(self._indexed,
                                            cfg.window, PAIRS_PER_SLAB)
        (self.syn0, self.syn1, self.syn1neg, self._dev_cache,
         self.kernel_used) = run_pair_training(
                self.syn0, self.syn1, self.syn1neg,
                vocab_size=len(self.cache), dim=cfg.vector_size,
                epochs=cfg.epochs, total_words=self._n_positions,
                codes_t=codes_t, points_t=points_t, mask_t=mask_t,
                table=table, window=cfg.window, alpha=cfg.alpha,
                min_alpha=cfg.min_alpha, use_hs=cfg.use_hs,
                negative=cfg.negative, batch_size=cfg.batch_size,
                kernel=cfg.kernel, seed=cfg.seed,
                dev_cache=self._dev_cache, pairs_iter=pairs_iter,
                pairs_iter_factory=factory,
                window_mask=cfg.pair_mode != "exact",
                hs_lengths=np.asarray(lengths_t),
                hs_weights=counts,
                depth_buckets=cfg.depth_buckets)
        self._wv = WordVectors(self.cache, self.syn0)
        return self._wv

    # -- query passthrough --------------------------------------------------
    @property
    def word_vectors(self) -> WordVectors:
        if self._wv is None:
            raise RuntimeError("call fit() first")
        return self._wv

    def similarity(self, a: str, b: str) -> float:
        return self.word_vectors.similarity(a, b)

    def words_nearest(self, word: str, top_n: int = 10):
        return self.word_vectors.words_nearest(word, top_n)
