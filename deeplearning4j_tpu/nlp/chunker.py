"""Learned shallow chunker — the trained parse model behind TreeParser.

Reference parity: ``text/corpora/treeparser/TreeParser.java:57,66`` uses a
TRAINED parse model (CoreNLP via UIMA) to turn sentences into
constituents; the round-4 TreeParser only had hand-written tag rules
(VERDICT r4 missing #5).  This module trains an averaged-perceptron
transition classifier (Collins 2002 — the same learning machinery as
nlp/pos.py) over chunk actions: at each token it greedily chooses
B-NP / I-NP / B-VP / I-VP / O, i.e. a shift–reduce pass where B-* shifts
a new constituent onto the stack and I-* reduces the token into the top
one.  Trained on the bundled bracketed corpus below — which includes the
constructions the rule chunker provably gets wrong (participles inside
noun phrases: "the damaged road"; adverbs inside: "the very tall man") —
so the model produces real constituents the rules cannot.

The rule chunker (treeparser._chunk) remains the zero-cost fallback.
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nlp.pos import default_tagger

#: chunk actions (BIO over the two phrase kinds TreeParser builds)
ACTIONS = ("B-NP", "I-NP", "B-VP", "I-VP", "O")


def _features(i: int, words: Sequence[str], tags: Sequence[str],
              prev: str, prev2: str) -> List[str]:
    """Feature templates for position ``i``: local word/tag window plus
    the last two ACTIONS (the transition-system state)."""
    n = len(words)
    w = words[i].lower()
    t = tags[i]
    wm1 = words[i - 1].lower() if i > 0 else "-START-"
    tm1 = tags[i - 1] if i > 0 else "-START-"
    wp1 = words[i + 1].lower() if i + 1 < n else "-END-"
    tp1 = tags[i + 1] if i + 1 < n else "-END-"
    return [
        "b",
        "w:" + w, "t:" + t,
        "wm1:" + wm1, "tm1:" + tm1,
        "wp1:" + wp1, "tp1:" + tp1,
        "t2:" + tm1 + "|" + t,
        "t3:" + t + "|" + tp1,
        "a1:" + prev,
        "a2:" + prev2 + "|" + prev,
        "a1t:" + prev + "|" + t,
        "a1w:" + prev + "|" + w,
    ]


class ChunkPerceptron:
    """Greedy transition chunker with averaged-perceptron weights."""

    def __init__(self):
        self.weights: Dict[str, Dict[str, float]] = {}

    def _score(self, feats: Sequence[str]) -> Dict[str, float]:
        scores: Dict[str, float] = defaultdict(float)
        for f in feats:
            for action, w in self.weights.get(f, {}).items():
                scores[action] += w
        return scores

    def _predict(self, feats: Sequence[str], prev: str) -> str:
        scores = self._score(feats)
        legal = [a for a in ACTIONS
                 if not (a.startswith("I-")
                         and prev not in (a.replace("I-", "B-"), a))]
        return max(legal, key=lambda a: (scores.get(a, 0.0), a))

    def train(self, annotated: Sequence[List[Tuple[str, str, str]]],
              n_iter: int = 8, seed: int = 1) -> "ChunkPerceptron":
        """``annotated``: sentences of (word, pos, action) triples."""
        totals: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        stamps: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int))
        weights: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        self.weights = weights
        rng = random.Random(seed)
        data = list(annotated)
        step = 0
        for _ in range(n_iter):
            rng.shuffle(data)
            for sent in data:
                words = [w for w, _, _ in sent]
                tags = [t for _, t, _ in sent]
                prev = prev2 = "-START-"
                for i, (_, _, gold) in enumerate(sent):
                    feats = _features(i, words, tags, prev, prev2)
                    guess = self._predict(feats, prev)
                    if guess != gold:
                        for f in feats:
                            for a, d in ((gold, 1.0), (guess, -1.0)):
                                totals[f][a] += \
                                    (step - stamps[f][a]) * weights[f][a]
                                stamps[f][a] = step
                                weights[f][a] += d
                    # teacher forcing: condition on GOLD history so the
                    # state features stay meaningful
                    prev2, prev = prev, gold
                    step += 1
        # average
        avg: Dict[str, Dict[str, float]] = {}
        for f, acts in weights.items():
            row = {}
            for a, w in acts.items():
                total = totals[f][a] + (step - stamps[f][a]) * w
                v = total / step
                if abs(v) > 1e-9:
                    row[a] = v
            if row:
                avg[f] = row
        self.weights = avg
        return self

    def actions(self, tagged: Sequence[Tuple[str, str]]) -> List[str]:
        words = [w for w, _ in tagged]
        tags = [t for _, t in tagged]
        prev = prev2 = "-START-"
        out = []
        for i in range(len(tagged)):
            a = self._predict(_features(i, words, tags, prev, prev2), prev)
            out.append(a)
            prev2, prev = prev, a
        return out

    def chunk(self, tagged: Sequence[Tuple[str, str]]) -> List[List[str]]:
        """Same output contract as treeparser._chunk: token groups."""
        chunks: List[List[str]] = []
        for (word, _), action in zip(tagged, self.actions(tagged)):
            if action.startswith("I-") and chunks:
                chunks[-1].append(word)
            else:
                chunks.append([word])
        return chunks


# ---------------------------------------------------------------------------
# Bundled bracketed corpus.  Bootstrapped from the PoS seed sentences and
# HAND-CORRECTED — the corrections (marked *) teach constructions the
# rule chunker cannot express: participles and adverbs inside noun
# phrases, demonstrative pronouns as NP.
# ---------------------------------------------------------------------------

CHUNK_CORPUS_TEXT: List[str] = [
    "(NP the quick brown fox) (VP jumps) (O over) (NP the lazy dog) (O .)",
    "(NP a cat) (VP sat) (O on) (NP the mat) (O .)",
    "(NP dogs) (O and) (NP cats) (VP are) (NP friendly animals) (O .)",
    "(NP she) (VP quickly opened) (NP the old wooden door) (O .)",
    "(NP he) (VP is running) (O to) (NP the store) (O .)",
    "(NP they) (VP have finished) (NP the long report) (O .)",
    "(NP we) (VP will build) (NP a new model) (NP tomorrow) (O .)",   # *
    "(NP the children) (VP played happily) (O in) (NP the park) (O .)",
    "(NP my older brother) (VP drives) (NP a red car) (O .)",
    "(NP this) (VP is) (NP the best result) (O of) (NP all) (O .)",   # *
    "(NP john) (VP gave) (NP mary) (NP a beautiful gift) (O .)",
    "(NP the company) (VP reported) (NP strong earnings) (NP yesterday)"
    " (O .)",                                                          # *
    "(NP researchers) (VP trained) (NP the network) (O on)"
    " (NP large datasets) (O .)",
    "(NP the model) (VP learns) (NP useful representations) (O from)"
    " (NP text) (O .)",
    "(NP it) (VP was raining heavily) (O when) (NP we) (VP arrived) (O .)",
    "(O can) (NP you) (VP open) (NP the window) (O ,) (O please) (O ?)",
    "(NP the very tall man) (VP walked slowly) (O .)",                 # *
    "(NP birds) (VP fly south) (O in) (NP the winter) (O .)",
    "(NP she) (VP wrote) (NP three papers) (O about) (NP neural networks)"
    " (O .)",
    "(NP the students) (VP are studying) (O for) (NP their exams) (O .)",
    "(NP i) (VP think) (O that) (NP he) (VP knows) (NP the answer) (O .)",
    "(NP a small boat) (VP sailed) (O across) (NP the calm lake) (O .)",
    "(NP the weather) (VP was) (O cold) (O and) (O windy) (O .)",
    "(NP computers) (VP process) (NP information) (O faster) (O than)"
    " (NP humans) (O .)",
    "(NP the old library) (VP contains) (NP thousands) (O of) (NP books)"
    " (O .)",
    "(NP he) (VP carefully examined) (NP the broken machine) (O .)",
    "(NP the team) (VP won) (NP the final game) (O easily) (O .)",
    "(NP new ideas) (VP often come) (O from) (NP simple questions) (O .)",
    "(NP the train) (VP arrives) (O at) (NP noon) (NP every day) (O .)",
    "(NP farmers) (VP grow) (NP wheat) (O in) (NP these fields) (O .)",
    "(NP she) (VP has been working here) (O for) (NP ten years) (O .)",
    "(NP the bright sun) (VP melted) (NP the snow) (O quickly) (O .)",
    "(NP good teachers) (VP explain) (NP difficult concepts) (O clearly)"
    " (O .)",
    "(NP the river) (VP flows) (O through) (NP the green valley) (O .)",
    "(NP we) (VP visited) (NP an ancient castle) (O in) (NP scotland)"
    " (O .)",
    "(NP the price) (O of) (NP oil) (VP rose sharply) (NP last week)"
    " (O .)",
    "(NP young children) (VP learn) (NP languages) (O very) (O quickly)"
    " (O .)",
    "(NP the musician) (VP played) (NP a beautiful song) (O .)",
    "(NP scientists) (VP discovered) (NP a new species) (O of) (NP frog)"
    " (O .)",
    "(NP the engine) (VP stopped suddenly) (O near) (NP the bridge) (O .)",
    "(NP many people) (VP enjoy reading) (NP mystery novels) (O .)",
    "(NP the chef) (VP prepared) (NP a delicious meal) (O for) (NP us)"
    " (O .)",
    "(NP strong winds) (VP damaged) (NP several houses) (NP last night)"
    " (O .)",                                                          # *
    "(NP the doctor) (VP examined) (NP the patient) (O carefully) (O .)",
    "(NP these flowers) (VP bloom early) (O in) (NP the spring) (O .)",
    "(NP the lawyer) (VP presented) (NP convincing evidence) (NP today)"
    " (O .)",                                                          # *
    "(NP tall buildings) (VP dominate) (NP the city skyline) (O .)",
    "(NP the baby) (VP slept peacefully) (O through) (NP the storm) (O .)",
    "(NP workers) (VP repaired) (NP the damaged road) (O quickly) (O .)",  # *
    "(NP the artist) (VP painted) (NP a stunning portrait) (O .)",
    "(NP fresh vegetables) (VP taste) (O better) (O than) (NP frozen ones)"
    " (O .)",
    "(NP the committee) (VP approved) (NP the new budget) (O .)",
    "(NP heavy rain) (VP flooded) (NP the lower streets) (O .)",
    "(NP the pilot) (VP landed) (NP the plane) (O safely) (O .)",
    "(NP curious tourists) (VP photographed) (NP the famous statue) (O .)",
    "(NP the software) (VP runs smoothly) (O on) (NP older machines)"
    " (O .)",
    "(NP loud music) (VP annoyed) (NP the sleeping neighbors) (O .)",  # *
    "(NP the gardener) (VP watered) (NP the thirsty plants) (O .)",
    "(NP brave firefighters) (VP rescued) (NP the trapped family) (O .)",  # *
    "(NP the economy) (VP grew steadily) (O during) (NP the decade) (O .)",
    "(NP a happy child) (VP held) (NP a shiny red balloon)",
    "(NP the hungry wolves) (VP followed) (NP the snowy trail)",
    "(NP sleepy travelers) (VP waited) (O near) (NP the busy gate)",
    "(NP she) (VP read) (NP an interesting book)",
    "(NP he) (VP bought) (NP an expensive watch)",
    "(NP an angry customer) (VP returned) (NP the faulty toaster)",
    "(NP tiny insects) (VP crawled) (O across) (NP the dusty window)",
    "(NP the funny clown) (VP made) (NP everyone) (VP laugh)",
    "(NP noisy trucks) (VP passed) (NP the quiet village)",
    "(NP several heavy boxes) (VP blocked) (NP the narrow hallway)",
    "(NP modern systems) (VP require) (NP careful testing)",
    "(NP large models) (VP need) (NP fast accelerators)",
    "(NP the compiler) (VP optimizes) (NP the generated code)",        # *
    "(NP distributed training) (VP uses) (NP many devices)",           # *
    "(NP a cloudy sky) (VP promised) (NP rainy weather)",
]


def parse_bracketed(line: str) -> List[Tuple[str, List[str]]]:
    """'(NP the cat) (VP sat)' -> [('NP', ['the','cat']), ...]."""
    out: List[Tuple[str, List[str]]] = []
    for part in line.split(")"):
        part = part.strip()
        if not part:
            continue
        if not part.startswith("("):
            raise ValueError(f"bad bracketed chunk: {part!r} in {line!r}")
        kind, *words = part[1:].split()
        if kind not in ("NP", "VP", "O") or not words:
            raise ValueError(f"bad chunk {part!r} in {line!r}")
        out.append((kind, words))
    return out


def _annotate(line: str, tagger) -> List[Tuple[str, str, str]]:
    """Bracketed line -> (word, pos, gold-action) triples.  PoS tags come
    from the tagger (the same input the model sees at parse time)."""
    chunks = parse_bracketed(line)
    words = [w for _, ws in chunks for w in ws]
    tags = [t for _, t in tagger.tag(words)]
    triples: List[Tuple[str, str, str]] = []
    k = 0
    for kind, ws in chunks:
        for j, w in enumerate(ws):
            if kind == "O":
                action = "O"
            else:
                action = ("B-" if j == 0 else "I-") + kind
            triples.append((w, tags[k], action))
            k += 1
    return triples


def annotated_corpus(tagger=None) -> List[List[Tuple[str, str, str]]]:
    tagger = tagger or default_tagger()
    return [_annotate(line, tagger) for line in CHUNK_CORPUS_TEXT]


_default_chunker: Optional[ChunkPerceptron] = None


def default_chunker() -> ChunkPerceptron:
    """Shared chunker trained once on the bundled bracketed corpus."""
    global _default_chunker
    if _default_chunker is None:
        _default_chunker = ChunkPerceptron().train(annotated_corpus())
    return _default_chunker
