"""Persistent, searchable inverted index (sqlite-backed).

Reference parity: ``text/invertedindex/LuceneInvertedIndex.java``
(~927 LoC) — a disk-persistent index of tokenized documents with
per-word posting lists, document reconstruction, label storage, and
batched writes, used as the backing store for bag-of-words vectorizers
and sampled document iteration.  Lucene is replaced by sqlite (stdlib):
the capability contract — persistence across reloads, word→documents
lookup, ranked search — is the parity target, not the Lucene API.

Drop-in superset of the in-memory ``vectorizers.InvertedIndex`` surface
(``add_document`` / ``documents_containing`` / ``doc_frequency`` /
``num_docs``), plus TF-IDF ranked ``search`` and document/label
round-trips.  Safe for concurrent readers; one writer at a time (sqlite
semantics).
"""

from __future__ import annotations

import json
import math
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

_SCHEMA = """
CREATE TABLE IF NOT EXISTS docs (
    id INTEGER PRIMARY KEY,
    tokens TEXT NOT NULL,
    label TEXT
);
CREATE TABLE IF NOT EXISTS postings (
    term TEXT NOT NULL,
    doc_id INTEGER NOT NULL,
    freq INTEGER NOT NULL,
    PRIMARY KEY (term, doc_id)
) WITHOUT ROWID;
CREATE INDEX IF NOT EXISTS postings_by_doc ON postings (doc_id);
"""


class SqliteInvertedIndex:
    """word → posting lists in a sqlite file (``":memory:"`` for tests).

    The index survives close/reopen on the same path — the persistence
    the reference gets from its Lucene directory.
    """

    def __init__(self, path: str = ":memory:"):
        self.path = path
        # one connection guarded by a lock: callers may index from a
        # producer thread while another thread searches
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    # -- writing ------------------------------------------------------------
    def _insert_locked(self, tokens: Sequence[str], label: Optional[str],
                       doc_id: Optional[int]) -> int:
        counts: Dict[str, int] = {}
        for t in tokens:
            counts[t] = counts.get(t, 0) + 1
        cur = self._conn.execute(
            "INSERT INTO docs (id, tokens, label) VALUES (?, ?, ?)",
            (doc_id, json.dumps(list(tokens)), label))
        new_id = cur.lastrowid
        self._conn.executemany(
            "INSERT OR REPLACE INTO postings (term, doc_id, freq) "
            "VALUES (?, ?, ?)",
            [(t, new_id, c) for t, c in counts.items()])
        return int(new_id)

    def add_document(self, tokens: Sequence[str],
                     label: Optional[str] = None,
                     doc_id: Optional[int] = None) -> int:
        """Index one document; returns its id (LuceneInvertedIndex
        ``addWordsToDoc`` parity, with the label-aware variant folded
        in)."""
        with self._lock:
            try:
                new_id = self._insert_locked(tokens, label, doc_id)
                self._conn.commit()
            except Exception:
                # never leave a partial insert pending on the shared
                # connection: the next unrelated commit would persist it
                self._conn.rollback()
                raise
        return new_id

    def add_documents(self, docs: Sequence[Tuple[Sequence[str],
                                                 Optional[str]]]) -> List[int]:
        """Batched variant (the reference buffers into miniBatches): ONE
        transaction/fsync for the whole batch, not one per document."""
        with self._lock:
            try:
                ids = [self._insert_locked(tokens, label, None)
                       for tokens, label in docs]
                self._conn.commit()
            except Exception:
                self._conn.rollback()     # all-or-nothing for the batch
                raise
        return ids

    # -- reading ------------------------------------------------------------
    def document(self, doc_id: int) -> Tuple[List[str], Optional[str]]:
        """(tokens, label) round-trip (``document(index)`` parity)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT tokens, label FROM docs WHERE id = ?",
                (doc_id,)).fetchone()
        if row is None:
            raise KeyError(f"no document {doc_id}")
        return json.loads(row[0]), row[1]

    def documents_containing(self, word: str) -> List[int]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT doc_id FROM postings WHERE term = ? ORDER BY doc_id",
                (word,)).fetchall()
        return [r[0] for r in rows]

    def doc_frequency(self, word: str) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM postings WHERE term = ?",
                (word,)).fetchone()
        return int(n)

    def term_frequency(self, word: str) -> int:
        """Total occurrences across the corpus."""
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COALESCE(SUM(freq), 0) FROM postings "
                "WHERE term = ?", (word,)).fetchone()
        return int(n)

    def num_docs(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(*) FROM docs").fetchone()
        return int(n)

    def doc_ids(self) -> List[int]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id FROM docs ORDER BY id").fetchall()
        return [r[0] for r in rows]

    def iter_documents(self) -> Iterator[Tuple[int, List[str],
                                               Optional[str]]]:
        """(id, tokens, label) over the whole corpus (``eachDoc``
        parity)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, tokens, label FROM docs ORDER BY id").fetchall()
        for doc_id, tokens, label in rows:
            yield doc_id, json.loads(tokens), label

    def vocab(self) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT DISTINCT term FROM postings ORDER BY term").fetchall()
        return [r[0] for r in rows]

    # -- search -------------------------------------------------------------
    def search(self, query: Sequence[str],
               top_n: int = 10) -> List[Tuple[int, float]]:
        """TF-IDF ranked document search over the query terms — the
        retrieval capability the reference gets from Lucene scoring.
        Returns [(doc_id, score)] best-first."""
        if isinstance(query, str):
            query = query.split()
        n_docs = self.num_docs()
        if n_docs == 0:
            return []
        scores: Dict[int, float] = {}
        for term in query:
            df = self.doc_frequency(term)
            if df == 0:
                continue
            idf = math.log((1 + n_docs) / (1 + df)) + 1.0
            with self._lock:
                rows = self._conn.execute(
                    "SELECT doc_id, freq FROM postings WHERE term = ?",
                    (term,)).fetchall()
            for doc_id, freq in rows:
                scores[doc_id] = scores.get(doc_id, 0.0) + freq * idf
        ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
        return ranked[:top_n]

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def __enter__(self) -> "SqliteInvertedIndex":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
