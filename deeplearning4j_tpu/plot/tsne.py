"""t-SNE — exact (device-native) and Barnes-Hut (host trees + device steps).

Reference parity: ``plot/Tsne.java:47`` (computeGaussianPerplexity:125,
gradient:334, momentum schedule step:351) and ``plot/BarnesHutTsne.java:63``
(O(N log N) via QuadTree; implements Model).

TPU-native split (SURVEY.md §7.10: "exact t-SNE on TPU is easy; BH trees
stay host-side"):
- exact mode: P/Q affinity matrices and the gradient are dense [N, N]
  device math; the whole iteration loop runs in ONE ``lax.fori_loop`` with
  the reference's momentum schedule (0.5 -> 0.8 at iter 250) and early
  exaggeration;
- barnes-hut mode: per-iteration positive forces from a kNN-sparse P
  (device gather math), negative forces via the host SpTree.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.trees import SpTree

Array = jax.Array


@dataclasses.dataclass
class TsneConfig:
    n_components: int = 2
    perplexity: float = 30.0
    #: "auto" = max(N / early_exaggeration, 50) — the Belkina et al.
    #: (2019) heuristic sklearn adopted as its default.  A fixed lr of
    #: 200 is far too hot for small N: gradient magnitudes scale with
    #: P ~ 1/N, so small embeddings bounce around the gain schedule and
    #: never tighten their clusters (the exact-tsne blob test failed on
    #: exactly this).  A float keeps the old fixed-rate behavior.
    learning_rate: "float | str" = "auto"
    max_iter: int = 500
    early_exaggeration: float = 12.0
    exaggeration_iters: int = 100
    momentum_initial: float = 0.5
    momentum_final: float = 0.8
    momentum_switch_iter: int = 250   # Tsne.java switchMomentumIteration
    theta: float = 0.5                # Barnes-Hut accuracy
    seed: int = 0


def _resolve_lr(cfg: TsneConfig, n: int) -> float:
    """Concrete learning rate for an N-point embedding (see
    ``TsneConfig.learning_rate``)."""
    if isinstance(cfg.learning_rate, str):
        if cfg.learning_rate != "auto":
            raise ValueError(
                f"learning_rate must be a float or 'auto', got "
                f"{cfg.learning_rate!r}")
        return max(n / cfg.early_exaggeration, 50.0)
    return float(cfg.learning_rate)


def _binary_search_betas(d2: np.ndarray, perplexity: float,
                         tol: float = 1e-5, max_steps: int = 50
                         ) -> np.ndarray:
    """Per-point precision search (computeGaussianPerplexity:125)."""
    n = d2.shape[0]
    target = np.log(perplexity)
    betas = np.ones(n)
    for i in range(n):
        lo, hi = -np.inf, np.inf
        beta = 1.0
        di = np.delete(d2[i], i)
        for _ in range(max_steps):
            p = np.exp(-di * beta)
            s = max(p.sum(), 1e-12)
            h = np.log(s) + beta * float((di * p).sum()) / s
            diff = h - target
            if abs(diff) < tol:
                break
            if diff > 0:
                lo = beta
                beta = beta * 2 if hi == np.inf else (beta + hi) / 2
            else:
                hi = beta
                beta = beta / 2 if lo == -np.inf else (beta + lo) / 2
        betas[i] = beta
    return betas


def joint_probabilities(x: np.ndarray, perplexity: float) -> np.ndarray:
    """Symmetrized high-dimensional affinities P."""
    x = np.asarray(x, np.float64)
    n = x.shape[0]
    sq = np.sum(x * x, axis=1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (x @ x.T)
    np.fill_diagonal(d2, 0.0)
    betas = _binary_search_betas(d2, perplexity)
    p = np.exp(-d2 * betas[:, None])
    np.fill_diagonal(p, 0.0)
    p /= np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
    p = (p + p.T) / (2.0 * n)
    return np.maximum(p, 1e-12)


@partial(jax.jit, static_argnames=("max_iter", "exag_iters", "switch_iter"))
def _exact_loop(p: Array, y0: Array, max_iter: int, exag_iters: int,
                switch_iter: int, lr: float, exag: float, mom_i: float,
                mom_f: float):
    n = y0.shape[0]

    def grad_kl(y, p_eff):
        sq = jnp.sum(y * y, axis=1)
        d2 = sq[:, None] + sq[None, :] - 2.0 * (y @ y.T)
        num = 1.0 / (1.0 + d2)
        num = num.at[jnp.arange(n), jnp.arange(n)].set(0.0)
        q = num / jnp.maximum(jnp.sum(num), 1e-12)
        q = jnp.maximum(q, 1e-12)
        pq = (p_eff - q) * num                       # [N, N]
        g = 4.0 * (jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y
        kl = jnp.sum(p_eff * jnp.log(p_eff / q))
        return g, kl

    def body(it, carry):
        y, vel, gains, _ = carry
        p_eff = jnp.where(it < exag_iters, p * exag, p)
        g, kl = grad_kl(y, p_eff)
        mom = jnp.where(it < switch_iter, mom_i, mom_f)
        # gains (bar-delta adaptive lr, standard t-SNE; Tsne.java gradient)
        same_sign = (jnp.sign(g) == jnp.sign(vel))
        gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2),
                         0.01, None)
        vel = mom * vel - lr * gains * g
        y = y + vel
        y = y - jnp.mean(y, axis=0, keepdims=True)
        return y, vel, gains, kl

    init = (y0, jnp.zeros_like(y0), jnp.ones_like(y0), jnp.asarray(0.0))
    y, _, _, kl = jax.lax.fori_loop(0, max_iter, body, init)
    return y, kl


class Tsne:
    """Exact t-SNE (Tsne.java parity), device-iterated."""

    def __init__(self, config: Optional[TsneConfig] = None, **kw):
        self.config = config or TsneConfig(**kw)
        self.kl_: Optional[float] = None

    def fit_transform(self, x) -> np.ndarray:
        cfg = self.config
        x = np.asarray(x, np.float64)
        p = jnp.asarray(joint_probabilities(x, cfg.perplexity), jnp.float32)
        key = jax.random.key(cfg.seed)
        y0 = 1e-4 * jax.random.normal(
            key, (x.shape[0], cfg.n_components), jnp.float32)
        y, kl = _exact_loop(
            p, y0, cfg.max_iter, cfg.exaggeration_iters,
            cfg.momentum_switch_iter, _resolve_lr(cfg, x.shape[0]),
            cfg.early_exaggeration, cfg.momentum_initial,
            cfg.momentum_final)
        self.kl_ = float(kl)
        return np.asarray(y)


class BarnesHutTsne:
    """O(N log N) t-SNE: kNN-sparse P + SpTree negative forces
    (BarnesHutTsne.java parity; tree traversal host-side by design)."""

    def __init__(self, config: Optional[TsneConfig] = None, **kw):
        self.config = config or TsneConfig(**kw)
        self.kl_: Optional[float] = None

    def fit_transform(self, x) -> np.ndarray:
        cfg = self.config
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        k = min(n - 1, int(3 * cfg.perplexity))
        p_full = joint_probabilities(x, cfg.perplexity)
        # sparsify to kNN of P mass
        cols = np.argsort(-p_full, axis=1)[:, :k]          # [N, k]
        vals = np.take_along_axis(p_full, cols, axis=1)
        vals /= max(vals.sum(), 1e-12)

        lr = _resolve_lr(cfg, n)
        rng = np.random.RandomState(cfg.seed)
        y = 1e-4 * rng.randn(n, cfg.n_components)
        vel = np.zeros_like(y)
        gains = np.ones_like(y)

        cols_j = jnp.asarray(cols)
        vals_j = jnp.asarray(vals, jnp.float32)

        @jax.jit
        def pos_forces(yj, p_eff):
            diff = yj[:, None, :] - yj[cols_j]              # [N, k, C]
            d2 = jnp.sum(diff * diff, axis=-1)
            w = p_eff / (1.0 + d2)
            return jnp.sum(w[..., None] * diff, axis=1)

        for it in range(cfg.max_iter):
            exag = cfg.early_exaggeration if it < cfg.exaggeration_iters else 1.0
            pos = np.asarray(pos_forces(jnp.asarray(y, jnp.float32),
                                        vals_j * exag))
            tree = SpTree.build(y)
            neg = np.zeros_like(y)
            z = 0.0
            for i in range(n):
                f = np.zeros(cfg.n_components)
                z += tree.compute_non_edge_forces(y[i], cfg.theta, f)
                neg[i] = f
            g = pos - neg / max(z, 1e-12)
            mom = (cfg.momentum_initial if it < cfg.momentum_switch_iter
                   else cfg.momentum_final)
            same = np.sign(g) == np.sign(vel)
            gains = np.clip(np.where(same, gains * 0.8, gains + 0.2),
                            0.01, None)
            vel = mom * vel - lr * gains * g
            y = y + vel
            y -= y.mean(axis=0, keepdims=True)
        return y
