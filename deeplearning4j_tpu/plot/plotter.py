"""Weight/activation plotting and filter rendering.

Reference parity: ``plot/NeuralNetPlotter.java:46`` (plotActivations:235 —
writes matrices to temp CSVs then shells out to
``resources/scripts/plot.py``/``render.py`` matplotlib subprocesses) and
``plot/FilterRenderer.java`` (PNG grids of first-layer filters).

Here matplotlib is called in-process with the Agg backend (no subprocess,
no display); every function degrades to writing the raw arrays as .npy
next to the requested path if matplotlib is unavailable.
"""

from __future__ import annotations

import logging
import math
import os
from typing import Dict, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)


def _mpl():
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        return plt
    except Exception:  # pragma: no cover - matplotlib is in-image
        return None


class NeuralNetPlotter:
    """Histograms of weights/gradients/activations per layer."""

    def plot_network_gradient(self, net, path: str) -> str:
        """Panel of per-layer weight + bias histograms (plotWeights
        equivalent).  ``net`` is a MultiLayerNetwork with params set."""
        params = net._require_params()
        panels: Dict[str, np.ndarray] = {}
        for i, layer_params in enumerate(params):
            for name, arr in layer_params.items():
                panels[f"layer{i}/{name}"] = np.asarray(arr).ravel()
        return self.histograms(panels, path)

    def plot_activations(self, net, x, path: str) -> str:
        """Histogram of each layer's activations on a batch
        (plotActivations:235 equivalent)."""
        params = net._require_params()
        acts = net.feed_forward(params, x)
        panels = {f"layer{i}": np.asarray(a).ravel()
                  for i, a in enumerate(acts[1:])}
        return self.histograms(panels, path)

    def histograms(self, panels: Dict[str, np.ndarray], path: str) -> str:
        plt = _mpl()
        if plt is None:  # pragma: no cover
            alt = path + ".npz"
            np.savez(alt, **panels)
            return alt
        n = max(len(panels), 1)
        cols = min(n, 3)
        rows = math.ceil(n / cols)
        fig, axes = plt.subplots(rows, cols, figsize=(4 * cols, 3 * rows),
                                 squeeze=False)
        for ax in axes.ravel():
            ax.axis("off")
        for ax, (name, vals) in zip(axes.ravel(), panels.items()):
            ax.axis("on")
            ax.hist(vals, bins=50)
            ax.set_title(name, fontsize=8)
        fig.tight_layout()
        fig.savefig(path)
        plt.close(fig)
        return path


class FilterRenderer:
    """PNG grid of filters (FilterRenderer.java parity): each row of W
    (or conv kernel) rendered as a small image tile."""

    def render_filters(self, weights, path: str,
                       patch_shape: Optional[tuple] = None,
                       max_filters: int = 100) -> str:
        w = np.asarray(weights)
        if w.ndim == 4:                       # conv [kh, kw, cin, cout]
            kh, kw, cin, cout = w.shape
            tiles = [w[:, :, 0, i] for i in range(min(cout, max_filters))]
        else:                                 # dense [n_in, n_out]
            n_in, n_out = w.shape
            if patch_shape is None:
                side = int(round(math.sqrt(n_in)))
                if side * side != n_in:
                    raise ValueError(
                        f"n_in={n_in} is not square; pass patch_shape")
                patch_shape = (side, side)
            tiles = [w[:, i].reshape(patch_shape)
                     for i in range(min(n_out, max_filters))]

        n = len(tiles)
        cols = int(math.ceil(math.sqrt(n)))
        rows = int(math.ceil(n / cols))
        th, tw = tiles[0].shape
        grid = np.zeros((rows * (th + 1) - 1, cols * (tw + 1) - 1))
        for i, t in enumerate(tiles):
            r, c = divmod(i, cols)
            lo, hi = t.min(), t.max()
            norm = (t - lo) / (hi - lo) if hi > lo else t * 0
            grid[r * (th + 1):r * (th + 1) + th,
                 c * (tw + 1):c * (tw + 1) + tw] = norm

        plt = _mpl()
        if plt is None:  # pragma: no cover
            alt = path + ".npy"
            np.save(alt, grid)
            return alt
        fig, ax = plt.subplots(figsize=(cols, rows))
        ax.imshow(grid, cmap="gray")
        ax.axis("off")
        fig.savefig(path, bbox_inches="tight", dpi=120)
        plt.close(fig)
        return path


def render_embedding_html(words: Sequence[str], coords_2d,
                          path: str, title: str = "embeddings") -> str:
    """Standalone-HTML scatter of 2-D embeddings (t-SNE output) — the
    file-based replacement for the reference's Dropwizard render webapp
    (nlp/.../plot/dropwizard/RenderApplication.java + render.ftl): open the
    file in a browser, no server process."""
    pts = np.asarray(coords_2d, dtype=float)
    if pts.shape[0] != len(words) or pts.shape[1] != 2:
        raise ValueError(f"need [{len(words)}, 2] coords, got {pts.shape}")
    data = [{"w": w, "x": float(x), "y": float(y)}
            for w, (x, y) in zip(words, pts)]
    import json as _json
    html = f"""<!doctype html><html><head><meta charset="utf-8">
<title>{title}</title></head><body>
<h3>{title}</h3><svg id="plot" width="900" height="700"
 style="border:1px solid #ccc"></svg>
<script>
const data = {_json.dumps(data)};
const svg = document.getElementById('plot');
const xs = data.map(d=>d.x), ys = data.map(d=>d.y);
const minx=Math.min(...xs), maxx=Math.max(...xs);
const miny=Math.min(...ys), maxy=Math.max(...ys);
const sx = x => 40 + (x-minx)/(maxx-minx||1)*820;
const sy = y => 660 - (y-miny)/(maxy-miny||1)*620;
for (const d of data) {{
  const c = document.createElementNS('http://www.w3.org/2000/svg','circle');
  c.setAttribute('cx', sx(d.x)); c.setAttribute('cy', sy(d.y));
  c.setAttribute('r', 3); c.setAttribute('fill', '#4878d0');
  svg.appendChild(c);
  const t = document.createElementNS('http://www.w3.org/2000/svg','text');
  t.setAttribute('x', sx(d.x)+4); t.setAttribute('y', sy(d.y)-4);
  t.setAttribute('font-size', '9'); t.textContent = d.w;
  svg.appendChild(t);
}}
</script></body></html>"""
    with open(path, "w") as fh:
        fh.write(html)
    return path


def render_scalars_html(scalars_path: str, path: str,
                        title: str = "training scalars") -> str:
    """Line charts from a runtime/metrics.ScalarsLogger JSONL file — the
    scalars-dashboard half of the render webapp."""
    from deeplearning4j_tpu.runtime.metrics import ScalarsLogger

    rows = ScalarsLogger.read(scalars_path)
    keys = sorted({k for r in rows for k in r if k != "step"})
    plt = _mpl()
    if plt is None:  # pragma: no cover
        raise RuntimeError("matplotlib unavailable")
    n = max(len(keys), 1)
    fig, axes = plt.subplots(n, 1, figsize=(8, 3 * n), squeeze=False)
    for ax, k in zip(axes.ravel(), keys):
        steps = [r["step"] for r in rows if k in r]
        vals = [r[k] for r in rows if k in r]
        ax.plot(steps, vals)
        ax.set_title(k, fontsize=9)
        ax.set_xlabel("step")
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)
    return path
