"""Gradient adjustment / updaters — parity with ``GradientAdjustment.java``.

The reference applies, per named parameter, in order
(optimize/GradientAdjustment.java:50-113):

  1. AdaGrad scaling if ``useAdaGrad`` else plain learning-rate scaling
  2. momentum (with an iteration-indexed ``momentumAfter`` schedule,
     NeuralNetConfiguration.java:52-115)
  3. L2 weight decay (if ``useRegularization``) applied to weight params
  4. unit-norm constraint (``constrainGradientToUnitNorm``)
  5. divide by the minibatch size

TPU-native design: a pure ``(state, grads, params, iteration) -> (updates,
state)`` transformation (optax-compatible shape) whose state is a pytree, so
the whole update is one fused XLA program and can live inside ``lax.scan``
training loops and ``shard_map`` shards.  Modern optimizers (Adam/LAMB/...)
are provided via optax for the new model families.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

Array = jax.Array
PyTree = Any


class UpdaterState(NamedTuple):
    adagrad_accum: PyTree   # sum of squared gradients (AdaGrad historicalGradient)
    momentum_buf: PyTree    # velocity


class Dl4jUpdater(NamedTuple):
    """A GradientTransformation implementing the reference's adjustment chain."""
    init: Any
    update: Any


def dl4j_updater(
    lr: float = 1e-1,
    momentum: float = 0.5,
    momentum_schedule: Dict[int, float] | None = None,
    use_adagrad: bool = False,
    l2: float = 0.0,
    use_regularization: bool = False,
    constrain_unit_norm: bool = False,
    adagrad_eps: float = 1e-6,
) -> Dl4jUpdater:
    """Build the reference's update rule as a pure transformation.

    ``update(state, grads, params, iteration, batch_size)`` returns updates to
    be SUBTRACTED from params (gradient-descent convention; note the reference
    mixes ascent/descent per model — callers choose the sign).
    """
    schedule_iters = tuple(sorted((momentum_schedule or {}).items()))

    def init(params: PyTree) -> UpdaterState:
        zeros = jax.tree.map(jnp.zeros_like, params)
        return UpdaterState(adagrad_accum=zeros, momentum_buf=jax.tree.map(jnp.zeros_like, params))

    def _momentum_at(iteration: Array) -> Array:
        m = jnp.asarray(momentum, dtype=jnp.float32)
        for after_iter, m_val in schedule_iters:
            m = jnp.where(iteration >= after_iter, jnp.float32(m_val), m)
        return m

    def update(
        state: UpdaterState,
        grads: PyTree,
        params: PyTree,
        iteration: Array | int = 0,
        batch_size: Array | int = 1,
    ) -> Tuple[PyTree, UpdaterState]:
        iteration = jnp.asarray(iteration)
        inv_batch = 1.0 / jnp.maximum(jnp.asarray(batch_size, jnp.float32), 1.0)

        # 1. AdaGrad-or-lr
        if use_adagrad:
            new_accum = jax.tree.map(lambda a, g: a + g * g, state.adagrad_accum, grads)
            scaled = jax.tree.map(
                lambda g, a: lr * g / (jnp.sqrt(a) + adagrad_eps), grads, new_accum)
        else:
            new_accum = state.adagrad_accum
            scaled = jax.tree.map(lambda g: lr * g, grads)

        # 2. momentum (heavy-ball): v = m*v + g_scaled ; update = v
        m = _momentum_at(iteration)
        new_buf = jax.tree.map(lambda v, g: m * v + g, state.momentum_buf, scaled)
        upd = new_buf

        # 3. L2 weight decay — applied to WEIGHT leaves only (keys named
        # "W"/"*_W"), matching the reference's GradientAdjustment which
        # regularizes weight matrices, not biases.  L2 lives EXCLUSIVELY
        # here (layer losses do not add it) so it is never double-counted.
        if use_regularization and l2 > 0.0:
            upd = _apply_l2(upd, params, lr * l2)

        # 4. unit-norm constraint
        if constrain_unit_norm:
            upd = jax.tree.map(
                lambda u: u / (jnp.linalg.norm(u.ravel()) + 1e-12), upd)

        # 5. ÷ batch size
        upd = jax.tree.map(lambda u: u * inv_batch, upd)
        return upd, UpdaterState(adagrad_accum=new_accum, momentum_buf=new_buf)

    return Dl4jUpdater(init=init, update=update)


def _is_weight_key(path) -> bool:
    """True for leaves whose final dict key names a weight matrix."""
    for entry in reversed(path):
        key = getattr(entry, "key", None)
        if isinstance(key, str):
            return key == "W" or key.endswith("_W")
    return False


def _apply_l2(upd: PyTree, params: PyTree, coeff: float) -> PyTree:
    return jax.tree_util.tree_map_with_path(
        lambda path, u, p: u + coeff * p if _is_weight_key(path) else u,
        upd, params)


def apply_updates(params: PyTree, updates: PyTree) -> PyTree:
    """Gradient-descent application: params - updates."""
    return jax.tree.map(lambda p, u: p - u, params, updates)


# ---------------------------------------------------------------------------
# Modern optimizer families (for new-capability models: BERT, ResNet).
# ---------------------------------------------------------------------------

def make_optimizer(name: str, lr: float = 1e-3, **kw) -> optax.GradientTransformation:
    """Registry of optax optimizers by name (config-system friendly)."""
    name = name.lower()
    if name == "sgd":
        return optax.sgd(lr, momentum=kw.get("momentum", 0.0))
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "adam":
        return optax.adam(lr, b1=kw.get("b1", 0.9), b2=kw.get("b2", 0.999))
    if name == "adamw":
        return optax.adamw(lr, weight_decay=kw.get("weight_decay", 0.01))
    if name == "lamb":
        return optax.lamb(lr, weight_decay=kw.get("weight_decay", 0.0))
    if name == "rmsprop":
        return optax.rmsprop(lr)
    raise ValueError(f"unknown optimizer '{name}'")
