"""Symbolic graph builder — the SameDiff/op-graph role, TPU-native.

Reference parity: the reference's compute stack sits on ND4J's op
factory/executioner (string-named op dispatch over INDArrays,
SURVEY.md §2.8), whose successor in later DL4J is the SameDiff graph
builder (define-placeholders → compose ops → autodiff → execute).  In
JAX the *graph* is the jaxpr: tracing a python function IS graph
construction, and XLA compiles it to HLO.  This module offers the
reference-style imperative building API on top of that reality:

    g = GraphBuilder()
    x = g.placeholder("x", (8, 4))
    w = g.variable("w", np.random.randn(4, 2))
    b = g.variable("b", np.zeros(2))
    y = g.softmax(g.add(g.matmul(x, w), b))
    loss = g.mean(g.square(g.sub(y, g.placeholder("t", (8, 2)))))

    g.jaxpr(loss)          # the traced graph (inspection/debugging)
    g.hlo(loss)            # lowered StableHLO text — "graph -> HLO"
    f = g.compile(loss)    # jitted executable: f(x=..., t=...)
    grads = g.grad(loss)   # d loss / d each variable, jitted

Every op node is a closure over its inputs; nothing executes until
``compile``/``grad`` traces the whole graph once — identical staging
semantics to jit, so the builder adds no runtime overhead.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class Node:
    """One graph vertex: op name + parents; ``fn(env)`` computes its
    value given the placeholder/variable environment."""
    graph: "GraphBuilder"
    name: str
    op: str
    parents: Tuple["Node", ...]
    fn: Callable[[Dict[str, Array]], Array]

    def __repr__(self) -> str:
        ps = ", ".join(p.name for p in self.parents)
        return f"{self.name} = {self.op}({ps})"


class GraphBuilder:
    """Imperative graph construction over jax tracing (SameDiff role)."""

    #: elementwise/binary ops exposed as builder methods, named like the
    #: reference's string-dispatched transforms (ops/registry parity)
    _UNARY = {
        "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": jax.nn.relu,
        "exp": jnp.exp, "log": jnp.log, "neg": jnp.negative,
        "abs": jnp.abs, "sqrt": jnp.sqrt, "square": jnp.square,
        "softmax": jax.nn.softmax,
    }
    _BINARY = {
        "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
        "div": jnp.divide, "pow": jnp.power, "maximum": jnp.maximum,
        "minimum": jnp.minimum,
    }
    _REDUCE = {"sum": jnp.sum, "mean": jnp.mean, "max": jnp.max,
               "min": jnp.min}

    def __init__(self):
        self.placeholders: Dict[str, jax.ShapeDtypeStruct] = {}
        self.variables: Dict[str, Array] = {}
        self.nodes: List[Node] = []
        self._counter = 0

    # -- leaves -------------------------------------------------------------
    def placeholder(self, name: str, shape: Sequence[int],
                    dtype=jnp.float32) -> Node:
        """Runtime input (SameDiff placeholder)."""
        if name in self.placeholders or name in self.variables:
            raise ValueError(f"name {name!r} already defined")
        self.placeholders[name] = jax.ShapeDtypeStruct(tuple(shape), dtype)
        return self._add(name, "placeholder", (),
                         lambda env, _n=name: env[_n])

    def variable(self, name: str, value) -> Node:
        """Trainable leaf (SameDiff variable); ``grad`` differentiates
        with respect to these."""
        if name in self.placeholders or name in self.variables:
            raise ValueError(f"name {name!r} already defined")
        self.variables[name] = jnp.asarray(value)
        return self._add(name, "variable", (),
                         lambda env, _n=name: env[_n])

    def constant(self, value) -> Node:
        arr = jnp.asarray(value)
        return self._add(self._fresh("const"), "constant", (),
                         lambda env, _a=arr: _a)

    # -- ops ----------------------------------------------------------------
    def _add(self, name: str, op: str, parents: Tuple[Node, ...],
             raw_fn: Callable[[Dict[str, Array]], Array]) -> Node:
        for p in parents:
            if p.graph is not self:
                # the evaluation cache keys on per-builder node ids, so a
                # foreign node would silently alias another node's value
                raise ValueError(
                    f"node {p.name!r} belongs to a different GraphBuilder")
        node_id = len(self.nodes)

        def fn(env: Dict[str, Array], _raw=raw_fn, _id=node_id) -> Array:
            # memoize per evaluation: a node shared by several consumers
            # must trace once, not once per consumer (a deep shared DAG
            # would otherwise blow up exponentially)
            cache = env.setdefault("__node_cache__", {})
            if _id not in cache:
                cache[_id] = _raw(env)
            return cache[_id]

        node = Node(self, name, op, parents, fn)
        self.nodes.append(node)
        return node

    def _fresh(self, op: str) -> str:
        self._counter += 1
        return f"{op}_{self._counter}"

    def apply(self, op: str, *args: Node, **kw) -> Node:
        """String-named dispatch — the op-factory surface
        (Nd4j.getOpFactory() parity): ``g.apply("tanh", x)``."""
        if op in self._REDUCE:
            unknown = set(kw) - {"axis", "keepdims"}
            if unknown:
                raise TypeError(f"{op} got unexpected kwargs "
                                f"{sorted(unknown)}")
            (a,) = args
            f = self._REDUCE[op]
            axis = kw.get("axis")
            keepdims = kw.get("keepdims", False)
            return self._add(self._fresh(op), op, (a,),
                             lambda env, _a=a: f(_a.fn(env), axis=axis,
                                                 keepdims=keepdims))
        if kw:
            raise TypeError(f"{op} takes no kwargs, got {sorted(kw)}")
        if op in self._UNARY:
            (a,) = args
            f = self._UNARY[op]
            return self._add(self._fresh(op), op, (a,),
                             lambda env, _a=a: f(_a.fn(env)))
        if op in self._BINARY:
            a, b = args
            f = self._BINARY[op]
            return self._add(self._fresh(op), op, (a, b),
                             lambda env, _a=a, _b=b: f(_a.fn(env),
                                                       _b.fn(env)))
        # fall through to the framework op registry so user-registered
        # activations (ops/registry.register_activation) work here too.
        # Only a LOOKUP miss means "unknown op"; any other failure (e.g.
        # a broken registry import) must surface as itself
        from deeplearning4j_tpu.ops.registry import get_activation
        try:
            f = get_activation(op)
        except ValueError:
            raise ValueError(f"unknown op {op!r}") from None
        (a,) = args
        return self._add(self._fresh(op), op, (a,),
                         lambda env, _a=a: f(_a.fn(env)))

    def __getattr__(self, op: str):
        # builder method sugar: g.tanh(x), g.add(a, b), g.sum(x, axis=0)
        if op in (*self._UNARY, *self._BINARY, *self._REDUCE):
            return lambda *args, **kw: self.apply(op, *args, **kw)
        raise AttributeError(op)

    def matmul(self, a: Node, b: Node) -> Node:
        return self._add(self._fresh("matmul"), "matmul", (a, b),
                         lambda env, _a=a, _b=b: jnp.matmul(_a.fn(env),
                                                            _b.fn(env)))

    def reshape(self, a: Node, shape: Sequence[int]) -> Node:
        shape = tuple(shape)
        return self._add(self._fresh("reshape"), "reshape", (a,),
                         lambda env, _a=a: jnp.reshape(_a.fn(env), shape))

    def transpose(self, a: Node, axes: Optional[Sequence[int]] = None
                  ) -> Node:
        return self._add(self._fresh("transpose"), "transpose", (a,),
                         lambda env, _a=a: jnp.transpose(_a.fn(env), axes))

    # -- tracing / lowering / execution -------------------------------------
    def _reachable_placeholders(self, out: Node) -> set:
        """Placeholder names `out` actually depends on (SameDiff only
        requires inputs the requested output consumes)."""
        seen, stack, names = set(), [out], set()
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            if n.op == "placeholder":
                names.add(n.name)
            stack.extend(n.parents)
        return names

    def _as_function(self, out: Node) -> Callable:
        """(variables_dict, **placeholders) -> value; the traceable whole-
        graph function."""
        required = self._reachable_placeholders(out)

        def f(variables: Dict[str, Array], **placeholders: Array) -> Array:
            env = {**variables, **placeholders}
            missing = required - set(placeholders)
            if missing:
                raise ValueError(f"missing placeholders: {sorted(missing)}")
            return out.fn(env)
        return f

    def _example_args(self, out: Node) -> Dict[str, Array]:
        req = self._reachable_placeholders(out)
        return {n: jnp.zeros(s.shape, s.dtype)
                for n, s in self.placeholders.items() if n in req}

    def jaxpr(self, out: Node) -> str:
        """The traced graph as a jaxpr (the TPU-native 'graph IR')."""
        f = self._as_function(out)
        return str(jax.make_jaxpr(f)(self.variables,
                                     **self._example_args(out)))

    def hlo(self, out: Node) -> str:
        """Lowered StableHLO text — the 'autodiff graph → HLO' north-star
        capability, natively via jit lowering."""
        f = self._as_function(out)
        return jax.jit(f).lower(self.variables,
                                **self._example_args(out)).as_text()

    def compile(self, out: Node) -> Callable:
        """Jitted executable over the CURRENT variable values:
        ``f(**placeholders) -> value``."""
        base = jax.jit(self._as_function(out))

        def run(**placeholders: Array) -> Array:
            return base(self.variables, **placeholders)
        return run

    def grad(self, out: Node, wrt: Optional[Sequence[str]] = None
             ) -> Callable:
        """Jitted gradient of a SCALAR output w.r.t. the named variables
        (default: all): ``g(**placeholders) -> {name: grad}``."""
        names = list(wrt) if wrt is not None else list(self.variables)
        unknown = set(names) - set(self.variables)
        if unknown:
            raise ValueError(f"not variables: {sorted(unknown)}")
        f = self._as_function(out)

        def scalar(subset: Dict[str, Array], others: Dict[str, Array],
                   **ph: Array) -> Array:
            return f({**others, **subset}, **ph)

        # others ride as a jit ARGUMENT: baking them in as constants
        # would freeze non-wrt variables at first-trace values and
        # silently ignore later set_variable() updates
        gradfn = jax.jit(jax.grad(scalar))

        def run(**placeholders: Array) -> Dict[str, Array]:
            subset = {n: self.variables[n] for n in names}
            others = {n: v for n, v in self.variables.items()
                      if n not in subset}
            return gradfn(subset, others, **placeholders)
        return run

    def set_variable(self, name: str, value) -> None:
        if name not in self.variables:
            raise KeyError(name)
        self.variables[name] = jnp.asarray(value)

    def __repr__(self) -> str:
        lines = [f"GraphBuilder({len(self.nodes)} nodes)"]
        lines += [f"  {n!r}" for n in self.nodes]
        return "\n".join(lines)
