"""Tensor-op substrate: the ND4J-equivalent layer.

The reference dispatches string-named elementwise transforms through
``Nd4j.getExecutioner()``/``getOpFactory()`` (e.g. BaseLayer.java:203,
MultiLayerNetwork.java:956 request ``activation`` and ``activation+"derivative"``
ops by name).  Here the same capability is a registry of pure JAX functions
with autodiff-derived derivatives.
"""

from deeplearning4j_tpu.ops.registry import (  # noqa: F401
    get_activation,
    get_activation_derivative,
    register_activation,
    list_activations,
)
from deeplearning4j_tpu.ops.losses import LossFunction, score as loss_score  # noqa: F401
from deeplearning4j_tpu.ops import random  # noqa: F401
