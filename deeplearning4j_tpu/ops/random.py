"""RNG stream management.

The reference threads ``org.nd4j.linalg.api.rng`` RNGs and Distributions
through configs (NeuralNetConfiguration holds an RNG + seed).  The TPU-native
equivalent is explicit ``jax.random`` key threading: a ``KeyStream`` is a
convenience for host-side sequential key splitting (init time); inside jit
everything takes and returns keys explicitly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


class KeyStream:
    """Host-side sequential splitter: ``next()`` yields a fresh key each call.

    Use only OUTSIDE jit (init, data shuffling). Inside jit, split keys
    explicitly so tracing stays pure.
    """

    def __init__(self, seed_or_key: int | Array = 0):
        if isinstance(seed_or_key, int):
            self._key = jax.random.key(seed_or_key)
        else:
            self._key = seed_or_key

    def next(self) -> Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def split(self, n: int) -> Array:
        self._key, *subs = jax.random.split(self._key, n + 1)
        return jnp.stack(subs)


def bernoulli_sample(key: Array, p: Array) -> Array:
    """Sample {0,1} with probability p (RBM binary units, dropout,
    BinomialSamplingPreProcessor parity)."""
    return jax.random.bernoulli(key, p).astype(p.dtype)


def gaussian_sample(key: Array, mean: Array, std: float | Array = 1.0) -> Array:
    return mean + std * jax.random.normal(key, mean.shape, dtype=mean.dtype)


def dropout(key: Array, x: Array, rate: float) -> Array:
    """Inverted dropout (scales at train time). The reference's
    ``BaseLayer.applyDropOutIfNecessary`` (BaseLayer.java:238) zeroes with
    prob ``dropOut`` without rescaling; we use the standard inverted form so
    inference needs no correction."""
    if rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
