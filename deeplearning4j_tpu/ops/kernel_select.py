"""Shared Pallas-vs-XLA kernel selection for the NLP trainers.

Word2Vec and GloVe both auto-select a VMEM-resident Pallas kernel on TPU
when their tables fit, fall back to the XLA gather/scatter path
otherwise, and honor a forced ``kernel=`` config value ("pallas" off-TPU
runs through the interpreter — the test harness).  This is the one copy
of that policy.
"""

from __future__ import annotations

from typing import Tuple

import jax

KERNELS = ("auto", "pallas", "xla")


def resolve_kernel(kernel: str, block: int, desc: str
                   ) -> Tuple[int, bool]:
    """(pallas_block, pallas_interpret) for a requested ``kernel`` mode
    and a precomputed VMEM ``block`` (0 = doesn't fit).  Raises for
    unknown modes and for ``kernel='pallas'`` when the budget excludes
    it — never a silent fallback on an explicit request."""
    if kernel not in KERNELS:
        raise ValueError(
            f"kernel must be one of {KERNELS}, got {kernel!r}")
    if kernel == "xla":
        return 0, False
    platform = jax.devices()[0].platform
    if block and (platform == "tpu" or kernel == "pallas"):
        return block, platform != "tpu"
    if kernel == "pallas":
        raise ValueError(
            f"kernel='pallas' but {desc} exceeds the VMEM-resident "
            f"budget (or the batch size is not divisible by a "
            f"supported block)")
    return 0, False


def kernel_name(pallas_block: int, pallas_interpret: bool) -> str:
    """Human-readable verdict for a resolved (block, interpret) pair —
    what benches record into round artifacts as the Mosaic
    accept/reject evidence."""
    if pallas_block and not pallas_interpret:
        return "pallas"
    return "pallas-interpret" if pallas_block else "xla"
