"""Shared Pallas-vs-XLA kernel selection policy.

Word2Vec and GloVe auto-select a VMEM-resident Pallas kernel on TPU when
their tables fit, fall back to the XLA gather/scatter path otherwise,
and honor a forced ``kernel=`` config value ("pallas" off-TPU runs
through the interpreter — the test harness).  ``resolve_attn_kernel``
generalizes the same contract to the flash-attention training path
(ops/pallas_attention.make_attn_fn): auto-selection may consult an
autotuned winner, an explicit ``kernel="pallas"`` request NEVER falls
back silently, and off-TPU a forced Pallas kernel runs interpreted so
tier-1 exercises the kernel code path.  This is the one copy of that
policy.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

KERNELS = ("auto", "pallas", "xla")

#: attention kernel modes add "ring" (sequence-parallel ring attention,
#: parallel/ring_attention.py) to the shared vocabulary — one policy,
#: one spelling
ATTN_KERNELS = KERNELS + ("ring",)


def resolve_attn_kernel(kernel: str, *, k_len: int, aligned: bool,
                        on_tpu: bool, blocked: Optional[str] = None,
                        autotuned_impl: Optional[str] = None,
                        min_seq: int, desc: str = "flash attention",
                        seq_degree: int = 1) -> Tuple[str, bool]:
    """(impl, interpret) for a requested attention ``kernel`` mode.

    ``aligned`` is the Mosaic-tileability verdict for the shape,
    ``blocked`` an optional reason the Pallas kernel cannot run in this
    context at all (seq-parallel mesh, indivisible sharding, ...).
    ``autotuned_impl`` is a persisted sweep winner ("pallas"/"xla") that
    overrides the ``min_seq`` heuristic for auto mode on TPU.
    ``seq_degree`` is the mesh's sequence-parallel degree: above 1, ring
    attention (parallel/ring_attention.py) owns the axis — auto selects
    impl "ring" (unless an autotuned winner says plain XLA is faster at
    this shape), an explicit ``kernel='ring'`` demands it, and an
    explicit ``kernel='pallas'`` raises (the flash kernel has no ring
    schedule).

    Contract (same as :func:`resolve_kernel` for word2vec/glove): auto
    degrades silently, an explicit ``kernel='pallas'``/``'ring'`` raises
    instead of falling back, and a forced Pallas kernel off-TPU runs
    through the interpreter (the CPU test harness)."""
    if kernel not in ATTN_KERNELS:
        raise ValueError(
            f"kernel must be one of {ATTN_KERNELS}, got {kernel!r}")
    if kernel == "ring":
        if seq_degree <= 1 or blocked is not None:
            raise ValueError(
                f"kernel='ring' but {desc} cannot run ring attention: "
                f"{blocked or f'no sharded sequence axis (seq degree {seq_degree})'}"
                f" — never a silent fallback on an explicit request")
        return "ring", False
    if kernel == "xla":
        return "xla", False
    if seq_degree > 1:
        if kernel == "pallas":
            raise ValueError(
                f"kernel='pallas' but {desc} runs under sequence "
                f"parallelism (seq degree {seq_degree}) — ring attention "
                f"owns a sharded sequence axis; request kernel='ring' or "
                f"'auto'")
        if autotuned_impl == "xla":
            return "xla", False
        return "ring", False
    if aligned and blocked is None:
        if kernel == "pallas":
            return "pallas", not on_tpu
        if not on_tpu:
            return "xla", False          # auto off-TPU: interpreter is
        if autotuned_impl in ("pallas", "xla"):   # no training kernel
            return autotuned_impl, False
        return ("pallas" if k_len >= min_seq else "xla"), False
    if kernel == "pallas":
        raise ValueError(
            f"kernel='pallas' but {desc} cannot run the Pallas kernel: "
            f"{blocked or 'shape is not Mosaic-tileable'} — never a "
            f"silent fallback on an explicit request")
    return "xla", False


def resolve_kernel(kernel: str, block: int, desc: str
                   ) -> Tuple[int, bool]:
    """(pallas_block, pallas_interpret) for a requested ``kernel`` mode
    and a precomputed VMEM ``block`` (0 = doesn't fit).  Raises for
    unknown modes and for ``kernel='pallas'`` when the budget excludes
    it — never a silent fallback on an explicit request."""
    if kernel not in KERNELS:
        raise ValueError(
            f"kernel must be one of {KERNELS}, got {kernel!r}")
    if kernel == "xla":
        return 0, False
    platform = jax.devices()[0].platform
    if block and (platform == "tpu" or kernel == "pallas"):
        return block, platform != "tpu"
    if kernel == "pallas":
        raise ValueError(
            f"kernel='pallas' but {desc} exceeds the VMEM-resident "
            f"budget (or the batch size is not divisible by a "
            f"supported block)")
    return 0, False


def kernel_name(pallas_block: int, pallas_interpret: bool) -> str:
    """Human-readable verdict for a resolved (block, interpret) pair —
    what benches record into round artifacts as the Mosaic
    accept/reject evidence."""
    if pallas_block and not pallas_interpret:
        return "pallas"
    return "pallas-interpret" if pallas_block else "xla"
