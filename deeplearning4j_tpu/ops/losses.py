"""Loss functions — parity with ND4J ``LossFunctions.LossFunction``.

The reference scores layers via ``LossFunctions.score(labels, lossFunction,
output, l2, useRegularization)`` (consumed at OutputLayer.java:68-92,
BasePretrainNetwork reconstruction scores).  The enum there is:
MSE, EXPLL, XENT, MCXENT, RMSE_XENT, SQUARED_LOSS,
RECONSTRUCTION_CROSSENTROPY, NEGATIVELOGLIKELIHOOD.

All losses are mean-per-example scalars, jit-safe, fp32-accumulated (inputs
may arrive bfloat16 from the MXU path).
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-10


class LossFunction(str, enum.Enum):
    MSE = "mse"
    EXPLL = "expll"                      # exponential log-likelihood (Poisson)
    XENT = "xent"                        # binary cross-entropy
    MCXENT = "mcxent"                    # multiclass cross-entropy
    RMSE_XENT = "rmse_xent"
    SQUARED_LOSS = "squared_loss"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    COSINE_PROXIMITY = "cosine_proximity"


def per_example_score(labels: Array, loss: LossFunction | str,
                      output: Array) -> Array:
    """Per-row losses, shape ``labels.shape[:-1]`` — the unreduced form of
    :func:`score` (``score == mean(per_example_score)``).  The sharded /
    microbatched training paths need the unreduced vector so zero-padded
    rows can be masked out of the sum BEFORE normalizing by the REAL row
    count (the trailing-batch padding contract in ``parallel/mesh.py``)."""
    loss = LossFunction(loss)
    labels = labels.astype(jnp.float32)
    output = output.astype(jnp.float32)

    if loss in (LossFunction.MSE, LossFunction.SQUARED_LOSS):
        per = jnp.sum((labels - output) ** 2, axis=-1)
        if loss is LossFunction.MSE:
            per = per / labels.shape[-1]
        return per
    if loss is LossFunction.RMSE_XENT:
        return jnp.sqrt(jnp.sum((labels - output) ** 2, axis=-1) + _EPS)
    if loss is LossFunction.XENT or loss is LossFunction.RECONSTRUCTION_CROSSENTROPY:
        p = jnp.clip(output, _EPS, 1.0 - _EPS)
        return -jnp.sum(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p), axis=-1)
    if loss in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        p = jnp.clip(output, _EPS, 1.0)
        return -jnp.sum(labels * jnp.log(p), axis=-1)
    if loss is LossFunction.EXPLL:
        # Poisson NLL: output - labels*log(output)
        p = jnp.clip(output, _EPS, None)
        return jnp.sum(p - labels * jnp.log(p), axis=-1)
    if loss is LossFunction.COSINE_PROXIMITY:
        num = jnp.sum(labels * output, axis=-1)
        den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(output, axis=-1) + _EPS
        return -(num / den)
    raise ValueError(f"unhandled loss {loss}")


def score(labels: Array, loss: LossFunction | str, output: Array) -> Array:
    """Mean loss over the batch. ``output`` is the model's (post-activation)
    prediction, as in the reference (loss composed with softmax/sigmoid output
    activations, not logits — logit-space variants live in the model families
    where they matter for numerics)."""
    return jnp.mean(per_example_score(labels, loss, output))


def per_example_softmax_cross_entropy_with_logits(labels: Array,
                                                  logits: Array) -> Array:
    """Per-row stable MCXENT on logits (unreduced ``[B]`` form)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.sum(labels.astype(jnp.float32) * logp, axis=-1)


def softmax_cross_entropy_with_logits(labels: Array, logits: Array) -> Array:
    """Numerically-stable MCXENT on logits — the TPU-native path the model
    families use (fuses into one XLA op chain; avoids log(softmax) blowup)."""
    return jnp.mean(per_example_softmax_cross_entropy_with_logits(labels,
                                                                  logits))


def per_example_sigmoid_binary_cross_entropy_with_logits(
        labels: Array, logits: Array) -> Array:
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(per, axis=-1)


def sigmoid_binary_cross_entropy_with_logits(labels: Array, logits: Array) -> Array:
    return jnp.mean(per_example_sigmoid_binary_cross_entropy_with_logits(
        labels, logits))
