"""Loss functions — parity with ND4J ``LossFunctions.LossFunction``.

The reference scores layers via ``LossFunctions.score(labels, lossFunction,
output, l2, useRegularization)`` (consumed at OutputLayer.java:68-92,
BasePretrainNetwork reconstruction scores).  The enum there is:
MSE, EXPLL, XENT, MCXENT, RMSE_XENT, SQUARED_LOSS,
RECONSTRUCTION_CROSSENTROPY, NEGATIVELOGLIKELIHOOD.

All losses are mean-per-example scalars, jit-safe, fp32-accumulated (inputs
may arrive bfloat16 from the MXU path).
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-10


class LossFunction(str, enum.Enum):
    MSE = "mse"
    EXPLL = "expll"                      # exponential log-likelihood (Poisson)
    XENT = "xent"                        # binary cross-entropy
    MCXENT = "mcxent"                    # multiclass cross-entropy
    RMSE_XENT = "rmse_xent"
    SQUARED_LOSS = "squared_loss"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    COSINE_PROXIMITY = "cosine_proximity"


def score(labels: Array, loss: LossFunction | str, output: Array) -> Array:
    """Mean loss over the batch. ``output`` is the model's (post-activation)
    prediction, as in the reference (loss composed with softmax/sigmoid output
    activations, not logits — logit-space variants live in the model families
    where they matter for numerics)."""
    loss = LossFunction(loss)
    labels = labels.astype(jnp.float32)
    output = output.astype(jnp.float32)
    n = labels.shape[0]

    if loss in (LossFunction.MSE, LossFunction.SQUARED_LOSS):
        per = jnp.sum((labels - output) ** 2, axis=-1)
        if loss is LossFunction.MSE:
            per = per / labels.shape[-1]
        return jnp.mean(per)
    if loss is LossFunction.RMSE_XENT:
        return jnp.mean(jnp.sqrt(jnp.sum((labels - output) ** 2, axis=-1) + _EPS))
    if loss is LossFunction.XENT or loss is LossFunction.RECONSTRUCTION_CROSSENTROPY:
        p = jnp.clip(output, _EPS, 1.0 - _EPS)
        per = -jnp.sum(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p), axis=-1)
        return jnp.mean(per)
    if loss in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        p = jnp.clip(output, _EPS, 1.0)
        return jnp.mean(-jnp.sum(labels * jnp.log(p), axis=-1))
    if loss is LossFunction.EXPLL:
        # Poisson NLL: mean(output - labels*log(output))
        p = jnp.clip(output, _EPS, None)
        return jnp.mean(jnp.sum(p - labels * jnp.log(p), axis=-1))
    if loss is LossFunction.COSINE_PROXIMITY:
        num = jnp.sum(labels * output, axis=-1)
        den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(output, axis=-1) + _EPS
        return -jnp.mean(num / den)
    raise ValueError(f"unhandled loss {loss}")


def softmax_cross_entropy_with_logits(labels: Array, logits: Array) -> Array:
    """Numerically-stable MCXENT on logits — the TPU-native path the model
    families use (fuses into one XLA op chain; avoids log(softmax) blowup)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(labels.astype(jnp.float32) * logp, axis=-1))


def sigmoid_binary_cross_entropy_with_logits(labels: Array, logits: Array) -> Array:
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.mean(jnp.sum(per, axis=-1))
