"""Named activation registry with derivative dispatch.

Reference parity: ND4J op-factory string dispatch — the reference's layers
call ``Nd4j.getExecutioner().execAndReturn(Nd4j.getOpFactory()
.createTransform(conf.getActivationFunction(), x))`` (BaseLayer.java:199-208)
and fetch derivatives by appending a suffix (MultiLayerNetwork.java:956).

TPU-native design: activations are pure ``jnp`` functions; derivatives are
computed once via ``jax.grad`` of the scalar elementwise map (so any custom
registered activation automatically has a correct derivative), except where
a closed form is cheaper for XLA to fuse.  Everything here is jit-safe.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Array = jax.Array

_ACTIVATIONS: Dict[str, Callable[[Array], Array]] = {}
_DERIVATIVES: Dict[str, Callable[[Array], Array]] = {}


def register_activation(
    name: str,
    fn: Callable[[Array], Array],
    derivative: Callable[[Array], Array] | None = None,
) -> None:
    """Register a named activation. If ``derivative`` is None it is derived
    with ``jax.grad`` applied elementwise (correct for any elementwise fn)."""
    _ACTIVATIONS[name] = fn
    if derivative is None:
        # Elementwise derivative via grad of the scalar map. vmap-free:
        # sum-trick gives d/dx_i sum(f(x)) == f'(x_i) for elementwise f.
        derivative = jax.grad(lambda x: jnp.sum(fn(x)))
    _DERIVATIVES[name] = derivative


def get_activation(name: str) -> Callable[[Array], Array]:
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation '{name}'. Known: {sorted(_ACTIVATIONS)}"
        ) from None


def get_activation_derivative(name: str) -> Callable[[Array], Array]:
    """The ``<name>+'derivative'`` op of the reference (applied to pre- or
    post-activation values depending on the layer, matching nd4j semantics
    where derivative ops take the *activated* value for sigmoid/tanh)."""
    try:
        return _DERIVATIVES[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation derivative '{name}'. Known: {sorted(_DERIVATIVES)}"
        ) from None


def list_activations() -> list[str]:
    return sorted(_ACTIVATIONS)


def _softmax(x: Array) -> Array:
    return jax.nn.softmax(x, axis=-1)


def _softmax_derivative(x: Array) -> Array:
    # Diagonal of the softmax Jacobian, matching nd4j's SoftMaxDerivative
    # elementwise convention: s * (1 - s).
    s = jax.nn.softmax(x, axis=-1)
    return s * (1.0 - s)


# nd4j names its derivative ops to take the ACTIVATED value for the sigmoid
# family (e.g. "sigmoid" derivative = y*(1-y) applied to y). The reference
# layers pass pre-activation z in backprop paths; we register derivatives of
# pre-activation z (the mathematically standard convention) since our layers
# consistently use z.
register_activation("sigmoid", jax.nn.sigmoid,
                    lambda z: jax.nn.sigmoid(z) * (1.0 - jax.nn.sigmoid(z)))
register_activation("tanh", jnp.tanh, lambda z: 1.0 - jnp.tanh(z) ** 2)
register_activation("relu", jax.nn.relu,
                    lambda z: (z > 0).astype(z.dtype))
register_activation("leakyrelu", lambda z: jax.nn.leaky_relu(z, 0.01))
register_activation("softplus", jax.nn.softplus, jax.nn.sigmoid)
register_activation("linear", lambda z: z, jnp.ones_like)
register_activation("identity", lambda z: z, jnp.ones_like)
register_activation("exp", jnp.exp, jnp.exp)
register_activation("hardtanh", lambda z: jnp.clip(z, -1.0, 1.0),
                    lambda z: ((z > -1.0) & (z < 1.0)).astype(z.dtype))
register_activation("softmax", _softmax, _softmax_derivative)
register_activation("softsign", jax.nn.soft_sign)
register_activation("gelu", jax.nn.gelu)
register_activation("silu", jax.nn.silu)
register_activation("abs", jnp.abs, jnp.sign)
register_activation("round", jnp.round, jnp.zeros_like)
register_activation("sqrt", jnp.sqrt)
register_activation("maxout", jax.nn.relu)  # reference "maxout" without pieces
