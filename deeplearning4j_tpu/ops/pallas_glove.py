"""Fused GloVe chunk update as a Pallas TPU kernel (small-vocab path).

Reference parity: ``GloveWeightLookupTable.iterateSample`` (the
f(X) = (X/xMax)^0.75-weighted WLS update with per-row AdaGrad).  The XLA
path (``nlp/glove._glove_update``) batches it as gathers + einsums +
count-normalized AdaGrad scatter-adds; like word2vec, those row
gathers/scatters dominate chunk time on TPU.

Same redesign as ``ops/pallas_word2vec``: for vocabularies whose tables
fit in VMEM, rows move exclusively through one-hot matmuls on the MXU.
The bias terms fold into EXTENDED tables so the whole pair score is one
row-dot:

    wext[i]  = (w[i]  | b[i] | 1)          [V, D+2]
    wtext[j] = (wt[j] | 1 | bt[j])         [V, D+2]
    score(i, j) = wext[i] . wtext[j] = w[i].wt[j] + b[i] + bt[j]

Per side the kernel emits dense accumulators
``(sum g*p | sum (g*p)^2 | hit count)`` over the D+1 update columns
(weights + own bias; ``p`` = the partner's matching columns), from which
the XLA AdaGrad semantics reconstruct outside the kernel:
per-occurrence grads are ``g*p/k`` (k = row hits in the chunk), so
``gsq += sum_sq / k^2`` and ``step = alpha * (sum/k) / sqrt(gsq + eps)``
— exact ALGEBRA vs ``_glove_update.adagrad_scatter``, but the grad-square
lanes accumulate through bf16 matmuls, so numeric parity holds at bf16
precision only (tests/test_nlp_glove_pv.py asserts rtol 3e-2 in
interpreter mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:                                     # TPU-only compiler knobs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:                      # pragma: no cover
    pltpu = None

Array = jax.Array

VMEM_BUDGET_BYTES = 14 * 2 ** 20


def choose_block(vocab: int, dim: int, batch: int,
                 interpret: bool = False) -> int:
    """Largest grid block for which the VMEM model fits, else 0."""
    # 2 extended fp32 tables + bf16 casts + 2 fp32 [V, 2D+3] accumulators
    fixed = vocab * ((dim + 2) * (2 * 4 + 2 * 2) + 2 * (2 * dim + 3) * 4)
    for blk in (2048, 1024):
        if batch % blk:
            continue
        if fixed + 2 * vocab * blk <= VMEM_BUDGET_BYTES:
            return blk
    if interpret and batch <= 1024:
        return batch
    return 0


def _kernel(rows_ref, cols_ref, x_ref, mask_ref,
            wext_ref, wtext_ref, accw_ref, accwt_ref, loss_ref,
            *, x_max: float, power: float):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        accw_ref[...] = jnp.zeros_like(accw_ref)
        accwt_ref[...] = jnp.zeros_like(accwt_ref)
        loss_ref[...] = jnp.zeros_like(loss_ref)

    bf = jnp.bfloat16
    BLK = rows_ref.shape[0]
    V = wext_ref.shape[0]
    E = wext_ref.shape[1]                       # D + 2
    D = E - 2

    def one_hot_t(r):
        iota = lax.broadcasted_iota(jnp.int32, (V, BLK), 0)
        return (iota == r[None, :]).astype(bf)

    ohr = one_hot_t(rows_ref[:])
    ohc = one_hot_t(cols_ref[:])
    wi = lax.dot_general(ohr, wext_ref[...].astype(bf),
                         (((0,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)  # [BLK, E]
    wj = lax.dot_general(ohc, wtext_ref[...].astype(bf),
                         (((0,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    x = x_ref[:]
    mask = mask_ref[:]
    diff = jnp.sum(wi * wj, axis=1) - jnp.log(jnp.maximum(x, 1e-12))
    fx = jnp.minimum((x / x_max) ** power, 1.0)
    g = fx * diff * mask                                       # [BLK]
    loss_ref[0, 0] += 0.5 * jnp.sum(fx * diff * diff * mask)
    loss_ref[0, 1] += jnp.sum(mask)

    def accumulate(acc_ref, oht, partner_cols):
        grad = g[:, None] * partner_cols                       # [BLK, D+1]
        payload = jnp.concatenate(
            [grad, grad * grad, mask[:, None]], axis=1).astype(bf)
        acc_ref[...] += lax.dot_general(
            oht, payload, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [V, 2D+3]

    # row side updates (w | b): partner columns = (wt_j | 1)
    accumulate(accw_ref, ohr, wj[:, :D + 1])
    # col side updates (wt | bt): partner columns = (w_i | 1)
    accumulate(accwt_ref, ohc,
               jnp.concatenate([wi[:, :D], wi[:, D + 1:D + 2]], axis=1))


@functools.partial(
    jax.jit, static_argnames=("x_max", "power", "block", "interpret"))
def fused_glove_chunk(wext: Array, wtext: Array, rows: Array, cols: Array,
                      x: Array, mask: Array,
                      *, x_max: float, power: float, block: int = 1024,
                      interpret: bool = False):
    """One chunk's dense gradient accumulators via the VMEM kernel.

    Returns (accw, accwt, loss_sums): acc* [V, 2D+3] =
    (grad sums [D+1] | grad-square sums [D+1] | hit count);
    loss_sums [1, 2] = (weighted sq-err sum, mask sum).
    """
    B = rows.shape[0]
    BLK = min(block, B)
    NB = B // BLK
    assert NB * BLK == B, f"B={B} not a multiple of block={BLK}"
    V, E = wext.shape
    D = E - 2
    W = 2 * D + 3
    accw, accwt, loss = pl.pallas_call(
        functools.partial(_kernel, x_max=x_max, power=power),
        grid=(NB,),
        in_specs=[
            pl.BlockSpec((BLK,), lambda i: (i,)),          # rows
            pl.BlockSpec((BLK,), lambda i: (i,)),          # cols
            pl.BlockSpec((BLK,), lambda i: (i,)),          # x
            pl.BlockSpec((BLK,), lambda i: (i,)),          # mask
            pl.BlockSpec((V, E), lambda i: (0, 0)),
            pl.BlockSpec((V, E), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((V, W), lambda i: (0, 0)),
            pl.BlockSpec((V, W), lambda i: (0, 0)),
            pl.BlockSpec((1, 2), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((V, W), jnp.float32),
            jax.ShapeDtypeStruct((V, W), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=None if (interpret or pltpu is None) else
        pltpu.CompilerParams(dimension_semantics=("arbitrary",)),
    )(rows, cols, x.astype(jnp.float32), mask.astype(jnp.float32),
      wext, wtext)
    return accw, accwt, loss


def apply_chunk(table_b: Array, gsq_b: Array, acc: Array, alpha):
    """Apply one side's accumulators to (weights|bias) [V, D+1] and
    their AdaGrad state [V, D+1] — the same ALGEBRA as the scatter path
    (gsq += sum_sq / k^2 ; step = alpha * (sum/k) / sqrt(gsq + eps)),
    at bf16 precision: the accumulators arrive from bf16 kernel matmuls,
    so parity with the fp32 XLA path is approximate (rtol ~3e-2), not
    bitwise."""
    d1 = table_b.shape[1]
    cnt = jnp.maximum(acc[:, 2 * d1:2 * d1 + 1], 1.0)
    grad = acc[:, :d1] / cnt
    gsq_b = gsq_b + acc[:, d1:2 * d1] / (cnt * cnt)
    return table_b - alpha * grad / jnp.sqrt(gsq_b + 1e-8), gsq_b


_PROBE_CACHE: dict = {}


def probe_compile(block: int, vocab_size: int = 128, dim: int = 8,
                  timeout_s: float = 240.0) -> bool:
    """One real compile of the kernel at the given block size AND the
    caller's actual (vocab, dim) — ``auto`` selection on hardware goes
    through here so a Mosaic rejection degrades to the XLA path instead
    of crashing fit() (the same guard pattern as the flash-attention
    bench probe).  VMEM fit depends on the table shapes, so the probe
    runs at the production shapes; cached per the full key.

    The compile runs in a daemon thread joined with ``timeout_s``: a
    Mosaic compile that HANGS (round-3: glove died as a 900 s bench
    timeout) reads as a reject and the fit proceeds on XLA.  CAVEAT
    (ADVICE r4): a timeout verdict abandons the hung compile thread
    ALIVE — it may still hold jaxlib's compile lock, so the subsequent
    in-process XLA compile can block behind it until it finishes or the
    process exits; there is no way to cancel a compile from Python, and
    a killable-subprocess probe is impossible here because by fit()
    time this process already holds the (single-holder) TPU chip.
    Callers that can probe BEFORE backend init should do so in their
    own subprocess — bench.py's ``_glove_mosaic_probe`` is that path."""
    key = (block, vocab_size, dim)
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]

    result = {}

    def _try():
        try:
            V, D = vocab_size, dim
            wext = jnp.zeros((V, D + 2), jnp.float32)
            rows = jnp.zeros((block,), jnp.int32)
            x = jnp.ones((block,), jnp.float32)
            accw, _, _ = fused_glove_chunk(
                wext, wext, rows, rows, x, x, x_max=100.0, power=0.75,
                block=block, interpret=False)
            float(accw[0, 0])
            result["ok"] = True
        except Exception as e:            # Mosaic/compile-specific
            result["err"] = e
            result["ok"] = False

    import threading
    t = threading.Thread(target=_try, daemon=True)
    t.start()
    t.join(timeout_s)
    ok = bool(result.get("ok"))
    if not ok:
        import logging
        why = ("compile timed out after %.0fs — the hung Mosaic compile "
               "thread is abandoned alive and may delay this process's "
               "next compile" % timeout_s
               if t.is_alive() else result.get("err"))
        logging.getLogger(__name__).warning(
            "glove Pallas kernel unavailable on this backend (%s); "
            "using the XLA path", why)
    _PROBE_CACHE[key] = ok
    return ok
